"""Columnar record batches: the unit of data flowing between operators.

TPU-native analog of the reference's row representation
(flink-table-common BinaryRowData.java:62 — binary row over MemorySegments) and of
per-record StreamRecords (flink-streaming-java runtime/streamrecord/): instead of one
object per record, records travel in fixed-size struct-of-arrays micro-batches whose
numeric columns can be shipped to the device as one transfer and processed by one
compiled step. Python-object payloads are supported for host-side operators via
object-dtype columns.

Every batch carries per-record event timestamps (int64 millis, like the reference's
StreamRecord timestamp) so event-time operators don't need a side channel.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Mapping, Optional, Sequence

import numpy as np

__all__ = ["Schema", "FieldType", "RecordBatch", "MIN_TIMESTAMP",
           "MAX_TIMESTAMP", "scalar"]

MIN_TIMESTAMP = -(1 << 62)


def scalar(v):
    """numpy scalar -> python scalar (identity otherwise); the canonical
    row-value unwrapper for host-side operators."""
    return v.item() if isinstance(v, np.generic) else v
MAX_TIMESTAMP = (1 << 62) - 1

# Canonical dtype aliases accepted in schemas.
_DTYPES = {
    "int32": np.int32, "int64": np.int64, "float32": np.float32,
    "float64": np.float64, "bool": np.bool_, "uint32": np.uint32,
    "object": object, "str": object, "bytes": object,
}


@dataclass(frozen=True)
class FieldType:
    name: str
    dtype: Any  # numpy dtype or `object`

    @property
    def is_numeric(self) -> bool:
        return self.dtype is not object


class Schema:
    """Ordered, named, typed fields of a stream (reference RowType analog)."""

    def __init__(self, fields: Sequence[tuple[str, Any]]):
        self.fields: tuple[FieldType, ...] = tuple(
            FieldType(n, _DTYPES.get(d, d) if isinstance(d, str) else d)
            for n, d in fields)
        self._index = {f.name: i for i, f in enumerate(self.fields)}
        if len(self._index) != len(self.fields):
            raise ValueError("Duplicate field names in schema")

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    def field(self, name: str) -> FieldType:
        return self.fields[self._index[name]]

    def index_of(self, name: str) -> int:
        return self._index[name]

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def __len__(self) -> int:
        return len(self.fields)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Schema) and self.fields == other.fields

    def __repr__(self) -> str:
        return "Schema(" + ", ".join(f"{f.name}:{getattr(f.dtype, '__name__', f.dtype)}"
                                     for f in self.fields) + ")"

    def numeric_names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.fields if f.is_numeric)

    @staticmethod
    def of(**fields: Any) -> "Schema":
        return Schema(list(fields.items()))

    @staticmethod
    def infer(row: Any) -> "Schema":
        """Infer a schema from one sample element.

        Scalars become single-column ('value',) schemas; tuples become f0..fN
        (like the reference's TypeExtractor for tuples).
        """
        def dtype_of(v: Any) -> Any:
            if isinstance(v, (bool, np.bool_)):
                return np.bool_
            if isinstance(v, (int, np.integer)):
                return np.int64
            if isinstance(v, (float, np.floating)):
                return np.float64
            return object

        if isinstance(row, tuple):
            return Schema([(f"f{i}", dtype_of(v)) for i, v in enumerate(row)])
        if isinstance(row, dict):
            return Schema([(k, dtype_of(v)) for k, v in row.items()])
        return Schema([("value", dtype_of(row))])


class RecordBatch:
    """A micro-batch of records: struct-of-arrays + per-record timestamps.

    Columns are dense numpy arrays of equal length ``n``. There is no validity
    mask at this level — host operators slice/compact eagerly; the device path
    pads to a static shape and carries its own mask (see ops/device_batch.py).
    """

    __slots__ = ("schema", "columns", "timestamps", "n")

    def __init__(self, schema: Schema, columns: Mapping[str, np.ndarray],
                 timestamps: Optional[np.ndarray] = None):
        self.schema = schema
        self.columns: dict[str, np.ndarray] = {}
        n = None
        for f in schema.fields:
            col = np.asarray(columns[f.name],
                             dtype=f.dtype if f.is_numeric else object)
            if n is None:
                n = len(col)
            elif len(col) != n:
                raise ValueError(f"Column {f.name} length {len(col)} != {n}")
            self.columns[f.name] = col
        self.n = n or 0
        if timestamps is None:
            timestamps = np.full(self.n, MIN_TIMESTAMP, dtype=np.int64)
        self.timestamps = np.asarray(timestamps, dtype=np.int64)
        if len(self.timestamps) != self.n:
            raise ValueError("timestamps length mismatch")

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_rows(cls, schema: Schema, rows: Sequence[Any],
                  timestamps: Optional[Sequence[int]] = None) -> "RecordBatch":
        """Build from Python rows (scalars / tuples / dicts per the schema)."""
        n = len(rows)
        cols: dict[str, list] = {f.name: [None] * n for f in schema.fields}
        single = len(schema) == 1
        for i, row in enumerate(rows):
            if isinstance(row, dict):
                for f in schema.fields:
                    cols[f.name][i] = row[f.name]
            elif isinstance(row, tuple) and not single:
                for f, v in zip(schema.fields, row):
                    cols[f.name][i] = v
            else:
                cols[schema.fields[0].name][i] = row
        arrs = {
            f.name: np.array(cols[f.name],
                             dtype=f.dtype if f.is_numeric else object)
            for f in schema.fields
        }
        ts = None if timestamps is None else np.asarray(timestamps, dtype=np.int64)
        return cls(schema, arrs, ts)

    @classmethod
    def from_rows_infer(cls, schema: Optional[Schema], rows: Sequence[Any],
                        timestamps: Optional[Sequence[int]] = None
                        ) -> tuple["RecordBatch", Schema]:
        """from_rows with inference + per-column promotion: user functions may
        emit heterogeneous rows, so each column that stops fitting its
        inferred dtype is promoted along int64 -> float64 -> object (never
        silently truncated); the promoted schema is returned for reuse so
        later batches stay consistent. Only the offending column widens —
        numeric siblings keep their dtype (and their device path)."""
        if not rows:
            if schema is None:
                raise ValueError(
                    "from_rows_infer needs a schema to build an empty batch")
            return cls.empty(schema), schema
        if schema is None:
            schema = Schema.infer(rows[0])
        # gather per-column python lists (same row-shape handling as from_rows)
        n = len(rows)
        single = len(schema) == 1
        cols: dict[str, list] = {f.name: [None] * n for f in schema.fields}
        for i, row in enumerate(rows):
            if isinstance(row, dict):
                for f in schema.fields:
                    cols[f.name][i] = row[f.name]
            elif isinstance(row, tuple) and not single:
                for f, v in zip(schema.fields, row):
                    cols[f.name][i] = v
            else:
                cols[schema.fields[0].name][i] = row

        out_fields: list[tuple[str, Any]] = []
        arrs: dict[str, np.ndarray] = {}
        for f in schema.fields:
            vals = cols[f.name]
            if not f.is_numeric:
                arrs[f.name] = np.array(vals, dtype=object)
                out_fields.append((f.name, object))
                continue
            try:
                natural = np.asarray(vals)
            except (ValueError, TypeError):
                natural = np.array(vals, dtype=object)
            if natural.dtype == object or natural.dtype.kind in "USV":
                arrs[f.name] = np.array(vals, dtype=object)
                out_fields.append((f.name, object))
            elif np.can_cast(natural.dtype, f.dtype, "safe"):
                arrs[f.name] = natural.astype(f.dtype)
                out_fields.append((f.name, f.dtype))
            else:
                promoted = np.promote_types(natural.dtype, np.dtype(f.dtype))
                arrs[f.name] = natural.astype(promoted)
                out_fields.append((f.name, promoted.type))
        out_schema = Schema(out_fields)
        ts = None if timestamps is None else np.asarray(timestamps,
                                                       dtype=np.int64)
        return cls(out_schema, arrs, ts), out_schema

    @classmethod
    def empty(cls, schema: Schema) -> "RecordBatch":
        cols = {f.name: np.empty(0, dtype=f.dtype if f.is_numeric else object)
                for f in schema.fields}
        return cls(schema, cols, np.empty(0, dtype=np.int64))

    @classmethod
    def concat(cls, batches: Sequence["RecordBatch"]) -> "RecordBatch":
        if not batches:
            raise ValueError("concat of zero batches")
        if len(batches) == 1:
            return batches[0]
        schema = batches[0].schema
        cols = {name: np.concatenate([b.columns[name] for b in batches])
                for name in schema.names}
        ts = np.concatenate([b.timestamps for b in batches])
        return cls(schema, cols, ts)

    # -- accessors ---------------------------------------------------------
    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def row(self, i: int) -> Any:
        """Materialize row i as a scalar (1-col schema) or tuple."""
        if len(self.schema) == 1:
            v = self.columns[self.schema.fields[0].name][i]
            return v.item() if isinstance(v, np.generic) else v
        return tuple(
            v.item() if isinstance(v := self.columns[f.name][i], np.generic) else v
            for f in self.schema.fields)

    def iter_rows(self) -> Iterator[Any]:
        for i in range(self.n):
            yield self.row(i)

    def to_pylist(self) -> list:
        return list(self.iter_rows())

    def __len__(self) -> int:
        return self.n

    def __repr__(self) -> str:
        return f"RecordBatch(n={self.n}, schema={self.schema!r})"

    # -- transforms (all return new batches; arrays are shared not copied) --
    def with_columns(self, schema: Schema,
                     columns: Mapping[str, np.ndarray]) -> "RecordBatch":
        return RecordBatch(schema, columns, self.timestamps)

    def with_timestamps(self, ts: np.ndarray) -> "RecordBatch":
        return RecordBatch(self.schema, self.columns, ts)

    def take(self, indices: np.ndarray) -> "RecordBatch":
        cols = {n: c[indices] for n, c in self.columns.items()}
        return RecordBatch(self.schema, cols, self.timestamps[indices])

    def filter(self, mask: np.ndarray) -> "RecordBatch":
        return self.take(np.flatnonzero(mask))

    def slice(self, start: int, stop: int) -> "RecordBatch":
        cols = {n: c[start:stop] for n, c in self.columns.items()}
        return RecordBatch(self.schema, cols, self.timestamps[start:stop])

    def select(self, names: Sequence[str]) -> "RecordBatch":
        schema = Schema([(n, self.schema.field(n).dtype) for n in names])
        return RecordBatch(schema, {n: self.columns[n] for n in names},
                           self.timestamps)

    def split_by(self, part: np.ndarray, num_parts: int) -> list["RecordBatch"]:
        """Partition rows by an int partition-id array (stable within parts)."""
        order = np.argsort(part, kind="stable")
        sorted_part = part[order]
        bounds = np.searchsorted(sorted_part, np.arange(num_parts + 1))
        return [self.take(order[bounds[p]:bounds[p + 1]])
                for p in range(num_parts)]
