"""Key groups: the unit of keyed-state sharding and rescaling.

Semantics follow the reference's KeyGroupRangeAssignment
(flink-runtime/src/main/java/org/apache/flink/runtime/state/KeyGroupRangeAssignment.java:
assignToKeyGroup:63, computeKeyGroupForKeyHash:75, computeOperatorIndexForKeyGroup:124)
and KeyGroupRange.java:31 exactly, so checkpoints re-shard across parallelism changes
with the same contiguous-range math. The implementation is vectorized (numpy on host,
jnp on device) instead of per-record virtual calls.

A key is assigned ``key_group = murmur(hash(key)) % max_parallelism``; an operator
subtask ``i`` of ``p`` owns the contiguous range
``[ceil(i*maxp/p), floor(((i+1)*maxp - 1)/p)]``.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Any, Iterator, Sequence

import numpy as np

__all__ = [
    "DEFAULT_MAX_PARALLELISM",
    "KeyGroupRange",
    "stable_hash",
    "murmur_mix",
    "key_group_for_hash",
    "assign_to_key_group",
    "operator_index_for_key_group",
    "key_group_range_for_operator",
    "compute_default_max_parallelism",
    "hash_batch",
    "key_groups_for_hash_batch",
]

DEFAULT_MAX_PARALLELISM = 128
UPPER_BOUND_MAX_PARALLELISM = 1 << 15

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def murmur_mix(code: "np.ndarray | int") -> "np.ndarray | int":
    """Murmur3_32 single-int round + finalizer, matching the reference's
    MathUtils.murmurHash semantics (spread + take absolute value).

    Vectorized: accepts scalars or uint32/int arrays.
    """
    scalar = np.isscalar(code) or (isinstance(code, np.ndarray) and code.ndim == 0)
    k = np.asarray(code, dtype=np.uint32)
    with np.errstate(over="ignore"):
        k = k * _C1
        k = _rotl32(k, 15)
        k = k * _C2
        h = _rotl32(k, 13)
        h = h * np.uint32(5) + np.uint32(0xE6546B64)
        h = h ^ np.uint32(4)  # len(bytes) == 4
        h = h ^ (h >> np.uint32(16))
        h = h * np.uint32(0x85EBCA6B)
        h = h ^ (h >> np.uint32(13))
        h = h * np.uint32(0xC2B2AE35)
        h = h ^ (h >> np.uint32(16))
    out = h.astype(np.int32)
    # abs() with MIN_VALUE -> 0, as the reference does
    out = np.where(out == np.int32(-2147483648), np.int32(0), np.abs(out))
    return int(out) if scalar else out


def stable_hash(key: Any) -> int:
    """A deterministic, process-stable 32-bit hash for a Python key.

    Replaces Java's Object.hashCode(): ints hash to themselves (mod 2^32, like
    Integer/Long.hashCode folding), strings/bytes via crc32 (deterministic,
    unlike Python's salted hash()), tuples by combining element hashes.
    """
    if isinstance(key, (bool, np.bool_)):
        return 1231 if key else 1237
    if isinstance(key, (int, np.integer)):
        # Fold the two's-complement 64-bit representation (Long.hashCode-style
        # v ^ (v >>> 32)); small non-negative ints hash to themselves.
        u = int(key) & 0xFFFFFFFFFFFFFFFF
        return (u ^ (u >> 32)) & 0xFFFFFFFF
    if isinstance(key, str):
        return zlib.crc32(key.encode("utf-8"))
    if isinstance(key, bytes):
        return zlib.crc32(key)
    if isinstance(key, (float, np.floating)):
        return zlib.crc32(np.float64(key).tobytes())
    if isinstance(key, tuple):
        h = 1
        for item in key:
            h = (31 * h + stable_hash(item)) & 0xFFFFFFFF
        return h
    # Fallback: repr bytes (stable for simple value objects)
    return zlib.crc32(repr(key).encode("utf-8"))


def _murmur_mix_scalar(code: int) -> int:
    """Pure-Python twin of murmur_mix for SCALAR calls — the numpy path
    costs ~70us per scalar (ufunc dispatch + errstate context) and sits
    on the per-key state-access path of the heap backend; this is ~100x
    faster and bit-exact (tested against the vectorized path)."""
    M = 0xFFFFFFFF
    k = (code * 0xCC9E2D51) & M
    k = ((k << 15) | (k >> 17)) & M
    k = (k * 0x1B873593) & M
    h = ((k << 13) | (k >> 19)) & M
    h = (h * 5 + 0xE6546B64) & M
    h ^= 4
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & M
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & M
    h ^= h >> 16
    if h & 0x80000000:                      # int32 abs, MIN_VALUE -> 0
        h = (~h + 1) & M
        if h == 0x80000000:
            h = 0
    return h


def key_group_for_hash(key_hash: int, max_parallelism: int) -> int:
    """reference computeKeyGroupForKeyHash:75 — murmur(hash) % maxParallelism."""
    return _murmur_mix_scalar(key_hash & 0xFFFFFFFF) % max_parallelism


import functools as _functools


@_functools.lru_cache(maxsize=1 << 16)
def _assign_cached(typed_key, max_parallelism: int) -> int:
    return key_group_for_hash(stable_hash(typed_key[1]), max_parallelism)


def assign_to_key_group(key: Any, max_parallelism: int) -> int:
    """reference assignToKeyGroup:63. Hashable keys memoize (the heap
    backend and timer service call this once per state access). The cache
    key includes type(key): True/1/1.0 are ==-equal and hash-equal in
    Python but stable_hash-DISTINCT, and a plain lru_cache would return
    the first-seen type's group for all of them."""
    try:
        return _assign_cached((type(key), key), max_parallelism)
    except TypeError:                        # unhashable key
        return key_group_for_hash(stable_hash(key), max_parallelism)


def operator_index_for_key_group(max_parallelism: int, parallelism: int,
                                 key_group: int) -> int:
    """reference computeOperatorIndexForKeyGroup:124 — kg * p // maxp."""
    return key_group * parallelism // max_parallelism


def key_group_range_for_operator(max_parallelism: int, parallelism: int,
                                 operator_index: int) -> "KeyGroupRange":
    """reference KeyGroupRangeAssignment.computeKeyGroupRangeForOperatorIndex."""
    start = (operator_index * max_parallelism + parallelism - 1) // parallelism
    end = ((operator_index + 1) * max_parallelism - 1) // parallelism
    return KeyGroupRange(start, end)


def compute_default_max_parallelism(parallelism: int) -> int:
    """reference computeDefaultMaxParallelism: next pow2 of 1.5x, clamped."""
    v = 1
    while v < round(parallelism * 1.5):
        v <<= 1
    return min(max(v, DEFAULT_MAX_PARALLELISM), UPPER_BOUND_MAX_PARALLELISM)


@dataclass(frozen=True, order=True)
class KeyGroupRange:
    """Inclusive contiguous range of key groups (reference KeyGroupRange.java:31)."""

    start: int
    end: int  # inclusive

    def __post_init__(self):
        if self.end < self.start and not (self.start == 0 and self.end == -1):
            raise ValueError(f"Invalid key group range [{self.start}, {self.end}]")

    @property
    def size(self) -> int:
        return self.end - self.start + 1

    def __contains__(self, key_group: int) -> bool:
        return self.start <= key_group <= self.end

    def __iter__(self) -> Iterator[int]:
        return iter(range(self.start, self.end + 1))

    def intersect(self, other: "KeyGroupRange") -> "KeyGroupRange":
        s, e = max(self.start, other.start), min(self.end, other.end)
        return KeyGroupRange(s, e) if s <= e else KeyGroupRange.EMPTY

    def is_empty(self) -> bool:
        return self.size <= 0


KeyGroupRange.EMPTY = KeyGroupRange(0, -1)


# ---------------------------------------------------------------------------
# Vectorized batch paths (host hot loop — numpy; device versions in ops/)
# ---------------------------------------------------------------------------

def hash_batch(keys: Sequence[Any]) -> np.ndarray:
    """Hash a batch of keys to uint32. Fast paths for integer/array inputs."""
    if isinstance(keys, np.ndarray) and np.issubdtype(keys.dtype, np.integer):
        u = keys.astype(np.int64).view(np.uint64)
        return ((u ^ (u >> np.uint64(32))) & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return np.fromiter((stable_hash(k) for k in keys), dtype=np.uint32,
                       count=len(keys))


def key_groups_for_hash_batch(hashes: np.ndarray, max_parallelism: int) -> np.ndarray:
    """Vectorized key_group_for_hash over a uint32 hash array -> int32 groups.
    Routes through the native library when built (flink_tpu/native,
    bit-exact parity with the numpy path is tested)."""
    try:
        from .. import native
        if native.NATIVE_AVAILABLE and len(hashes) >= 512:
            return native.key_group_batch(hashes, max_parallelism)
    except ImportError:
        pass
    return (murmur_mix(hashes.astype(np.uint32)) % np.int32(max_parallelism)).astype(
        np.int32)
