"""Plugin SPI: discover and load extension modules from plugin directories.

Reference: flink-core core/plugin/ (PluginManager.java:27,
DirectoryBasedPluginFinder, PluginLoader with an isolated classloader per
plugin). Python has no classloader isolation; the closest honest analog is
loading each plugin file as its OWN uniquely-named module (no sys.modules
collisions between plugins, no package imports leaking between them) and
handing it a registry of extension points to populate:

    # plugins/my_fs.py
    def register(registry):
        registry.filesystem("s3", MyS3FileSystem)
        registry.state_backend("rocks2", MyBackend)
        registry.connector("my-source", my_source_factory)

Extension points map onto the framework's existing seams: path-scheme
filesystems (core/fs.py), state backends (state/backend.py register_backend
— the StateBackendLoader.java:113 seam), SQL connectors (sql/ddl.py), and
metric reporters.
"""

from __future__ import annotations

import importlib.util
import os
import uuid
from typing import Any, Callable

__all__ = ["PluginRegistry", "PluginManager"]


class PluginRegistry:
    """Extension points a plugin's register() hook can populate."""

    def __init__(self):
        self.loaded: list[str] = []           # plugin names, for inspection

    @property
    def connectors(self) -> dict:
        """Read-through view of the single source of truth (the DDL
        layer's process-global connector table)."""
        from ..sql.ddl import _PLUGIN_CONNECTORS
        return dict(_PLUGIN_CONNECTORS)

    @property
    def metric_reporters(self) -> dict:
        from ..metrics.reporters import _REPORTER_FACTORIES
        return dict(_REPORTER_FACTORIES)

    def filesystem(self, scheme: str, factory: Callable) -> None:
        from .fs import register_filesystem
        register_filesystem(scheme, factory)

    def state_backend(self, name: str, cls: Any) -> None:
        from ..state.backend import register_backend
        register_backend(name, cls)

    def connector(self, name: str, source: Callable = None,
                  sink: Callable = None) -> None:
        """SQL connector: ``source(env, catalog_table) -> DataStream``,
        ``sink(catalog_table) -> Sink|SinkFunction``. The DDL layer
        consults plugin connectors after the built-ins."""
        from ..sql.ddl import register_connector
        register_connector(name, source=source, sink=sink)

    def metric_reporter(self, name: str, factory: Callable) -> None:
        """Reporter resolvable by name from metrics.reporters config."""
        from ..metrics.reporters import register_reporter
        register_reporter(name, factory)


class PluginManager:
    """Loads every ``*.py`` in the given directories as an isolated module
    and invokes its ``register(registry)`` hook."""

    def __init__(self, plugin_dirs: list[str]):
        self.plugin_dirs = list(plugin_dirs)
        self.registry = PluginRegistry()
        self.errors: list[tuple[str, str]] = []   # (path, error)

    def load_all(self) -> PluginRegistry:
        for d in self.plugin_dirs:
            if not os.path.isdir(d):
                continue
            for name in sorted(os.listdir(d)):
                if not name.endswith(".py") or name.startswith("_"):
                    continue
                self._load_one(os.path.join(d, name))
        return self.registry

    def _load_one(self, path: str) -> None:
        # unique module name per load: two plugins named util.py in
        # different dirs never collide in sys.modules (the classloader-
        # isolation analog)
        mod_name = f"flink_tpu_plugin_{uuid.uuid4().hex[:8]}"
        try:
            spec = importlib.util.spec_from_file_location(mod_name, path)
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)
            hook = getattr(module, "register", None)
            if hook is None:
                self.errors.append((path, "no register(registry) hook"))
                return
            hook(self.registry)
            self.registry.loaded.append(os.path.basename(path)[:-3])
        except Exception as e:  # noqa: BLE001 - a bad plugin must not kill
            self.errors.append((path, f"{type(e).__name__}: {e}"))
