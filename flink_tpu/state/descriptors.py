"""State descriptors: named, typed handles to keyed state.

Analog of flink-core's state descriptor family
(api/common/state/: ValueStateDescriptor, ListStateDescriptor,
ReducingStateDescriptor, AggregatingStateDescriptor, MapStateDescriptor).
A descriptor identifies a state in the backend by name and prescribes how
values fold (for reducing/aggregating state the backend may lower the fold to
a device segment-reduce — see state/tpu_backend.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..core.functions import AggregateFunction, ReduceFunction

__all__ = [
    "StateDescriptor", "ValueStateDescriptor", "ListStateDescriptor",
    "ReducingStateDescriptor", "AggregatingStateDescriptor",
    "MapStateDescriptor", "StateTtlConfig",
]


@dataclass(frozen=True)
class StateTtlConfig:
    """Relaxed TTL (reference StateTtlConfig): entries expire ttl seconds
    after last update; cleanup happens lazily on access and on snapshot."""

    ttl: float
    update_on_read: bool = False


@dataclass(frozen=True)
class StateDescriptor:
    name: str
    kind: str  # value | list | reducing | aggregating | map
    default: Any = None
    ttl: Optional[StateTtlConfig] = None
    # queryable-state external name (reference setQueryable); None = private
    queryable_name: Optional[str] = None
    # value serializer (None = registry default); its versioned snapshot
    # is written with checkpoints and resolved on restore (migration)
    serializer: Any = None

    def __post_init__(self):
        if self.kind not in ("value", "list", "reducing", "aggregating", "map"):
            raise ValueError(f"Unknown state kind {self.kind!r}")

    def queryable(self, external_name: str) -> "StateDescriptor":
        """Expose this state for external queries (reference
        StateDescriptor.setQueryable). copy+setattr rather than
        dataclasses.replace: the reducing/aggregating subclasses have
        custom __init__ signatures."""
        import copy
        c = copy.copy(self)
        object.__setattr__(c, "queryable_name", external_name)
        return c


def ValueStateDescriptor(name: str, default: Any = None,
                         ttl: Optional[StateTtlConfig] = None,
                         serializer: Any = None) -> StateDescriptor:
    return StateDescriptor(name, "value", default, ttl,
                           serializer=serializer)


def ListStateDescriptor(name: str,
                        ttl: Optional[StateTtlConfig] = None) -> StateDescriptor:
    return StateDescriptor(name, "list", None, ttl)


def MapStateDescriptor(name: str,
                       ttl: Optional[StateTtlConfig] = None) -> StateDescriptor:
    return StateDescriptor(name, "map", None, ttl)


@dataclass(frozen=True)
class ReducingStateDescriptor(StateDescriptor):
    reduce_function: ReduceFunction = None  # type: ignore[assignment]

    def __init__(self, name: str, reduce_function: ReduceFunction,
                 ttl: Optional[StateTtlConfig] = None):
        object.__setattr__(self, "name", name)
        object.__setattr__(self, "kind", "reducing")
        object.__setattr__(self, "default", None)
        object.__setattr__(self, "ttl", ttl)
        object.__setattr__(self, "queryable_name", None)
        object.__setattr__(self, "serializer", None)
        object.__setattr__(self, "reduce_function", reduce_function)


@dataclass(frozen=True)
class AggregatingStateDescriptor(StateDescriptor):
    aggregate_function: AggregateFunction = None  # type: ignore[assignment]

    def __init__(self, name: str, aggregate_function: AggregateFunction,
                 ttl: Optional[StateTtlConfig] = None):
        object.__setattr__(self, "name", name)
        object.__setattr__(self, "kind", "aggregating")
        object.__setattr__(self, "default", None)
        object.__setattr__(self, "ttl", ttl)
        object.__setattr__(self, "queryable_name", None)
        object.__setattr__(self, "serializer", None)
        object.__setattr__(self, "aggregate_function", aggregate_function)
