"""State backend SPI.

Analog of the reference's StateBackend stack (flink-runtime state/:
StateBackend.java:80, CheckpointableKeyedStateBackend.java:37,
AbstractKeyedStateBackend, StateBackendLoader.java:50): a keyed backend owns
all keyed state for one operator subtask's key-group range; an operator state
backend owns non-keyed (e.g. source offset) state. Backends are chosen by name
through a registry — the seam where the device-resident TPU backend plugs in
alongside the host hashmap backend, mirroring how RocksDB is loaded by factory
class in the reference.

Keyed state is addressed by (key, namespace): the namespace is the window in
windowed aggregations (reference's InternalKvState namespace concept).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable, Optional

from ..core.keygroups import KeyGroupRange, assign_to_key_group
from .descriptors import StateDescriptor

__all__ = [
    "State", "ValueState", "ListState", "ReducingState", "AggregatingState",
    "MapState", "KeyedStateBackend", "OperatorStateBackend",
    "StateBackendFactory", "register_backend", "create_backend",
    "VOID_NAMESPACE",
]

VOID_NAMESPACE = None


class State:
    def clear(self) -> None:
        raise NotImplementedError


class ValueState(State):
    def value(self) -> Any:
        raise NotImplementedError

    def update(self, value: Any) -> None:
        raise NotImplementedError


class ListState(State):
    def get(self) -> Iterable[Any]:
        raise NotImplementedError

    def add(self, value: Any) -> None:
        raise NotImplementedError

    def update(self, values: list) -> None:
        raise NotImplementedError


class ReducingState(State):
    def get(self) -> Any:
        raise NotImplementedError

    def add(self, value: Any) -> None:
        raise NotImplementedError


class AggregatingState(State):
    def get(self) -> Any:
        raise NotImplementedError

    def add(self, value: Any) -> None:
        raise NotImplementedError


class MapState(State):
    def get(self, key: Any) -> Any:
        raise NotImplementedError

    def put(self, key: Any, value: Any) -> None:
        raise NotImplementedError

    def remove(self, key: Any) -> None:
        raise NotImplementedError

    def contains(self, key: Any) -> bool:
        raise NotImplementedError

    def items(self) -> Iterable[tuple]:
        raise NotImplementedError


class KeyedStateBackend:
    """Owns keyed state for one key-group range (reference
    CheckpointableKeyedStateBackend). Subtask-confined: no locking, matching
    the mailbox-thread discipline."""

    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int):
        self.key_group_range = key_group_range
        self.max_parallelism = max_parallelism
        self._current_key: Any = None
        self._current_key_group: int = -1
        self._current_namespace: Any = VOID_NAMESPACE

    # -- current-key context (row path) -----------------------------------
    def set_current_key(self, key: Any, key_group: Optional[int] = None) -> None:
        self._current_key = key
        self._current_key_group = (assign_to_key_group(key, self.max_parallelism)
                                   if key_group is None else key_group)

    def set_current_namespace(self, namespace: Any) -> None:
        self._current_namespace = namespace

    @property
    def current_key(self) -> Any:
        return self._current_key

    # queryable-state registry, injected by the runtime (OperatorContext)
    kv_registry: Any = None

    # -- state handles -----------------------------------------------------
    def get_partitioned_state(self, descriptor: StateDescriptor) -> State:
        raise NotImplementedError

    def read_raw(self, state_name: str, key: Any,
                 namespace: Any = VOID_NAMESPACE) -> Any:
        """Point read for queryable state (reference InternalKvState
        .getSerializedValue); None when absent."""
        raise NotImplementedError

    # -- introspection / iteration (savepoint reader, window cleanup) ------
    def keys(self, state_name: str, namespace: Any = VOID_NAMESPACE) -> Iterable[Any]:
        raise NotImplementedError

    def namespaces(self, state_name: str) -> Iterable[Any]:
        raise NotImplementedError

    # -- checkpointing -----------------------------------------------------
    def snapshot(self, checkpoint_id: int) -> dict:
        """Serializable snapshot keyed by key group so restore can re-shard
        (reference snapshot strategies + StateAssignmentOperation)."""
        raise NotImplementedError

    def restore(self, snapshots: Iterable[dict]) -> None:
        """Restore from one or more snapshots, keeping only the key groups in
        this backend's range (rescaling restore)."""
        raise NotImplementedError

    def notify_checkpoint_complete(self, checkpoint_id: int,
                                    is_savepoint: bool = False) -> None:
        """Coordinator confirmed the checkpoint completed (operators
        forward this). Backends with deferred artifact cleanup (changelog
        generations) prune here — never on snapshot attempts, which may
        belong to checkpoints that later fail."""

    def notify_checkpoint_aborted(self, checkpoint_id: int) -> None:
        pass

    def dispose(self) -> None:
        pass


class OperatorStateBackend:
    """Non-keyed per-subtask state with redistribution on rescale
    (reference OperatorStateBackend: split/union list state)."""

    def __init__(self):
        self._lists: dict[str, list] = {}
        self._modes: dict[str, str] = {}  # split | union

    def get_list_state(self, name: str, mode: str = "split") -> list:
        self._modes.setdefault(name, mode)
        return self._lists.setdefault(name, [])

    def update_list_state(self, name: str, values: list) -> None:
        self._lists[name] = list(values)

    def snapshot(self, checkpoint_id: int) -> dict:
        return {"lists": {k: list(v) for k, v in self._lists.items()},
                "modes": dict(self._modes)}

    @staticmethod
    def redistribute(snapshots: list[dict], new_parallelism: int) -> list[dict]:
        """split: round-robin elements across new subtasks;
        union: every subtask gets everything;
        broadcast maps (CoBroadcastWithKeyedOperator): every old subtask
        snapshotted an IDENTICAL replica, so each new subtask receives
        the first copy (reference: broadcast state re-shipped whole)."""
        names = set()
        modes: dict[str, str] = {}
        for s in snapshots:
            names.update(s.get("lists", {}))
            modes.update(s.get("modes", {}))
        out = [{"lists": {n: [] for n in names}, "modes": modes}
               for _ in range(new_parallelism)]
        for name in names:
            all_items = [x for s in snapshots for x in s.get("lists", {}).get(name, [])]
            if modes.get(name) == "union":
                for o in out:
                    o["lists"][name] = list(all_items)
            else:
                for i, item in enumerate(all_items):
                    out[i % new_parallelism]["lists"][name].append(item)
        bmap = next((s["broadcast"] for s in snapshots
                     if s.get("broadcast")), None)
        if bmap is not None:
            for o in out:
                o["broadcast"] = {n: dict(m) for n, m in bmap.items()}
        return out

    def restore(self, snapshot: dict) -> None:
        self._lists = {k: list(v) for k, v in snapshot.get("lists", {}).items()}
        self._modes = dict(snapshot.get("modes", {}))


# ---------------------------------------------------------------------------
# Backend registry (reference StateBackendLoader.loadStateBackendFromConfig)
# ---------------------------------------------------------------------------

StateBackendFactory = Callable[..., KeyedStateBackend]
_BACKENDS: dict[str, StateBackendFactory] = {}


def register_backend(name: str, factory: StateBackendFactory) -> None:
    _BACKENDS[name] = factory


def backend_supports_general_state(name: str) -> bool:
    """Whether the named backend holds arbitrary namespaced list/
    aggregating state (PARTIAL backends like the tpu value plane declare
    SUPPORTS_GENERAL_STATE = False; operators needing general shapes fall
    back to hashmap). Unknown/plugin names are assumed capable."""
    if name not in _BACKENDS and name in _LAZY_BACKENDS:
        import importlib
        importlib.import_module(_LAZY_BACKENDS[name])
    cls = _BACKENDS.get(name)
    return getattr(cls, "SUPPORTS_GENERAL_STATE", True) if cls else True


# built-in backends whose modules load on first use (the reference's
# StateBackendLoader factory-class lookup, StateBackendLoader.java:113 —
# the RocksDB backend is found by class name the same way)
_LAZY_BACKENDS = {"tpu": "flink_tpu.state.tpu_backend"}


def create_backend(name: str, key_group_range: KeyGroupRange,
                   max_parallelism: int, **kwargs) -> KeyedStateBackend:
    if name not in _BACKENDS and name in _LAZY_BACKENDS:
        import importlib

        importlib.import_module(_LAZY_BACKENDS[name])  # registers itself
    if name not in _BACKENDS:
        if ":" in name:  # fully-qualified "module:attr" factory, plugin-style
            mod, attr = name.split(":", 1)
            import importlib
            factory = getattr(importlib.import_module(mod), attr)
            return factory(key_group_range, max_parallelism, **kwargs)
        raise ValueError(
            f"Unknown state backend {name!r}; known: {sorted(_BACKENDS)}")
    return _BACKENDS[name](key_group_range, max_parallelism, **kwargs)
