"""TpuKeyedStateBackend: device-resident keyed state.

The framework's answer to the reference's RocksDB backend
(flink-state-backends RocksDBKeyedStateBackend.java:114,
EmbeddedRocksDBStateBackend.java:100): instead of an LSM tree behind JNI,
keyed state for one subtask's key-group range lives in HBM as dense arrays
indexed by a device hash table (ops/hash_table.py). Registered under name
"tpu" in the backend registry (the StateBackendLoader seam).

Two access planes:
* **array states** — the hot path: named [capacity] or [ring, capacity]
  accumulator arrays updated by whole-batch scatter folds; used by the device
  window/aggregate operators. Rehash (growth) remaps every array on device.
* **row states** — API-compatibility plane (ValueState etc.) with host-side
  gather/scatter per access; correct but slow, for small/irregular state.

Snapshots materialize (keys, key_groups, arrays) to host numpy, partitioned
by key group for rescaling restore — the device analog of key-group-ordered
snapshot streams.

Device keys must be int64 (Nexmark-style ids). Non-integer keys belong on
the host backend — the graph planner routes accordingly.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.keygroups import KeyGroupRange, hash_batch, \
    key_groups_for_hash_batch
from ..ops.hash_table import (
    EMPTY_KEY, lookup, lookup_or_insert, make_table,
)
from ..ops.segment_ops import AGG_INITS, make_accumulator, scatter_fold
from .backend import KeyedStateBackend, State, ValueState, register_backend
from .descriptors import StateDescriptor

__all__ = ["TpuKeyedStateBackend"]


def _sanitize_keys(keys: np.ndarray) -> np.ndarray:
    """Remap the EMPTY sentinel (int64 max) to int64 max - 1."""
    return np.where(keys == np.int64(EMPTY_KEY), np.int64(EMPTY_KEY) - 1,
                    keys.astype(np.int64))


class _ArrayState:
    __slots__ = ("name", "kind", "dtype", "ring", "array")

    def __init__(self, name: str, kind: str, dtype, ring: Optional[int],
                 capacity: int):
        self.name = name
        self.kind = kind
        self.dtype = dtype
        self.ring = ring
        shape = (ring, capacity) if ring else (capacity,)
        self.array = make_accumulator(kind, shape, dtype)


class TpuKeyedStateBackend(KeyedStateBackend):
    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int,
                 capacity: int = 1 << 16, config=None, **_kw):
        super().__init__(key_group_range, max_parallelism)
        cap = 1
        while cap < capacity:
            cap <<= 1
        self.capacity = cap
        self.table = make_table(cap)
        self._array_states: dict[str, _ArrayState] = {}
        self._row_states: dict[str, State] = {}
        self._num_keys = 0  # host-tracked occupancy (exact: insert-only table)

    # ------------------------------------------------------------------
    # hot path: batched slot resolution + scatter folds
    # ------------------------------------------------------------------
    def slots_for_batch(self, keys: np.ndarray) -> jax.Array:
        """Lookup-or-insert a batch of int64 keys; grows (rehash) on
        overflow. Returns device int32 slots."""
        keys = _sanitize_keys(np.asarray(keys))
        dkeys = jnp.asarray(keys)
        while True:
            new_table, slots, ok = lookup_or_insert(self.table, dkeys)
            all_ok, occupancy = jax.device_get(
                (ok.all(), (new_table != EMPTY_KEY).sum()))
            if bool(all_ok):
                self.table = new_table
                self._num_keys = int(occupancy)
                if self._num_keys > 0.6 * self.capacity:
                    self._rehash(self.capacity * 2)
                    # slots computed against the pre-rehash table are stale
                    slots = lookup(self.table, dkeys)
                return slots
            self._rehash(self.capacity * 2)

    def _rehash(self, new_capacity: int) -> None:
        """Grow the table and remap every array state on device."""
        old_table = self.table
        occupied = jax.device_get(old_table != EMPTY_KEY)
        old_keys = jax.device_get(old_table)[occupied]
        old_slots = np.flatnonzero(occupied).astype(np.int32)

        new_table = make_table(new_capacity)
        new_table, new_slots, ok = lookup_or_insert(
            new_table, jnp.asarray(old_keys))
        if not bool(jax.device_get(ok.all())):  # pragma: no cover
            raise RuntimeError("rehash failed: pathological key distribution")
        self.table = new_table
        self.capacity = new_capacity
        for st in self._array_states.values():
            shape = ((st.ring, new_capacity) if st.ring else (new_capacity,))
            new_arr = make_accumulator(st.kind, shape, st.dtype)
            if st.ring:
                new_arr = new_arr.at[:, new_slots].set(
                    st.array[:, jnp.asarray(old_slots)])
            else:
                new_arr = new_arr.at[new_slots].set(
                    st.array[jnp.asarray(old_slots)])
            st.array = new_arr

    def register_array_state(self, name: str, kind: str, dtype,
                             ring: Optional[int] = None) -> None:
        if name not in self._array_states:
            self._array_states[name] = _ArrayState(name, kind, dtype, ring,
                                                   self.capacity)

    def get_array(self, name: str) -> jax.Array:
        return self._array_states[name].array

    def set_array(self, name: str, array: jax.Array) -> None:
        self._array_states[name].array = array

    def fold_batch(self, name: str, slots: jax.Array, values: jax.Array,
                   valid: jax.Array,
                   ring_idx: Optional[jax.Array] = None) -> None:
        """acc[(ring_idx,) slot] op= values — one scatter per aggregate."""
        st = self._array_states[name]
        if st.ring:
            flat = ring_idx.astype(jnp.int32) * st.array.shape[1] + slots
            folded = scatter_fold(st.kind, st.array.reshape(-1), flat,
                                  values, valid)
            st.array = folded.reshape(st.array.shape)
        else:
            st.array = scatter_fold(st.kind, st.array, slots, values, valid)

    def reset_ring_row(self, row: int) -> None:
        """Zero one ring row of every ring-shaped array state back to its
        aggregate identity — pane retirement for the window operators."""
        for st in self._array_states.values():
            if st.ring:
                st.array = st.array.at[row].set(
                    AGG_INITS[st.kind](st.array.dtype))

    def conform_ring(self, ring: int, live_panes: Iterable[int]) -> None:
        """Re-seat ring-shaped array states restored under a DIFFERENT ring
        size onto ``ring`` rows: each live pane's row moves from
        (p % old_ring) to (p % ring); every other row is the aggregate
        identity (retired). No-op when sizes already match."""
        live = list(live_panes)
        for st in self._array_states.values():
            if not st.ring or st.ring == ring:
                continue
            if len(live) > ring:
                raise RuntimeError(
                    f"cannot conform ring {st.ring} -> {ring}: "
                    f"{len(live)} panes are live; increase ring_size")
            old = st.array
            new = make_accumulator(st.kind, (ring, self.capacity), st.dtype)
            for p in live:
                new = new.at[p % ring].set(old[p % st.ring])
            st.array = new
            st.ring = ring

    def occupied_mask(self) -> jax.Array:
        return self.table != EMPTY_KEY

    @property
    def num_keys(self) -> int:
        return self._num_keys

    # ------------------------------------------------------------------
    # row-access compatibility plane (slow; host roundtrip per call)
    # ------------------------------------------------------------------
    def get_partitioned_state(self, descriptor: StateDescriptor) -> State:
        if descriptor.kind != "value":
            raise NotImplementedError(
                "TPU backend row plane supports ValueState only; use array "
                "states (device operators) or the hashmap backend")
        handle = self._row_states.get(descriptor.name)
        if handle is None:
            self.register_array_state(descriptor.name, "sum", jnp.float32)
            self.register_array_state(f"{descriptor.name}.__set__", "sum",
                                      jnp.int32)
            handle = _TpuValueState(self, descriptor)
            self._row_states[descriptor.name] = handle
        return handle

    def keys(self, state_name: str, namespace=None) -> Iterable[Any]:
        t = jax.device_get(self.table)
        return t[t != EMPTY_KEY].tolist()

    def namespaces(self, state_name: str) -> Iterable[Any]:
        return [None]

    # ------------------------------------------------------------------
    # checkpointing
    # ------------------------------------------------------------------
    def snapshot(self, checkpoint_id: int) -> dict:
        t = jax.device_get(self.table)
        occupied = t != EMPTY_KEY
        keys = t[occupied]
        slots = np.flatnonzero(occupied)
        # same hash as record routing (hash_batch), so restored keys filter
        # into exactly the key-group ranges the exchange routes them to
        groups = key_groups_for_hash_batch(hash_batch(keys),
                                           self.max_parallelism)
        states = {}
        for name, st in self._array_states.items():
            arr = jax.device_get(st.array)
            vals = arr[:, slots] if st.ring else arr[slots]
            states[name] = {"kind": st.kind, "dtype": str(np.dtype(st.dtype)),
                            "ring": st.ring, "values": vals}
        return {"kind": "tpu", "keys": keys, "key_groups": groups,
                "states": states}

    def restore(self, snapshots: Iterable[dict]) -> None:
        all_keys, per_state_vals = [], {}
        state_meta: dict[str, dict] = {}
        for snap in snapshots:
            groups = np.asarray(snap["key_groups"])
            sel = np.array([g in self.key_group_range for g in groups],
                           dtype=bool)
            keys = np.asarray(snap["keys"])[sel]
            all_keys.append(keys)
            for name, sdata in snap["states"].items():
                state_meta[name] = sdata
                vals = np.asarray(sdata["values"])
                vals = vals[:, sel] if sdata["ring"] else vals[sel]
                per_state_vals.setdefault(name, []).append(vals)
        keys = (np.concatenate(all_keys) if all_keys
                else np.empty(0, np.int64))
        while self.capacity < 2 * max(len(keys), 1):
            self.capacity *= 2
        self.table = make_table(self.capacity)
        self._num_keys = len(keys)
        if len(keys):
            self.table, slots, ok = lookup_or_insert(self.table,
                                                     jnp.asarray(keys))
            assert bool(jax.device_get(ok.all()))
        else:
            slots = jnp.zeros(0, jnp.int32)
        self._array_states.clear()
        for name, meta in state_meta.items():
            dtype = jnp.dtype(meta["dtype"])
            st = _ArrayState(name, meta["kind"], dtype, meta["ring"],
                             self.capacity)
            if len(keys):
                vals = (np.concatenate(per_state_vals[name], axis=-1))
                if meta["ring"]:
                    st.array = st.array.at[:, slots].set(jnp.asarray(vals))
                else:
                    st.array = st.array.at[slots].set(jnp.asarray(vals))
            self._array_states[name] = st


class _TpuValueState(ValueState):
    """Row plane: one float32 cell per key plus a presence bit, so a stored
    0.0 is distinguishable from 'never written' (API completeness; each call
    is a host round-trip — the hot path is the array plane)."""

    def __init__(self, backend: TpuKeyedStateBackend, desc: StateDescriptor):
        self._b, self._d = backend, desc

    def _read_slot(self) -> int:
        """Lookup WITHOUT insert: reading an absent key must not occupy a
        table slot (it would leak into snapshots and occupancy)."""
        key = jnp.asarray(
            _sanitize_keys(np.asarray([self._b._current_key])))
        return int(jax.device_get(lookup(self._b.table, key))[0])

    def _write_slot(self) -> int:
        key = np.asarray([self._b._current_key], dtype=np.int64)
        return int(jax.device_get(self._b.slots_for_batch(key))[0])

    def value(self):
        slot = self._read_slot()
        if slot < 0:
            return self._d.default
        present = int(jax.device_get(
            self._b.get_array(f"{self._d.name}.__set__")[slot]))
        if not present:
            return self._d.default
        return float(jax.device_get(self._b.get_array(self._d.name)[slot]))

    def update(self, value) -> None:
        slot = self._write_slot()
        arr = self._b.get_array(self._d.name)
        self._b.set_array(self._d.name, arr.at[slot].set(float(value)))
        flag = self._b.get_array(f"{self._d.name}.__set__")
        self._b.set_array(f"{self._d.name}.__set__", flag.at[slot].set(1))

    def clear(self) -> None:
        slot = self._read_slot()
        if slot < 0:
            return
        arr = self._b.get_array(self._d.name)
        self._b.set_array(self._d.name, arr.at[slot].set(0.0))
        flag = self._b.get_array(f"{self._d.name}.__set__")
        self._b.set_array(f"{self._d.name}.__set__", flag.at[slot].set(0))


register_backend("tpu", TpuKeyedStateBackend)
