"""TpuKeyedStateBackend: device-resident keyed state.

The framework's answer to the reference's RocksDB backend
(flink-state-backends RocksDBKeyedStateBackend.java:114,
EmbeddedRocksDBStateBackend.java:100): instead of an LSM tree behind JNI,
keyed state for one subtask's key-group range lives in HBM as dense arrays
indexed by a device hash table (ops/hash_table.py). Registered under name
"tpu" in the backend registry (the StateBackendLoader seam).

Two access planes:
* **array states** — the hot path: named [capacity] or [ring, capacity]
  accumulator arrays updated by whole-batch scatter folds; used by the device
  window/aggregate operators. Rehash (growth) remaps every array on device.
* **row states** — API-compatibility plane (ValueState etc.) with host-side
  gather/scatter per access; correct but slow, for small/irregular state.

Snapshots materialize (keys, key_groups, arrays) to host numpy, partitioned
by key group for rescaling restore — the device analog of key-group-ordered
snapshot streams.

Device keys must be int64 (Nexmark-style ids). Non-integer keys belong on
the host backend — the graph planner routes accordingly.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.keygroups import KeyGroupRange, hash_batch, \
    key_groups_for_hash_batch
from ..metrics.device import DEVICE_STATS, instrumented_program_cache
from ..ops.hash_table import (
    EMPTY_KEY, lookup, lookup_or_insert, make_table, sanitize_keys_device,
)
from ..ops.segment_ops import AGG_INITS, make_accumulator, scatter_fold
from .backend import KeyedStateBackend, State, ValueState, register_backend
from .descriptors import StateDescriptor
from .spill import HostTier
from .tiering import PrefetchPipeline, ResidencyManager

__all__ = ["TpuKeyedStateBackend"]


def _sanitize_keys(keys: np.ndarray) -> np.ndarray:
    """Remap the EMPTY sentinel (int64 max) to int64 max - 1."""
    return np.where(keys == np.int64(EMPTY_KEY), np.int64(EMPTY_KEY) - 1,
                    keys.astype(np.int64))


def _tiering_params(config) -> dict:
    """Resolve state.tiering.* knobs (option defaults when the backend is
    constructed without a Configuration, e.g. directly in tests)."""
    from ..core.config import TieringOptions as T
    if config is None:
        return {"seed": T.SEED.default,
                "decay_interval": T.DECAY_INTERVAL.default,
                "decay_factor": T.DECAY_FACTOR.default,
                "promote_headroom": T.PROMOTE_HEADROOM.default,
                "promote_min_heat": T.PROMOTE_MIN_HEAT.default,
                "async_prefetch": T.ASYNC_PREFETCH.default}
    return {"seed": int(config.get(T.SEED)),
            "decay_interval": int(config.get(T.DECAY_INTERVAL)),
            "decay_factor": float(config.get(T.DECAY_FACTOR)),
            "promote_headroom": float(config.get(T.PROMOTE_HEADROOM)),
            "promote_min_heat": float(config.get(T.PROMOTE_MIN_HEAT)),
            "async_prefetch": bool(config.get(T.ASYNC_PREFETCH))}


# ----------------------------------------------------------------------
# typed row-plane programs (batched per-key value access; see
# TpuKeyedStateBackend.rows_* below). All scatters resolve duplicate keys
# within a batch DETERMINISTICALLY (last occurrence wins for writes,
# first occurrence admits for dedup) via first/last-position scatters.
# ----------------------------------------------------------------------

@instrumented_program_cache("state.reset_row")
def _reset_row_program(sig: tuple):
    """One jitted pane-retirement program per ring-plane signature: zero
    ring row ``row`` of every plane to its aggregate identity in a single
    dispatch. ``sig`` = tuple of (kind, dtype_str, shape); the row index is
    a traced scalar so one executable serves every row. State planes are
    donated off-CPU so XLA updates them in place."""
    donate = (0,)

    @partial(jax.jit, donate_argnums=donate)
    def reset(arrays: tuple, row):
        out = []
        for (kind, _dt, _shape), a in zip(sig, arrays):
            fill = jnp.full((1,) + a.shape[1:], AGG_INITS[kind](a.dtype),
                            a.dtype)
            out.append(jax.lax.dynamic_update_slice_in_dim(a, fill, row, 0))
        return tuple(out)

    return reset


@partial(jax.jit, donate_argnums=(0,))
def _mirror_claimed(table, slots, keys):
    """Write natively-claimed (slot, key) pairs into the device table
    mirror; padding rows carry slot == len(table) and drop."""
    return table.at[slots].set(keys, mode="drop")


@jax.jit
def _rows_set(vals, present, last_ts, slots, new_vals, now):
    B = slots.shape[0]
    cap = vals.shape[0]
    widx = jnp.where(slots >= 0, slots, cap).astype(jnp.int32)
    lastpos = jnp.full(cap + 1, -1, jnp.int32).at[widx].max(
        jnp.arange(B, dtype=jnp.int32))
    widx = jnp.where(jnp.arange(B, dtype=jnp.int32) == lastpos[widx],
                     widx, cap)
    vals = vals.at[widx].set(new_vals.astype(vals.dtype), mode="drop")
    present = present.at[widx].set(jnp.int8(1), mode="drop")
    if last_ts is not None:
        last_ts = last_ts.at[widx].set(now, mode="drop")
    return vals, present, last_ts


@jax.jit
def _rows_get(table, vals, present, last_ts, keys, now, ttl_ms):
    slots = lookup(table, keys)
    found = slots >= 0
    sc = jnp.maximum(slots, 0)
    p = (present[sc] > 0) & found
    if last_ts is not None:
        p = p & ((now - last_ts[sc]) <= ttl_ms)
    return vals[sc], p


@jax.jit
def _rows_get_slots(vals, present, last_ts, slots, now, ttl_ms):
    """_rows_get with slots already resolved (native host index)."""
    found = slots >= 0
    sc = jnp.maximum(slots, 0)
    p = (present[sc] > 0) & found
    if last_ts is not None:
        p = p & ((now - last_ts[sc]) <= ttl_ms)
    return vals[sc], p


@jax.jit
def _rows_unset_slots(present, slots):
    cap = present.shape[0]
    widx = jnp.where(slots >= 0, slots, cap).astype(jnp.int32)
    return present.at[widx].set(jnp.int8(0), mode="drop"), \
        jnp.maximum(slots, 0)


@jax.jit
def _rows_unset(table, present, keys):
    slots = lookup(table, keys)
    cap = present.shape[0]
    widx = jnp.where(slots >= 0, slots, cap).astype(jnp.int32)
    return present.at[widx].set(jnp.int8(0), mode="drop"), \
        jnp.maximum(slots, 0)


@jax.jit
def _dedup_first_slots(present, last_ts, slots, valid, ts, ttl_ms):
    """Keep-first admission with slots ALREADY resolved (native host
    index): same semantics as _dedup_first minus the insert."""
    B = slots.shape[0]
    cap = present.shape[0]
    ok = valid.astype(bool)
    widx = jnp.where(ok, slots, cap).astype(jnp.int32)
    firstpos = jnp.full(cap + 1, B, jnp.int32).at[widx].min(
        jnp.arange(B, dtype=jnp.int32))
    is_first = jnp.arange(B, dtype=jnp.int32) == firstpos[widx]
    sc = jnp.maximum(slots, 0)
    was = (present[sc] > 0) & ok
    if last_ts is not None:
        was = was & ((ts - last_ts[sc]) <= ttl_ms)
    fresh = ok & ~was & is_first
    present = present.at[widx].set(jnp.int8(1), mode="drop")
    if last_ts is not None:
        fidx = jnp.where(fresh, slots, cap).astype(jnp.int32)
        last_ts = last_ts.at[fidx].set(ts, mode="drop")
    return present, last_ts, fresh, sc


@jax.jit
def _dedup_first(table, present, last_ts, keys, valid, ts, ttl_ms):
    """Keep-first admission: fresh[i] iff row i is valid, its key admits
    (absent / cleared / TTL-expired in state), and i is the key's first
    occurrence in this batch. Presence is claimed for admitted keys; the
    TTL clock refreshes on admission only (keep-first write semantics)."""
    B = keys.shape[0]
    cap = present.shape[0]
    table, slots, ok = lookup_or_insert(table, keys, valid)
    widx = jnp.where(ok, slots, cap).astype(jnp.int32)
    firstpos = jnp.full(cap + 1, B, jnp.int32).at[widx].min(
        jnp.arange(B, dtype=jnp.int32))
    is_first = jnp.arange(B, dtype=jnp.int32) == firstpos[widx]
    sc = jnp.maximum(slots, 0)
    was = (present[sc] > 0) & ok
    if last_ts is not None:
        was = was & ((ts - last_ts[sc]) <= ttl_ms)
    fresh = ok & ~was & is_first
    present = present.at[widx].set(jnp.int8(1), mode="drop")
    if last_ts is not None:
        fidx = jnp.where(fresh, slots, cap).astype(jnp.int32)
        last_ts = last_ts.at[fidx].set(ts, mode="drop")
    overflow = jnp.any(valid & ~ok)
    occ = (table != jnp.int64(EMPTY_KEY)).sum()
    return table, present, last_ts, fresh, sc, overflow, occ


class _ArrayState:
    __slots__ = ("name", "kind", "dtype", "ring", "array", "role")

    def __init__(self, name: str, kind: str, dtype, ring: Optional[int],
                 capacity: int, role: str = "pane"):
        self.name = name
        self.kind = kind
        self.dtype = dtype
        self.ring = ring
        # role "pane" (default): source-of-truth pane accumulators — they
        # snapshot, spill, retire and conform. role "window": DERIVED
        # incremental-fire state (running window accumulators / merge-tree
        # planes). Window planes follow slot remaps (rehash/growth) but are
        # excluded from snapshots, the host spill tier, ring-row
        # retirement and conform_ring — a restore simply rebuilds them
        # from the pane planes.
        self.role = role
        shape = (ring, capacity) if ring else (capacity,)
        self.array = make_accumulator(kind, shape, dtype)


class TpuKeyedStateBackend(KeyedStateBackend):
    # the row plane is ValueState-only: operators needing namespaced list/
    # aggregating state (host WindowOperator) must fall back to hashmap
    SUPPORTS_GENERAL_STATE = False

    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int,
                 capacity: int = 1 << 16, config=None,
                 defer_overflow: bool = False,
                 hbm_budget_slots: int = 0,
                 host_index: bool = True, **_kw):
        super().__init__(key_group_range, max_parallelism)
        cap = 1
        while cap < capacity:
            cap <<= 1
        self.capacity = cap
        self.table = make_table(cap)
        self._array_states: dict[str, _ArrayState] = {}
        self._row_states: dict[str, State] = {}
        self._row_meta: dict[str, int] = {}  # row-plane name -> ttl_ms
        self._num_keys = 0  # host-tracked occupancy (exact: insert-only table)
        # deferred mode: the hot path never syncs with the host; overflow
        # accumulates in a device counter checked at watermark boundaries
        self._defer = bool(defer_overflow)
        self._dropped = jnp.zeros((), jnp.int64)
        # spill tier: device capacity is capped at the HBM budget; cold key
        # groups page out to host RAM (state/spill.py). 0 = unlimited.
        # With defer_overflow the split is computed ON DEVICE (spilled-group
        # mask + staging compaction in the fused step; see
        # runtime/operators/device_window._step_program) so the hot path
        # still never syncs — round-3 unification of VERDICT r2 weak #4.
        budget = 0
        if hbm_budget_slots:
            budget = 1
            while budget * 2 <= hbm_budget_slots:
                budget <<= 1
            if cap > budget:
                # the budget wins: start at the cap the device may use
                cap = budget
                self.capacity = cap
                self.table = make_table(cap)
        self._budget = budget
        self._host: Optional[HostTier] = None
        self._batch_no = 0
        # tiered residency (state/tiering/): the manager owns the decayed
        # 2Q heat policy deciding WHICH groups evict/promote; the pipeline
        # stages warm->hot promotions off the mailbox thread. Both exist
        # only under a budget; decisions apply at batch boundaries
        # (tier_boundary) and on overflow pressure (_evict_cold_groups).
        self._residency: Optional[ResidencyManager] = None
        self._prefetch: Optional[PrefetchPipeline] = None
        if budget:
            params = _tiering_params(config)
            self._residency = ResidencyManager(
                max_parallelism, budget,
                seed=params["seed"],
                decay_interval=params["decay_interval"],
                decay_factor=params["decay_factor"],
                promote_headroom=params["promote_headroom"],
                promote_min_heat=params["promote_min_heat"])
            self._prefetch = PrefetchPipeline(
                self._stage_promotion,
                asynchronous=params["async_prefetch"])
        self._pending_host: Optional[tuple[np.ndarray, np.ndarray]] = None
        # -- incremental snapshot capture (delta CAPTURE, the analog of
        # RocksIncrementalSnapshotStrategy.java:70's SST diff): a device
        # dirty bitmap over slot blocks + a host mirror of the last
        # snapshot. A snapshot transfers only dirty blocks and patches the
        # mirror; ring-row retirements replay host-side (no device work).
        self._block = min(512, self.capacity)    # slots per dirty block
        self._n_blocks = self.capacity // self._block
        self._dirty = jnp.zeros(self._n_blocks, bool)
        self._mirror: Optional[dict] = None
        self._retired_rows: set[int] = set()
        self.last_snapshot_dma_bytes = 0
        # deferred-spill device mirrors: spilled-group mask (read by the
        # fused step) and per-group last-touch (device LRU clock)
        self._spilled_dev: Optional[jax.Array] = None
        self._touch_dev: Optional[jax.Array] = None
        # native host index (CPU fallback hot path): when the "device" IS
        # the host, slot resolution through the C++ open-addressing index
        # (native/native.cpp HashIndex) beats the XLA probe loop ~15x —
        # XLA's gathers are single-threaded general loads, while the
        # sequential C++ probe walks cache lines. Slots are dense
        # (first-seen order), so plane growth is a pad, never a remap; the
        # device table stays authoritative for fires/snapshots via a
        # per-batch mirror scatter of the claimed keys. Excluded under an
        # HBM budget (the spill split needs device-computed groups), and
        # opted out (host_index=False) by operators whose own fused
        # programs insert into the table with the XLA probe — mixing the
        # two allocators on one table would place the same key at two
        # slots (native slots are dense, XLA slots lie on the probe
        # sequence).
        self._hi = None
        if config is not None:
            from ..core.config import StateOptions
            host_index = host_index and bool(
                config.get(StateOptions.TPU_HOST_INDEX))
        if host_index and not self._budget \
                and jax.default_backend() == "cpu":
            try:
                from .. import native as _native
                if _native.NATIVE_AVAILABLE:
                    self._hi = _native.HostHashIndex(cap)
            except ImportError:  # pragma: no cover
                pass

    # ------------------------------------------------------------------
    # hot path: batched slot resolution + scatter folds
    # ------------------------------------------------------------------
    @property
    def host_index_active(self) -> bool:
        return self._hi is not None

    def native_slots(self, keys: np.ndarray) -> np.ndarray:
        """Slot resolution through the native host index (CPU fallback):
        dense first-seen slots from the C++ open-addressing table, planes
        grown by padding when the key count crosses capacity (dense slots
        never remap), and the claimed keys mirrored into the device table
        so fires/snapshots read the same state as the XLA path."""
        keys = _sanitize_keys(np.asarray(keys))
        slots = self._hi.upsert(keys)
        n = len(self._hi)
        while n > self.capacity:
            self._grow_planes(self.capacity * 2)
        from ..ops.segment_ops import pow2_ceil

        B = len(keys)
        P = pow2_ceil(max(B, 1))
        if P != B:  # constant shapes: one mirror executable per bucket
            pslots = np.full(P, self.capacity, np.int64)
            pslots[:B] = slots
            pkeys = np.concatenate(
                [keys, np.zeros(P - B, np.int64)])
        else:
            pslots, pkeys = slots.astype(np.int64), keys
        self.table = _mirror_claimed(self.table, jnp.asarray(pslots),
                                     jnp.asarray(pkeys))
        self._num_keys = n
        return slots

    def _grow_planes(self, new_capacity: int) -> None:
        """Native-mode growth: dense slots are stable, so growing is a pad
        of every plane (and the table mirror) — no remap, no re-probe."""
        pad = new_capacity - self.capacity
        self.table = jnp.concatenate(
            [self.table, jnp.full(pad, EMPTY_KEY, jnp.int64)])
        for st in self._array_states.values():
            ident = AGG_INITS[st.kind](st.dtype)
            if st.ring:
                st.array = jnp.concatenate(
                    [st.array, jnp.full((st.ring, pad), ident, st.dtype)],
                    axis=1)
            else:
                st.array = jnp.concatenate(
                    [st.array, jnp.full(pad, ident, st.dtype)])
        self.capacity = new_capacity
        self._invalidate_mirror()

    def slots_for_batch(self, keys: np.ndarray) -> jax.Array:
        """Lookup-or-insert a batch of int64 keys. In the default
        (synchronous) mode the table grows by rehash on overflow, at the
        cost of one host sync per batch. In deferred mode (the pipelined
        bench/production path) there is NO sync: failed inserts return
        negative slots (the fold skips them), a device drop counter
        accumulates, and ``check_health`` at the next watermark raises /
        grows. Returns device int32 slots."""
        if self._hi is not None:
            slots = self.native_slots(np.asarray(keys))
            dslots = jnp.asarray(slots)
            self._pending_host = None
            self.mark_dirty(dslots)
            return dslots
        keys = _sanitize_keys(np.asarray(keys))
        if self._defer:
            return self.slots_for_batch_device(jnp.asarray(keys))
        self._pending_host = None
        groups = None
        if self._budget:
            self._batch_no += 1
            groups = key_groups_for_hash_batch(hash_batch(keys),
                                               self.max_parallelism)
            self._residency.observe(
                groups, self._batch_no,
                self._host.spilled_mask if self._host is not None else None)
        dkeys = jnp.asarray(keys)
        while True:
            # keep the device call's shapes CONSTANT across batches (one
            # compiled executable): spilled rows ride along masked invalid
            # instead of being sliced out
            if (self._host is not None and self._host.active
                    and groups is not None):
                sp = self._host.spilled_mask[groups]
                if not sp.any():
                    sp = None
            else:
                sp = None
            dvalid = None if sp is None else jnp.asarray(~sp)
            new_table, slots, ok = lookup_or_insert(self.table, dkeys,
                                                    dvalid)
            ok_all = ok.all() if sp is None else (ok | jnp.asarray(sp)).all()
            all_ok, occupancy = jax.device_get(
                (ok_all, (new_table != EMPTY_KEY).sum()))
            if bool(all_ok):
                self.table = new_table
                self._num_keys = int(occupancy)
                if self._num_keys > 0.6 * self.capacity:
                    if not self._budget or 2 * self.capacity <= self._budget:
                        self._rehash(self.capacity * 2)
                        # slots against the pre-rehash table are stale
                        slots = lookup(self.table, dkeys)
                    else:
                        self._evict_cold_groups(batch_groups=groups)
                        continue  # spilled set changed; re-split the batch
                break
            if not self._budget or 2 * self.capacity <= self._budget:
                self._rehash(self.capacity * 2)
            else:
                self._evict_cold_groups(batch_groups=groups)
        if sp is not None:
            host_pos = np.flatnonzero(sp)
            hslots = self._host.slots_for(keys[host_pos])
            self._host.host_folds += 1
            self._pending_host = (host_pos, hslots)
        self.mark_dirty(slots)
        return slots

    # -- incremental snapshot capture ----------------------------------
    @property
    def dirty_block_size(self) -> int:
        return self._block

    def mark_dirty(self, slots) -> None:
        """Mark the dirty blocks containing ``slots`` (device or numpy).
        Invalid slots (<0) conservatively mark block 0."""
        idx = jnp.maximum(jnp.asarray(slots), 0) // self._block
        self._dirty = self._dirty.at[idx].set(True)

    def set_dirty_mask(self, dirty: jax.Array) -> None:
        """Adopt a dirty mask updated inside a fused step program."""
        self._dirty = dirty

    @property
    def dirty_mask(self) -> jax.Array:
        return self._dirty

    def _invalidate_mirror(self) -> None:
        """Structural change (rehash/evict/restore/ring conform): the next
        snapshot re-captures everything."""
        self._mirror = None
        self._block = min(512, self.capacity)
        self._n_blocks = self.capacity // self._block
        self._dirty = jnp.zeros(self._n_blocks, bool)
        self._retired_rows.clear()

    def _sync_mirror(self) -> None:
        """Bring the host mirror up to date with device state, transferring
        only dirty blocks (plus any state registered since the mirror was
        built). Tracks the DMA bytes of this capture.

        Deadline-bounded (fault site transfer.d2h; the deadline is the
        CHECKPOINT timeout — this is a bulk snapshot-path capture, not a
        per-batch transfer — and there is no in-place retry: the mirror
        update mutates self, so a stall propagates as StallError — a
        wedged snapshot capture then fails the checkpoint/evacuation
        instead of freezing it, and recovery rides the restart path)."""
        from ..runtime.watchdog import WATCHDOG

        def _capture():
            from ..runtime.faults import fire_with_retries
            fire_with_retries("transfer.d2h", scope="tpu_backend.snapshot")
            self._sync_mirror_inner()

        WATCHDOG.run("transfer.d2h", _capture, scope="tpu_backend.snapshot",
                     deadline=WATCHDOG.deadline_for("checkpoint.write"))

    def _sync_mirror_inner(self) -> None:
        nb, bs = self._n_blocks, self._block
        self.last_snapshot_dma_bytes = 0
        snap_states = self._snapshot_states()
        if self._mirror is None:
            # writable copies: device_get may return read-only views
            t = np.array(jax.device_get(self.table))
            arrs = {n: np.array(jax.device_get(st.array))
                    for n, st in snap_states}
            self._mirror = {"table": t, "arrays": arrs}
            self.last_snapshot_dma_bytes = t.nbytes + sum(
                a.nbytes for a in arrs.values())
        else:
            arrs = self._mirror["arrays"]
            for n, st in snap_states:
                if n not in arrs:
                    a = np.array(jax.device_get(st.array))
                    arrs[n] = a
                    self.last_snapshot_dma_bytes += a.nbytes
            # ① replay ring-row retirements host-side (no DMA)
            for row in self._retired_rows:
                for n, st in snap_states:
                    if st.ring:
                        arrs[n][row, :] = np.asarray(
                            AGG_INITS[st.kind](st.array.dtype))
            # ② patch dirty blocks: gather on device, ONE transfer
            d = np.asarray(jax.device_get(self._dirty))
            self.last_snapshot_dma_bytes += d.nbytes
            blocks = np.flatnonzero(d)
            if len(blocks):
                bidx = jnp.asarray(blocks)
                parts = {"__table__": self.table.reshape(nb, bs)[bidx]}
                for n, st in snap_states:
                    if st.ring:
                        parts[n] = st.array.reshape(
                            st.array.shape[0], nb, bs)[:, bidx]
                    else:
                        parts[n] = st.array.reshape(nb, bs)[bidx]
                host = jax.device_get(parts)
                self.last_snapshot_dma_bytes += sum(
                    np.asarray(v).nbytes for v in host.values())
                self._mirror["table"].reshape(nb, bs)[blocks] = \
                    np.asarray(host["__table__"])
                for n, st in snap_states:
                    a, p = arrs[n], np.asarray(host[n])
                    if st.ring:
                        a.reshape(a.shape[0], nb, bs)[:, blocks] = p
                    else:
                        a.reshape(nb, bs)[blocks] = p
        self._retired_rows.clear()
        self._dirty = jnp.zeros(nb, bool)

    def _rehash(self, new_capacity: int) -> None:
        """Grow the table and remap every array state on device."""
        old_table = self.table
        occupied = jax.device_get(old_table != EMPTY_KEY)
        old_keys = jax.device_get(old_table)[occupied]
        old_slots = np.flatnonzero(occupied).astype(np.int32)
        self._rebuild_device(old_keys, old_slots, new_capacity)

    def _rebuild_device(self, keep_keys: np.ndarray,
                        old_slots: np.ndarray, new_capacity: int) -> None:
        """Re-key the device table to ``keep_keys`` only (rehash growth or
        post-eviction shrink of the resident set), remapping every array
        state's rows on device."""
        old_arrays = {n: st.array for n, st in self._array_states.items()}
        new_table = make_table(new_capacity)
        if len(keep_keys):
            new_table, new_slots, ok = lookup_or_insert(
                new_table, jnp.asarray(keep_keys))
            if not bool(jax.device_get(ok.all())):  # pragma: no cover
                raise RuntimeError(
                    "rebuild failed: pathological key distribution")
        self.table = new_table
        self.capacity = new_capacity
        self._num_keys = len(keep_keys)
        for name, st in self._array_states.items():
            shape = ((st.ring, new_capacity) if st.ring else (new_capacity,))
            new_arr = make_accumulator(st.kind, shape, st.dtype)
            if len(keep_keys):
                if st.ring:
                    new_arr = new_arr.at[:, new_slots].set(
                        old_arrays[name][:, jnp.asarray(old_slots)])
                else:
                    new_arr = new_arr.at[new_slots].set(
                        old_arrays[name][jnp.asarray(old_slots)])
            st.array = new_arr
        self._invalidate_mirror()

    # ------------------------------------------------------------------
    # spill tier (HBM budget; state/spill.py)
    # ------------------------------------------------------------------
    @property
    def spill_active(self) -> bool:
        return self._host is not None and self._host.active

    @property
    def host_tier(self) -> Optional[HostTier]:
        return self._host

    def _evict_cold_groups(self, rebuild_capacity: Optional[int] = None,
                           batch_groups: Optional[np.ndarray] = None
                           ) -> None:
        """Page the coldest resident key groups to the host tier —
        deadline-bounded under site ``tier.evict`` (the d2h pull plus the
        device-table rebuild used to run unbounded inline on the mailbox
        thread; a wedged DMA now raises StallError into the restart path
        instead of freezing ingest). The fault site fires BEFORE any
        state moves: a transient trip retries with nothing mutated, a
        persistent one fails the batch."""
        from ..runtime.faults import fire_with_retries
        from ..runtime.watchdog import WATCHDOG
        fire_with_retries("tier.evict", scope="tpu_backend.tier")
        WATCHDOG.run(
            "tier.evict",
            lambda: self._evict_cold_groups_inner(rebuild_capacity,
                                                  batch_groups),
            scope="tpu_backend.tier")

    def _evict_cold_groups_inner(self,
                                 rebuild_capacity: Optional[int] = None,
                                 batch_groups: Optional[np.ndarray] = None
                                 ) -> None:
        """Eviction body: the unit of movement is the key group
        (KeyGroupRangeAssignment.java:63), coldest first by the residency
        policy's decayed 2Q order (probationary by recency, then
        protected by heat). When the resident set alone cannot make room
        (e.g. one batch introduces more new keys than the whole budget),
        groups OF THE INCOMING BATCH are marked spilled too — each call
        spills at least one, so the caller's retry loop always
        terminates."""
        from ..metrics.tracing import TRACER
        self._ensure_host_tier()
        cap = rebuild_capacity or self.capacity
        with TRACER.span("tier", "Evict") as sp:
            keys_dev, slots_dev, groups_dev = self._device_resident()
            counts = np.bincount(groups_dev,
                                 minlength=self.max_parallelism)
            resident = np.flatnonzero(counts > 0)
            order = self._residency.eviction_order(resident)
            target = int(0.4 * cap)
            need = max(len(keys_dev) - target, max(1, len(keys_dev) // 4))
            evict_groups, acc = [], 0
            for g in order:
                evict_groups.append(int(g))
                acc += int(counts[g])
                if acc >= need:
                    break
            if acc < need and batch_groups is not None:
                # resident set can't make room: spill half the incoming
                # batch's (not yet spilled) groups as well
                fresh = np.unique(batch_groups)
                fresh = fresh[~self._host.spilled_mask[fresh]]
                fresh = [int(g) for g in fresh
                         if g not in set(evict_groups)]
                evict_groups.extend(fresh[:max(1, len(fresh) // 2)])
            if not evict_groups:
                raise RuntimeError(
                    "spill eviction made no progress; raise the HBM "
                    "budget")
            gmask = np.zeros(self.max_parallelism, bool)
            gmask[evict_groups] = True
            sel = gmask[groups_dev]
            self._absorb_and_rebuild(keys_dev, slots_dev, sel,
                                     evict_groups, cap)
            self._residency.note_demoted(np.asarray(evict_groups, np.int64))
            DEVICE_STATS.note_tier_eviction(len(evict_groups),
                                            int(sel.sum()))
            sp.set_attribute("groups", len(evict_groups))
            sp.set_attribute("keys", int(sel.sum()))

    # -- deferred spill (device-side split; see device_window) ----------
    @property
    def is_deferred(self) -> bool:
        return self._defer

    @property
    def hbm_budget(self) -> int:
        return self._budget

    @property
    def spilled_mask_device(self) -> jax.Array:
        if self._spilled_dev is None:
            self._spilled_dev = jnp.zeros(self.max_parallelism, bool)
        return self._spilled_dev

    @property
    def touch_device(self) -> jax.Array:
        if self._touch_dev is None:
            self._touch_dev = jnp.zeros(self.max_parallelism, jnp.int64)
        return self._touch_dev

    def set_touch_device(self, touch: jax.Array) -> None:
        self._touch_dev = touch

    def note_batch(self) -> int:
        """Monotone batch clock for the device LRU."""
        self._batch_no += 1
        return self._batch_no

    def _sync_spilled_dev(self) -> None:
        if self._host is not None:
            self._spilled_dev = jnp.asarray(self._host.spilled_mask)

    def _sync_touch_from_device(self) -> None:
        """Merge the on-device per-group touch clock into the residency
        policy (deferred spill path: the fused step maintains the clock,
        the policy only sees it at boundaries / eviction time)."""
        if self._touch_dev is not None and self._residency is not None:
            self._residency.adopt_clock(
                np.asarray(jax.device_get(self._touch_dev)),
                self._host.spilled_mask if self._host is not None else None)

    def _ensure_host_tier(self) -> HostTier:
        if self._host is None:
            self._host = HostTier(self.max_parallelism)
        for name, st in self._snapshot_states():
            self._host.register(name, st.kind, np.dtype(jnp.dtype(st.dtype)),
                                st.ring)
        return self._host

    def _device_resident(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(keys, slots, key_groups) of every device-resident entry."""
        t = np.asarray(jax.device_get(self.table))
        occupied = t != np.int64(EMPTY_KEY)
        keys_dev = t[occupied]
        slots_dev = np.flatnonzero(occupied).astype(np.int32)
        g_dev = key_groups_for_hash_batch(hash_batch(keys_dev),
                                          self.max_parallelism)
        return keys_dev, slots_dev, g_dev

    def _absorb_and_rebuild(self, keys_dev: np.ndarray,
                            slots_dev: np.ndarray, sel: np.ndarray,
                            groups, cap: int) -> None:
        """Shared spill tail: move the selected device rows into the host
        tier, mark their groups spilled, rebuild the device table without
        them (used by LRU eviction AND the deferred-drain force-spill so
        the two paths cannot diverge)."""
        host = self._ensure_host_tier()
        if sel.any():
            values = {}
            for name, st in self._snapshot_states():
                arr = np.asarray(jax.device_get(st.array))
                values[name] = (arr[:, slots_dev[sel]] if st.ring
                                else arr[slots_dev[sel]])
            host.absorb(keys_dev[sel], values)
        host.spilled_mask[np.asarray(groups, np.int64)] = True
        if sel.any() or cap != self.capacity:
            self._rebuild_device(keys_dev[~sel], slots_dev[~sel], cap)
        self._sync_spilled_dev()

    def _force_spill_groups(self, groups: np.ndarray) -> None:
        """Page the given key groups to the host tier NOW (deferred-spill
        drain: a group touched by staging overflow becomes host-resident
        so no key is ever split across tiers). Same guarded demotion as
        `_evict_cold_groups`: the `tier.evict` fault site fires BEFORE
        anything moves, the move runs under the watchdog deadline, and
        the residency manager accounts the demotion."""
        groups = np.asarray(groups, np.int64)
        from ..runtime.faults import fire_with_retries
        from ..runtime.watchdog import WATCHDOG
        fire_with_retries("tier.evict", scope="tpu_backend.tier")
        WATCHDOG.run("tier.evict",
                     lambda: self._force_spill_groups_inner(groups),
                     scope="tpu_backend.tier")

    def _force_spill_groups_inner(self, groups: np.ndarray) -> None:
        from ..metrics.tracing import TRACER
        with TRACER.span("tier", "Evict") as sp:
            keys_dev, slots_dev, g_dev = self._device_resident()
            gmask = np.zeros(self.max_parallelism, bool)
            gmask[groups] = True
            sel = gmask[g_dev]
            self._absorb_and_rebuild(keys_dev, slots_dev, sel, groups,
                                     self.capacity)
            if self._residency is not None:
                self._residency.note_demoted(groups)
            DEVICE_STATS.note_tier_eviction(len(groups), int(sel.sum()))
            sp.set_attribute("groups", int(len(groups)))
            sp.set_attribute("keys", int(sel.sum()))
            sp.set_attribute("forced", True)

    def drain_staged(self, keys: np.ndarray, ring_idx: np.ndarray,
                     values: dict[str, np.ndarray]) -> None:
        """Fold rows the fused step staged for the host (spilled-group
        records + failed inserts) into the host tier. Groups seen here for
        the first time are force-spilled first, so their device rows merge
        before the fold and future records route host-side on device."""
        if len(keys) == 0:
            return
        keys = _sanitize_keys(np.asarray(keys))
        host = self._ensure_host_tier()
        groups = key_groups_for_hash_batch(hash_batch(keys),
                                           self.max_parallelism)
        fresh = np.unique(groups[~host.spilled_mask[groups]])
        if len(fresh):
            self._force_spill_groups(fresh)
        hslots = host.slots_for(keys)
        host.host_folds += 1
        for name, vals in values.items():
            st = self._array_states[name]
            host.fold(name, hslots, np.asarray(vals),
                      np.asarray(ring_idx) if st.ring else None)

    # ------------------------------------------------------------------
    # tiered residency (state/tiering/): promotion pipeline + boundary hook
    # ------------------------------------------------------------------
    @property
    def tiering_active(self) -> bool:
        return self._residency is not None

    @property
    def residency(self) -> Optional[ResidencyManager]:
        return self._residency

    @property
    def prefetch_pipeline(self) -> Optional[PrefetchPipeline]:
        return self._prefetch

    def _hbm_bytes_in_use(self) -> int:
        """Device bytes held by the keyed-state planes (table + every
        array state). Shape metadata only — never a device sync."""
        total = int(self.table.nbytes)
        for st in self._array_states.values():
            total += int(st.array.nbytes)
        return total

    def tier_boundary(self) -> bool:
        """Batch-boundary tiering step, called by the operator after the
        staged-spill drain (so nothing is in flight for any group):
        advance the decay cadence, queue promotion candidates on the
        prefetch pipeline, and apply at most one staged payload. Returns
        True when residency changed (a promotion landed) so the operator
        can invalidate derived window planes."""
        if self._residency is None:
            return False
        self._sync_touch_from_device()
        self._residency.on_boundary()
        changed = False
        host = self._host
        if host is not None and host.active and self._prefetch is not None:
            cands = self._residency.promotion_candidates(
                host.spilled_mask, host.group_counts(), self._num_keys,
                self.capacity)
            if len(cands):
                self._prefetch.request(cands)
            payload = self._prefetch.poll()
            if payload is not None:
                changed = self.apply_promotion(payload)
            self._residency.update_view(host.spilled_mask,
                                        host.group_counts())
        DEVICE_STATS.set_tier_hbm_bytes(self._hbm_bytes_in_use())
        return changed

    def _stage_promotion(self, groups: np.ndarray) -> Optional[dict]:
        """Gather ``groups``' warm rows and upload the staged device
        arrays (runs on the prefetch thread in async mode). The gather is
        read-only and versioned: apply_promotion re-validates against
        the host tier's mutation counter, so a payload raced by a
        concurrent fold is re-gathered, never applied stale. Keys pad to
        the next power of two (valid-masked) so the insert and scatters
        reuse a bounded set of executables — residency changes stay
        recompile-free."""
        host = self._host
        if host is None:
            return None
        version = host.version
        groups = np.asarray(groups, np.int64)
        groups = groups[host.spilled_mask[groups]]
        if len(groups) == 0:
            return None
        keys, vals = host.peek_groups(groups)
        n = len(keys)
        if n == 0:
            return None
        from ..ops.segment_ops import pow2_ceil
        P = pow2_ceil(max(n, 1))
        pkeys = np.zeros(P, np.int64)
        pkeys[:n] = keys
        valid = np.zeros(P, bool)
        valid[:n] = True
        dvals = {}
        for name, v in vals.items():
            pad = P - n
            if pad:
                v = np.concatenate(
                    [v, np.zeros(v.shape[:-1] + (pad,), v.dtype)], axis=-1)
            dvals[name] = jnp.asarray(v)
        return {"groups": groups, "version": version, "n": n,
                "dkeys": jnp.asarray(pkeys), "valid": jnp.asarray(valid),
                "values": dvals}

    def apply_promotion(self, payload: dict) -> bool:
        """Install a staged promotion at a batch boundary (mailbox
        thread): insert the keys into the device table at FIXED capacity,
        scatter the staged rows into every snapshot-state plane, then —
        only after the insert fully succeeded — drop the groups from the
        host tier and clear their spilled flags. Ordering guarantees a
        key is never split across (or lost between) tiers."""
        host = self._host
        groups = np.asarray(payload["groups"], np.int64)
        if host is None:
            return False
        if payload["version"] != host.version:
            # raced by a host-tier mutation since staging: re-gather
            # synchronously (small, boundary-amortized) and fall through
            payload = self._stage_promotion(groups)
            if payload is None:
                return False
        n = int(payload["n"])
        if self._num_keys + n > int(0.6 * self.capacity):
            self._prefetch.forget(groups)
            return False  # headroom gone since staging; stay warm
        new_table, slots, ok = lookup_or_insert(
            self.table, payload["dkeys"], payload["valid"])
        if not bool(jax.device_get((ok | ~payload["valid"]).all())):
            self._prefetch.forget(groups)
            return False  # table could not admit; discard, keys stay warm
        self.table = new_table
        self._num_keys += n
        widx = jnp.where(payload["valid"], slots, self.capacity)
        for name, st in self._snapshot_states():
            dv = payload["values"][name]
            if st.ring:
                st.array = st.array.at[:, widx].set(dv, mode="drop")
            else:
                st.array = st.array.at[widx].set(dv, mode="drop")
        host.drop_groups(groups)
        self._sync_spilled_dev()
        self.mark_dirty(slots)
        self._residency.note_promoted(groups)
        DEVICE_STATS.note_tier_prefetch(len(groups), n)
        return True

    def register_array_state(self, name: str, kind: str, dtype,
                             ring: Optional[int] = None,
                             role: str = "pane") -> None:
        if name not in self._array_states:
            self._array_states[name] = _ArrayState(name, kind, dtype, ring,
                                                   self.capacity, role)
            if self._host is not None and role != "window":
                self._host.register(name, kind,
                                    np.dtype(jnp.dtype(dtype)), ring)

    def has_array(self, name: str) -> bool:
        return name in self._array_states

    def drop_array_state(self, name: str) -> None:
        self._array_states.pop(name, None)

    def _snapshot_states(self):
        """(name, state) pairs that participate in snapshots/mirror/spill —
        everything except derived window-role planes."""
        return [(n, st) for n, st in self._array_states.items()
                if st.role != "window"]

    def get_array(self, name: str) -> jax.Array:
        return self._array_states[name].array

    def set_array(self, name: str, array: jax.Array) -> None:
        self._array_states[name].array = array

    def fold_batch(self, name: str, slots: jax.Array, values,
                   valid: jax.Array,
                   ring_idx=None) -> None:
        """acc[(ring_idx,) slot] op= values — one scatter per aggregate.
        ``values``/``ring_idx`` may be numpy (preferred when a spill tier
        is configured: the host-side rows of the batch fold into the host
        mirror without a device round-trip)."""
        st = self._array_states[name]
        dvals = values if isinstance(values, jax.Array) else \
            jnp.asarray(values)
        if st.ring:
            dring = (ring_idx if isinstance(ring_idx, jax.Array)
                     else jnp.asarray(ring_idx))
            cap = st.array.shape[1]
            idt = (jnp.int64 if st.ring * cap > (1 << 31) - 1
                   else jnp.int32)
            flat = dring.astype(idt) * cap + slots.astype(idt)
            folded = scatter_fold(st.kind, st.array.reshape(-1), flat,
                                  dvals, valid)
            st.array = folded.reshape(st.array.shape)
        else:
            st.array = scatter_fold(st.kind, st.array, slots, dvals, valid)
        if self._pending_host is not None:
            pos, hslots = self._pending_host
            vals_np = (np.asarray(jax.device_get(values))
                       if isinstance(values, jax.Array)
                       else np.asarray(values))
            ring_np = None
            if st.ring is not None and ring_idx is not None:
                ring_np = (np.asarray(jax.device_get(ring_idx))
                           if isinstance(ring_idx, jax.Array)
                           else np.asarray(ring_idx))[pos]
            self._host.fold(name, hslots, vals_np[pos], ring_np)

    def reset_ring_row(self, row: int) -> None:
        """Zero one ring row of every ring-shaped array state back to its
        aggregate identity — pane retirement for the window operators.
        ONE cached jitted program over all ring planes with the row as a
        traced scalar (eager per-plane .at[].set ran un-jitted: each call
        re-dispatched a full-plane scatter and dominated the whole fire
        stage — measured 7.7s of an 8.4s Q5@1M fire budget on CPU).
        The host knows the retired row, so the snapshot mirror replays it
        without marking anything dirty on device."""
        ring_states = [st for st in self._array_states.values()
                       if st.ring and st.role != "window"]
        if ring_states:
            sig = tuple((st.kind, str(st.array.dtype), st.array.shape)
                        for st in ring_states)
            outs = _reset_row_program(sig)(
                tuple(st.array for st in ring_states), np.int32(row))
            for st, arr in zip(ring_states, outs):
                st.array = arr
        self._retired_rows.add(int(row))
        if self._host is not None:
            self._host.reset_ring_row(row)

    def slots_for_batch_device(self, dkeys: jax.Array) -> jax.Array:
        """Deferred-mode hot path for keys ALREADY on device (one packed
        upload per batch; see DeviceWindowAggOperator._fold_packed): pure
        dispatch, no host sync, sentinel keys remapped on device."""
        if not self._defer:
            raise RuntimeError("device-resident slot resolution requires "
                               "defer_overflow mode")
        if self._hi is not None:
            slots = jnp.asarray(self.native_slots(
                np.asarray(jax.device_get(dkeys))))
            self.mark_dirty(slots)
            return slots
        dkeys = sanitize_keys_device(dkeys)
        self.table, slots, ok = lookup_or_insert(self.table, dkeys)
        self._dropped = self._dropped + jnp.sum(~ok).astype(jnp.int64)
        self.mark_dirty(slots)
        return slots

    # ------------------------------------------------------------------
    # deferred-mode health (device scalars; ride along with fire programs)
    # ------------------------------------------------------------------
    @property
    def dropped_device(self) -> jax.Array:
        return self._dropped

    def apply_health(self, dropped: int, occupancy: int) -> None:
        """Consume host-materialized health scalars (fetched in the same
        device_get as a fire's results): hard-error on any dropped insert,
        grow the table before the load factor bites — or, under an HBM
        budget, page cold key groups to the host tier instead."""
        if int(dropped) > 0:
            if self._budget:
                raise RuntimeError(
                    f"spill staging overflow: {int(dropped)} records could "
                    "not be staged for the host tier in one watermark "
                    "interval; raise spill_staging_slots or the HBM budget")
            raise RuntimeError(
                f"device hash table overflow: {int(dropped)} records "
                f"dropped (capacity {self.capacity}); raise "
                "state.backend.tpu.slots-per-key-group or disable "
                "deferred overflow checking")
        self._num_keys = int(occupancy)
        if self._hi is not None:
            return  # growth is handled inline by native_slots (pad, no remap)
        if self._num_keys > 0.6 * self.capacity:
            if not self._budget or 2 * self.capacity <= self._budget:
                self._rehash(self.capacity * 2)
            else:
                self._sync_touch_from_device()
                self._evict_cold_groups()

    def check_health(self) -> None:
        """Standalone (blocking) variant of apply_health."""
        d, occ = jax.device_get((self._dropped,
                                 (self.table != EMPTY_KEY).sum()))
        self.apply_health(int(d), int(occ))

    def conform_ring(self, ring: int, live_panes: Iterable[int]) -> None:
        """Re-seat ring-shaped array states restored under a DIFFERENT ring
        size onto ``ring`` rows: each live pane's row moves from
        (p % old_ring) to (p % ring); every other row is the aggregate
        identity (retired). No-op when sizes already match."""
        live = list(live_panes)
        for st in self._array_states.values():
            if not st.ring or st.ring == ring or st.role == "window":
                continue
            if len(live) > ring:
                raise RuntimeError(
                    f"cannot conform ring {st.ring} -> {ring}: "
                    f"{len(live)} panes are live; increase ring_size")
            old = st.array
            new = make_accumulator(st.kind, (ring, self.capacity), st.dtype)
            for p in live:
                new = new.at[p % ring].set(old[p % st.ring])
            st.array = new
            st.ring = ring
            self._invalidate_mirror()

    def occupied_mask(self) -> jax.Array:
        return self.table != EMPTY_KEY

    @property
    def num_keys(self) -> int:
        return self._num_keys

    # ------------------------------------------------------------------
    # typed row plane: per-key values of ANY numeric dtype with presence
    # bits and optional TTL, accessed in BATCHES (one lookup + one gather
    # or scatter per batch — the per-key State handles below wrap this).
    # ------------------------------------------------------------------
    def register_row_state(self, name: str, dtype,
                           ttl_ms: Optional[int] = None) -> None:
        """Value plane [capacity] of ``dtype`` + presence int8 plane
        (+ last-update int64 plane when a TTL is set: entries expire
        ttl_ms after last update, checked lazily at read — the relaxed
        cleanup of the reference's StateTtlConfig)."""
        if self._budget:
            raise NotImplementedError(
                "the typed row plane does not page to the host tier; "
                "configure this backend without hbm_budget_slots (the "
                "budget applies to the array/window plane)")
        if name in self._row_meta:
            return
        self._row_meta[name] = (int(ttl_ms or 0),
                                jnp.dtype(np.dtype(dtype)))
        self._ensure_row_planes(name)

    def _ensure_row_planes(self, name: str) -> None:
        """(Re-)materialize a row state's planes; a restore() rebuilds
        _array_states from the snapshot alone, so planes the snapshot
        lacked (e.g. the TTL clock of a job upgraded from no-TTL) come
        back here. A fresh TTL clock next to RESTORED presence fills with
        int64 max: existing entries never expire rather than all expiring
        at once."""
        ttl, dtype = self._row_meta[name]
        restored_presence = f"{name}.__set__" in self._array_states
        self.register_array_state(name, "sum", dtype)
        self.register_array_state(f"{name}.__set__", "sum", jnp.int8)
        if ttl and f"{name}.__ts__" not in self._array_states:
            self.register_array_state(f"{name}.__ts__", "sum", jnp.int64)
            if restored_presence:
                self.set_array(f"{name}.__ts__", jnp.full(
                    self.capacity, np.iinfo(np.int64).max, jnp.int64))

    def _row_planes(self, name: str):
        ttl, _dtype = self._row_meta[name]
        self._ensure_row_planes(name)
        last = self.get_array(f"{name}.__ts__") if ttl else None
        return (self.get_array(name), self.get_array(f"{name}.__set__"),
                last, ttl)

    def rows_upsert(self, name: str, keys: np.ndarray, values: np.ndarray,
                    now_ms=0) -> None:
        """Set values for a batch of keys (last occurrence wins for
        duplicate keys, deterministically). One slot resolution + one
        scatter program. ``now_ms`` may be a scalar or a per-row array
        (TTL clock)."""
        slots = self.slots_for_batch(np.asarray(keys))
        vals, present, last, ttl = self._row_planes(name)
        arrs = _rows_set(vals, present, last, slots,
                         jnp.asarray(np.asarray(values)),
                         jnp.asarray(np.asarray(now_ms, np.int64)))
        self.set_array(name, arrs[0])
        self.set_array(f"{name}.__set__", arrs[1])
        if last is not None:
            self.set_array(f"{name}.__ts__", arrs[2])

    def rows_lookup(self, name: str, keys: np.ndarray,
                    now_ms: int = 0) -> tuple[np.ndarray, np.ndarray]:
        """(values, present) for a batch of keys — absent, cleared, or
        TTL-expired keys report present=False. One lookup + one gather +
        one transfer."""
        vals, present, last, ttl = self._row_planes(name)
        if self._hi is not None:
            # the mirror table holds keys at DENSE slots, not probe slots:
            # resolve through the native index (read-only lookup)
            slots = self._hi.lookup(_sanitize_keys(np.asarray(keys)))
            v, p = _rows_get_slots(vals, present, last, jnp.asarray(slots),
                                   np.int64(now_ms), np.int64(ttl))
        else:
            v, p = _rows_get(self.table, vals, present, last,
                             jnp.asarray(_sanitize_keys(np.asarray(keys))),
                             np.int64(now_ms), np.int64(ttl))
        v, p = jax.device_get((v, p))
        return np.asarray(v), np.asarray(p)

    def rows_clear(self, name: str, keys: np.ndarray) -> None:
        vals, present, last, _ttl = self._row_planes(name)
        if self._hi is not None:
            nslots = self._hi.lookup(_sanitize_keys(np.asarray(keys)))
            new_present, slots = _rows_unset_slots(present,
                                                   jnp.asarray(nslots))
        else:
            new_present, slots = _rows_unset(
                self.table, present,
                jnp.asarray(_sanitize_keys(np.asarray(keys))))
        self.set_array(f"{name}.__set__", new_present)
        self.mark_dirty(slots)

    def dedup_first_batch(self, name: str, keys: np.ndarray,
                          ts: np.ndarray,
                          valid: Optional[np.ndarray] = None) -> np.ndarray:
        """Keep-first admission for a batch: returns a bool mask of the
        rows seen for the FIRST time (within the batch, against state, and
        — under a TTL — since expiry). The whole batch is one fused
        program; overflow grows the table and retries (sync-mode
        semantics)."""
        if name not in self._row_meta:
            raise RuntimeError(f"row state {name!r} not registered")
        keys = _sanitize_keys(np.asarray(keys))
        dvalid = (jnp.asarray(np.asarray(valid, bool)) if valid is not None
                  else jnp.ones(len(keys), bool))
        dts = jnp.asarray(np.asarray(ts, np.int64))
        if self._hi is not None:
            # invalid (e.g. retraction) rows must not claim slots — the
            # XLA path threads `valid` through lookup_or_insert; here
            # only valid rows reach the native upsert
            valid_np = (np.asarray(valid, bool) if valid is not None
                        else np.ones(len(keys), bool))
            slots = np.full(len(keys), -1, np.int32)
            if valid_np.any():
                slots[valid_np] = self.native_slots(keys[valid_np])
            _vals, present, last, ttl = self._row_planes(name)
            new_present, new_last, fresh, sc = _dedup_first_slots(
                present, last, jnp.asarray(slots), dvalid, dts,
                np.int64(ttl))
            self.set_array(f"{name}.__set__", new_present)
            if new_last is not None:
                self.set_array(f"{name}.__ts__", new_last)
            self.mark_dirty(sc)
            return np.asarray(jax.device_get(fresh))
        while True:
            _vals, present, last, ttl = self._row_planes(name)
            table, new_present, new_last, fresh, slots, overflow, occ = \
                _dedup_first(self.table, present, last, jnp.asarray(keys),
                             dvalid, dts, np.int64(ttl))
            fresh_h, overflow_h, occ_h = jax.device_get(
                (fresh, overflow, occ))
            if bool(overflow_h):
                self._rehash(self.capacity * 2)
                continue
            self.table = table
            self.set_array(f"{name}.__set__", new_present)
            if new_last is not None:
                self.set_array(f"{name}.__ts__", new_last)
            self.mark_dirty(slots)
            self._num_keys = int(occ_h)
            if self._num_keys > 0.6 * self.capacity:
                self._rehash(self.capacity * 2)
            return np.asarray(fresh_h)

    # ------------------------------------------------------------------
    # row-access compatibility plane (slow; host roundtrip per call)
    # ------------------------------------------------------------------
    def get_partitioned_state(self, descriptor: StateDescriptor) -> State:
        if descriptor.kind != "value":
            raise NotImplementedError(
                "TPU backend row plane supports ValueState only; use array "
                "states (device operators), the device list plane "
                "(state/device_lists.py), or the hashmap backend")
        handle = self._row_states.get(descriptor.name)
        if handle is None:
            default = descriptor.default
            # float64 unless the user EXPLICITLY typed the default with a
            # numpy integer (a plain python-int default must not make
            # later float updates truncate)
            if isinstance(default, (np.integer, np.ndarray)) and \
                    np.asarray(default).dtype.kind in "iu":
                dtype = np.asarray(default).dtype
            else:
                dtype = np.float64
            ttl_ms = (int(descriptor.ttl.ttl * 1000)
                      if descriptor.ttl is not None else None)
            self.register_row_state(descriptor.name, dtype, ttl_ms)
            handle = _TpuValueState(self, descriptor)
            self._row_states[descriptor.name] = handle
        return handle

    def keys(self, state_name: str, namespace=None) -> Iterable[Any]:
        t = jax.device_get(self.table)
        return t[t != EMPTY_KEY].tolist()

    def namespaces(self, state_name: str) -> Iterable[Any]:
        return [None]

    # ------------------------------------------------------------------
    # checkpointing
    # ------------------------------------------------------------------
    def snapshot(self, checkpoint_id: int) -> dict:
        # delta capture: only dirty blocks cross the device boundary; the
        # snapshot itself is assembled from the host mirror
        self._sync_mirror()
        t = self._mirror["table"]
        occupied = t != EMPTY_KEY
        keys = t[occupied]
        slots = np.flatnonzero(occupied)
        # same hash as record routing (hash_batch), so restored keys filter
        # into exactly the key-group ranges the exchange routes them to
        groups = key_groups_for_hash_batch(hash_batch(keys),
                                           self.max_parallelism)
        host_keys = host_vals = None
        if self._host is not None and len(self._host.index):
            host_keys, host_vals = self._host.snapshot_parts()
            keys = np.concatenate([keys, host_keys])
            groups = np.concatenate([groups, key_groups_for_hash_batch(
                hash_batch(host_keys), self.max_parallelism)])
        # canonical (group, key) order: the snapshot is residency-AGNOSTIC
        # — byte-identical whether a key group is device-hot or host-warm
        # (raw order would leak slot/eviction history into the artifact)
        order = np.lexsort((keys, groups))
        keys = np.ascontiguousarray(keys[order])
        groups = np.ascontiguousarray(groups[order])
        states = {}
        for name, st in self._snapshot_states():
            arr = self._mirror["arrays"][name]
            vals = arr[:, slots] if st.ring else arr[slots]
            if host_vals is not None:
                vals = np.concatenate(
                    [vals, host_vals[name].astype(vals.dtype)], axis=-1)
            vals = np.ascontiguousarray(vals[..., order])
            states[name] = {"kind": st.kind, "dtype": str(np.dtype(st.dtype)),
                            "ring": st.ring, "values": vals}
        return {"kind": "tpu", "keys": keys, "key_groups": groups,
                "max_parallelism": self.max_parallelism, "states": states}

    def restore(self, snapshots: Iterable[dict]) -> None:
        """Deadline-bounded (fault site transfer.h2d; the deadline is the
        CHECKPOINT timeout — a restore is a bulk state rebuild, not a
        per-batch transfer — and there is no in-place retry: the rebuild
        mutates self in stages, so a stalled restore upload raises
        StallError into the restart path rather than freezing recovery
        mid-rebuild)."""
        from ..runtime.watchdog import WATCHDOG

        if self._prefetch is not None:
            # restart/restore boundary: in-flight promotion stagings were
            # gathered against pre-restore state — cancel, never apply
            self._prefetch.cancel()
        snapshots = list(snapshots)
        WATCHDOG.run("transfer.h2d",
                     lambda: self._restore_inner(snapshots),
                     scope="tpu_backend.restore",
                     deadline=WATCHDOG.deadline_for("checkpoint.load"))

    def _restore_inner(self, snapshots: Iterable[dict]) -> None:
        all_keys, per_state_vals = [], {}
        state_meta: dict[str, dict] = {}
        for snap in snapshots:
            groups = np.asarray(snap["key_groups"])
            sel = np.array([g in self.key_group_range for g in groups],
                           dtype=bool)
            keys = np.asarray(snap["keys"])[sel]
            all_keys.append(keys)
            for name, sdata in snap["states"].items():
                state_meta[name] = sdata
                vals = np.asarray(sdata["values"])
                vals = vals[:, sel] if sdata["ring"] else vals[sel]
                per_state_vals.setdefault(name, []).append(vals)
        keys = (np.concatenate(all_keys) if all_keys
                else np.empty(0, np.int64))
        from ..runtime.faults import fire_with_retries
        fire_with_retries("transfer.h2d", scope="tpu_backend.restore")
        while self.capacity < 2 * max(len(keys), 1):
            self.capacity *= 2  # may exceed the budget; evicted back below
        self.table = make_table(self.capacity)
        self._num_keys = len(keys)
        if self._hi is not None:
            # fresh native index; restored keys get dense slots and the
            # table mirror is rebuilt from them
            from .. import native as _native
            self._hi = _native.HostHashIndex(self.capacity)
            if len(keys):
                skeys = _sanitize_keys(keys)
                nslots = self._hi.upsert(skeys)
                slots = jnp.asarray(nslots)
                self.table = self.table.at[slots].set(jnp.asarray(skeys))
            else:
                slots = jnp.zeros(0, jnp.int32)
        elif len(keys):
            self.table, slots, ok = lookup_or_insert(self.table,
                                                     jnp.asarray(keys))
            assert bool(jax.device_get(ok.all()))
        else:
            slots = jnp.zeros(0, jnp.int32)
        self._array_states.clear()
        for name, meta in state_meta.items():
            dtype = jnp.dtype(meta["dtype"])
            st = _ArrayState(name, meta["kind"], dtype, meta["ring"],
                             self.capacity)
            if len(keys):
                vals = (np.concatenate(per_state_vals[name], axis=-1))
                if meta["ring"]:
                    st.array = st.array.at[:, slots].set(jnp.asarray(vals))
                else:
                    st.array = st.array.at[slots].set(jnp.asarray(vals))
            self._array_states[name] = st
        # restored state may exceed the HBM budget: page the overflow out
        # immediately (fresh LRU; group order decides coldness)
        self._host = None
        self._spilled_dev = None
        self._touch_dev = None
        self._invalidate_mirror()
        if self._budget and self.capacity > self._budget:
            self._evict_cold_groups(rebuild_capacity=self._budget)


class _TpuValueState(ValueState):
    """Row plane per-key API handle over the typed batched plane below
    (API completeness; each call is a host round-trip — batched access via
    ``rows_lookup``/``rows_upsert`` and the array plane are the hot
    paths)."""

    def __init__(self, backend: TpuKeyedStateBackend, desc: StateDescriptor):
        self._b, self._d = backend, desc

    def value(self):
        key = np.asarray([self._b._current_key], np.int64)
        vals, present = self._b.rows_lookup(
            self._d.name, key, now_ms=int(time.time() * 1000))
        if not present[0]:
            return self._d.default
        v = vals[0]
        return v.item() if isinstance(v, np.generic) else v

    def update(self, value) -> None:
        key = np.asarray([self._b._current_key], np.int64)
        self._b.rows_upsert(self._d.name, key, np.asarray([value]),
                            now_ms=int(time.time() * 1000))

    def clear(self) -> None:
        key = np.asarray([self._b._current_key], np.int64)
        self._b.rows_clear(self._d.name, key)


register_backend("tpu", TpuKeyedStateBackend)
