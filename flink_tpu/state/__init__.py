"""State backends: SPI + host heap backend + device-resident TPU backend.

Maps the reference's state layer (SURVEY.md §2.4 state backends, §2.8 FRocksDB).
"""

from .backend import (  # noqa: F401
    VOID_NAMESPACE, AggregatingState, KeyedStateBackend, ListState, MapState,
    OperatorStateBackend, ReducingState, State, ValueState, create_backend,
    register_backend,
)
from .descriptors import (  # noqa: F401
    AggregatingStateDescriptor, ListStateDescriptor, MapStateDescriptor,
    ReducingStateDescriptor, StateDescriptor, StateTtlConfig,
    ValueStateDescriptor,
)
from .heap import HeapKeyedStateBackend  # noqa: F401
from .changelog import ChangelogKeyedStateBackend  # noqa: F401
