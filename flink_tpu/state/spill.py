"""Host-RAM spill tier for device keyed state.

The RocksDB-replacement risk item (SURVEY.md §7): keyed state larger than
the HBM budget pages out of the device. Where the reference pushes every
access through an LSM tree (RocksDBKeyedStateBackend.java:114), this tier
keeps the device hash table + accumulator arrays as the HOT set and moves
whole COLD KEY GROUPS to host RAM: a native open-addressing index
(native/HostHashIndex, the C++ layer built for exactly this) maps spilled
keys to dense slots in numpy mirror arrays, and every operation stays
batched — a record batch is split by key group into a device scatter-fold
and a vectorized numpy fold (np.add.at / minimum.at / maximum.at), never a
per-record loop. Fires merge pane rows from both tiers.

Eviction is LRU at key-group granularity (the reference's unit of state
movement, KeyGroupRangeAssignment.java:63): when the device table can no
longer grow within the budget, the coldest groups' keys and accumulator
rows are pulled to host in one DMA and the device table is rebuilt
without them.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

import numpy as np

from ..core.keygroups import hash_batch, key_groups_for_hash_batch
from ..native import HostHashIndex

__all__ = ["HostTier", "HOST_IDENT"]


def _ident(kind: str, dtype: np.dtype):
    if kind in ("sum", "count"):
        return dtype.type(0)
    if kind == "min":
        return (np.finfo(dtype).max if np.issubdtype(dtype, np.floating)
                else np.iinfo(dtype).max)
    return (np.finfo(dtype).min if np.issubdtype(dtype, np.floating)
            else np.iinfo(dtype).min)


HOST_IDENT = _ident

_FOLDS = {
    "sum": np.add.at,
    "count": np.add.at,
    "min": np.minimum.at,
    "max": np.maximum.at,
}

_MERGES = {
    "sum": lambda v: v.sum(axis=0),
    "count": lambda v: v.sum(axis=0),
    "min": lambda v: v.min(axis=0),
    "max": lambda v: v.max(axis=0),
}


class _HostArray:
    __slots__ = ("kind", "dtype", "ring", "array")

    def __init__(self, kind: str, dtype, ring: Optional[int], cap: int):
        self.kind = kind
        self.dtype = np.dtype(dtype)
        self.ring = ring
        shape = (ring, cap) if ring else (cap,)
        self.array = np.full(shape, _ident(kind, self.dtype), self.dtype)

    def grow(self, cap: int) -> None:
        old = self.array
        shape = (self.ring, cap) if self.ring else (cap,)
        self.array = np.full(shape, _ident(self.kind, self.dtype),
                             self.dtype)
        if self.ring:
            self.array[:, :old.shape[1]] = old
        else:
            self.array[:old.shape[0]] = old


class HostTier:
    """Spilled key groups: key index + accumulator mirrors + LRU stats."""

    def __init__(self, max_parallelism: int):
        self.max_parallelism = max_parallelism
        self.index = HostHashIndex(1 << 12)
        self.cap = 1 << 12
        self.arrays: dict[str, _HostArray] = {}
        # True where the key group lives on host
        self.spilled_mask = np.zeros(max_parallelism, bool)
        self.evicted_keys = 0      # cumulative keys moved HBM -> host
        self.promoted_keys = 0     # cumulative keys moved host -> HBM
        self.host_folds = 0        # batches (partially) folded on host
        # Monotone mutation counter: the prefetch pipeline stages gathers
        # on a background thread and validates against this at apply time,
        # so a payload raced by a concurrent fold/absorb is discarded (or
        # re-gathered synchronously) instead of applied stale.
        self.version = 0
        # Guards mutation vs the prefetch thread's multi-read gather: the
        # version check makes a raced payload harmless, but peek_groups
        # reads the index and the shadow list at different times and a
        # fold landing in between tears the gather (mismatched lengths).
        # RLock because absorb -> slots_for nests.
        self._mtx = threading.RLock()

    @property
    def active(self) -> bool:
        return bool(self.spilled_mask.any())

    def register(self, name: str, kind: str, dtype,
                 ring: Optional[int]) -> None:
        if name not in self.arrays:
            self.arrays[name] = _HostArray(kind, dtype, ring, self.cap)

    def _ensure(self, n: int) -> None:
        while self.cap < n:
            self.cap *= 2
        for a in self.arrays.values():
            if (a.array.shape[-1]) < self.cap:
                a.grow(self.cap)

    def slots_for(self, keys: np.ndarray) -> np.ndarray:
        """Upsert spilled-side keys -> dense host slots."""
        with self._mtx:
            self.version += 1
            slots = self.index.upsert(keys)
            self._ensure(len(self.index) + 1)
            self.record_new_keys(keys, slots)
            return slots

    def absorb(self, keys: np.ndarray,
               values: dict[str, np.ndarray]) -> None:
        """Fold evicted device rows into the host tier (values[name]:
        [ring?, n] rows aligned with keys)."""
        if len(keys) == 0:
            return
        with self._mtx:
            slots = self.slots_for(keys)
            for name, vals in values.items():
                a = self.arrays[name]
                if a.ring:
                    _FOLDS[a.kind](a.array, (slice(None), slots), vals)
                else:
                    _FOLDS[a.kind](a.array, slots, vals)
            self.evicted_keys += len(keys)

    def fold(self, name: str, slots: np.ndarray, values: np.ndarray,
             ring_idx: Optional[np.ndarray]) -> None:
        with self._mtx:
            self.version += 1
            a = self.arrays[name]
            if a.ring:
                _FOLDS[a.kind](a.array, (ring_idx, slots),
                               values.astype(a.dtype, copy=False))
            else:
                _FOLDS[a.kind](a.array, slots,
                               values.astype(a.dtype, copy=False))

    def keys(self) -> np.ndarray:
        """All spilled keys, in dense-slot order (shadow list: the index
        only maps key -> slot)."""
        return self._shadow[:len(self.index)]

    # -- shadow key list (dense-slot order) -----------------------------
    # HostHashIndex gives key -> slot; fires and snapshots need slot ->
    # key, so mirror inserted keys in insertion order.
    @property
    def _shadow(self) -> np.ndarray:
        if not hasattr(self, "_shadow_arr"):
            self._shadow_arr = np.empty(0, np.int64)
        return self._shadow_arr

    def record_new_keys(self, keys: np.ndarray, slots: np.ndarray) -> None:
        """Track insertion-ordered keys for slot->key reverse lookup."""
        n = len(self.index)
        cur = self._shadow
        if len(cur) < n:
            new = np.empty(n, np.int64)
            new[:len(cur)] = cur
            fresh = slots >= len(cur)
            new[slots[fresh]] = keys[fresh]
            self._shadow_arr = new

    def fire(self, name: str, pane_rows: np.ndarray) -> np.ndarray:
        """Merge the given ring rows -> per-key window results
        [n_spilled_keys]."""
        a = self.arrays[name]
        n = len(self.index)
        if a.ring is None:
            return a.array[:n].copy()
        return _MERGES[a.kind](a.array[pane_rows][:, :n])

    def reset_ring_row(self, row: int) -> None:
        with self._mtx:
            self.version += 1
            for a in self.arrays.values():
                if a.ring:
                    a.array[row] = _ident(a.kind, a.dtype)

    # -- promotion support (warm -> hot paging) -------------------------
    def key_groups(self) -> np.ndarray:
        """Key group of every spilled key, in dense-slot order."""
        return key_groups_for_hash_batch(hash_batch(self.keys()),
                                         self.max_parallelism)

    def group_counts(self) -> np.ndarray:
        """Spilled-key histogram over key groups [max_parallelism]."""
        return np.bincount(self.key_groups(),
                           minlength=self.max_parallelism)

    def peek_groups(self, groups: np.ndarray
                    ) -> tuple[np.ndarray, dict[str, np.ndarray]]:
        """Read-only gather of ``groups``' keys and accumulator rows.

        Does NOT remove anything: promotion inserts on device first and
        only then calls :meth:`drop_groups`, so a failed insert can never
        strand keys between tiers.  Safe to call from the prefetch thread;
        the caller validates ``version`` before applying the result.
        """
        sel = np.zeros(self.max_parallelism, bool)
        sel[np.asarray(groups, np.int64)] = True
        with self._mtx:
            pick = sel[self.key_groups()]
            keys = self.keys()[pick].copy()
            vals = {}
            n = len(self.index)
            for name, a in self.arrays.items():
                vals[name] = (a.array[:, :n][:, pick].copy() if a.ring
                              else a.array[:n][pick].copy())
        return keys, vals

    def drop_groups(self, groups: np.ndarray) -> int:
        """Remove ``groups`` from the tier, rebuilding the dense index.

        HostHashIndex has no delete, so the surviving keys re-upsert into
        a fresh index (dense slots in insertion order) and the arrays are
        compacted to match.  Returns how many keys were dropped.
        """
        with self._mtx:
            return self._drop_groups_locked(groups)

    def _drop_groups_locked(self, groups: np.ndarray) -> int:
        self.version += 1
        groups = np.asarray(groups, np.int64)
        sel = np.zeros(self.max_parallelism, bool)
        sel[groups] = True
        pick = sel[self.key_groups()]
        dropped = int(pick.sum())
        if dropped:
            keep_keys = self.keys()[~pick]
            n = len(self.index)
            keep_vals = {
                name: (a.array[:, :n][:, ~pick] if a.ring
                       else a.array[:n][~pick])
                for name, a in self.arrays.items()}
            self.index = HostHashIndex(self.cap)
            self._shadow_arr = np.empty(0, np.int64)
            for a in self.arrays.values():
                shape = ((a.ring, self.cap) if a.ring else (self.cap,))
                a.array = np.full(shape, _ident(a.kind, a.dtype), a.dtype)
            if len(keep_keys):
                slots = self.index.upsert(keep_keys)
                self.record_new_keys(keep_keys, slots)
                for name, a in self.arrays.items():
                    if a.ring:
                        a.array[:, slots] = keep_vals[name]
                    else:
                        a.array[slots] = keep_vals[name]
            self.promoted_keys += dropped
        self.spilled_mask[groups] = False
        return dropped

    def snapshot_parts(self) -> tuple[np.ndarray, dict[str, np.ndarray]]:
        """(keys, {name: [ring?, n] values}) for checkpointing."""
        n = len(self.index)
        keys = self._shadow[:n]
        vals = {}
        for name, a in self.arrays.items():
            vals[name] = (a.array[:, :n].copy() if a.ring
                          else a.array[:n].copy())
        return keys, vals
