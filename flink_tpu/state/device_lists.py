"""Device list plane: per-key bounded row lists resident in HBM.

The ListState analog for device execution (reference surface:
flink-runtime state/KeyedStateBackend.java:35 ListState / the interval
join's per-key row buffers in table-runtime operators/join/interval/).
Instead of an LSM-backed list per key, every key owns L fixed slots in a
dense [capacity, L, C] int64 block (numeric columns bit-packed: floats
ride as their int64 bit patterns), addressed by the same open-addressing
device hash table as the keyed backend. Every operation is ONE compiled
batch program:

* ``append_batch``  — slot resolution + in-batch rank (duplicate keys get
  sequential positions deterministically) + one scatter;
* ``probe_batch``   — lookup + gather of [B, L, C] candidate rows +
  counts, one transfer; the caller masks (e.g. by a time window) on host;
* ``prune``         — per-key compaction keeping rows with ts >= horizon
  (one argsort-gather over the whole block — the watermark cleanup of the
  reference's interval join).

Snapshots are key-group partitioned ({keys, key_groups, rows, counts}) so
lists re-shard across parallelism changes exactly like keyed state.
List overflow (a key exceeding L live rows) fails loudly — size L for the
retention window (watermark pruning bounds live rows).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.keygroups import KeyGroupRange, hash_batch, \
    key_groups_for_hash_batch
from ..ops.hash_table import EMPTY_KEY, ensure_x64, lookup, \
    lookup_or_insert, make_table, sanitize_keys_device

__all__ = ["DeviceListStore"]


@partial(jax.jit, donate_argnums=(0, 1, 2))
def _append_prog(table, rows, counts, keys, packed, n_valid):
    """Append one packed row per key; duplicate keys within the batch take
    consecutive positions (stable in-batch order). Rows at/after
    ``n_valid`` are power-of-two padding (constant shapes keep one
    executable across variable batch lengths) and write nothing."""
    B = keys.shape[0]
    cap, L, _C = rows.shape
    valid = jnp.arange(B) < n_valid
    keys = sanitize_keys_device(keys)
    table, slots, ok = lookup_or_insert(table, keys, valid)
    # rank of i among VALID batch rows sharing its slot (stable); invalid
    # rows sort to the virtual slot `cap` so they never claim positions
    rslot = jnp.where(ok, slots, cap).astype(jnp.int32)
    order = jnp.argsort(rslot, stable=True)
    ss = rslot[order]
    first = jnp.searchsorted(ss, ss, side="left")
    rank_sorted = jnp.arange(B, dtype=jnp.int32) - first.astype(jnp.int32)
    rank = jnp.zeros(B, jnp.int32).at[order].set(rank_sorted)
    sc = jnp.maximum(slots, 0)
    pos = counts[sc] + rank
    can = ok & (pos < L)
    flat = jnp.where(can, sc * L + pos, cap * L).astype(jnp.int64)
    rows = rows.reshape(cap * L, -1).at[flat].set(
        packed, mode="drop").reshape(cap, L, -1)
    counts = counts.at[jnp.where(can, sc, cap)].add(1, mode="drop")
    list_full = jnp.any(ok & (pos >= L))
    insert_failed = jnp.any(valid & ~ok)
    occ = (table != jnp.int64(EMPTY_KEY)).sum()
    failed_rows = valid & ~ok
    return table, rows, counts, list_full, insert_failed, occ, failed_rows


@jax.jit
def _probe_slots(table, counts, keys):
    keys = sanitize_keys_device(keys)
    slots = lookup(table, keys)
    found = slots >= 0
    sc = jnp.maximum(slots, 0)
    return sc, jnp.where(found, counts[sc], 0)


@partial(jax.jit, static_argnames=("l_eff",))
def _probe_gather(rows, sc, l_eff):
    return rows[sc, :l_eff, :]


@partial(jax.jit, donate_argnums=(1, 2))
def _prune_prog(table, rows, counts, horizon, ts_col):
    """Compact every key's list to rows with ts >= horizon (ts stored in
    column ``ts_col`` of the packed block). Also reports occupancy and
    how many occupied keys are now EMPTY — the caller compacts the hash
    table when dead keys dominate (open addressing cannot delete in
    place; the host twin deletes per watermark)."""
    cap, L, C = rows.shape
    live = jnp.arange(L)[None, :] < counts[:, None]         # [cap, L]
    keep = live & (rows[:, :, ts_col] >= horizon)
    # stable permutation putting kept rows first per key
    perm = jnp.argsort(~keep, axis=1, stable=True)           # [cap, L]
    rows = jnp.take_along_axis(rows, perm[:, :, None], axis=1)
    counts = keep.sum(axis=1).astype(counts.dtype)
    occupied = table != jnp.int64(EMPTY_KEY)
    dead = occupied & (counts == 0)
    return rows, counts, occupied.sum(), dead.sum()


class DeviceListStore:
    """Bounded per-key row lists on device (see module docstring).

    ``col_dtypes``: numpy dtypes of the payload columns. Column 0 of the
    packed block is always the row's event timestamp (int64)."""

    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int,
                 col_dtypes: Sequence[np.dtype], capacity: int = 1 << 12,
                 rows_per_key: int = 256):
        ensure_x64()
        cap = 1
        while cap < capacity:
            cap <<= 1
        self.key_group_range = key_group_range
        self.max_parallelism = max_parallelism
        self.capacity = cap
        self.L = int(rows_per_key)
        self.col_dtypes = [np.dtype(d) for d in col_dtypes]
        for d in self.col_dtypes:
            if d.kind not in "iufb":
                raise TypeError(
                    f"device list columns must be numeric/bool; got {d}")
        self.C = 1 + len(self.col_dtypes)    # ts + payload columns
        self.table = make_table(cap)
        self.rows = jnp.zeros((cap, self.L, self.C), jnp.int64)
        self.counts = jnp.zeros(cap, jnp.int32)
        self._occ = 0   # host-tracked occupancy (insert-only table)
        # lower bound on the oldest live row's ts: prune() is a whole-
        # block permutation, skipped when it provably cannot drop a row
        self._min_ts: Optional[int] = None

    # -- packing -------------------------------------------------------
    def _pack(self, ts: np.ndarray, cols: Sequence[np.ndarray]) -> np.ndarray:
        out = [np.asarray(ts, np.int64)]
        for c, d in zip(cols, self.col_dtypes):
            c = np.asarray(c)
            if d.kind == "f":
                out.append(np.ascontiguousarray(
                    c.astype(np.float64)).view(np.int64))
            else:
                out.append(c.astype(np.int64))
        return np.stack(out, axis=1)         # [B, C]

    def _unpack_col(self, packed: np.ndarray, i: int) -> np.ndarray:
        """packed[..., 1 + i] back to the column's dtype."""
        raw = packed[..., 1 + i]
        d = self.col_dtypes[i]
        if d.kind == "f":
            return raw.view(np.float64).astype(d)
        if d.kind == "b":
            return raw.astype(bool)
        return raw.astype(d)

    # -- operations ----------------------------------------------------
    def append_batch(self, keys: np.ndarray, ts: np.ndarray,
                     cols: Sequence[np.ndarray]) -> None:
        from ..ops.segment_ops import pow2_ceil

        n = len(keys)
        if n == 0:
            return
        P = pow2_ceil(n)
        packed_np = self._pack(ts, cols)
        keys_np = np.asarray(keys, np.int64)
        if P != n:   # constant shapes: one executable per pow2 bucket
            packed_np = np.concatenate(
                [packed_np, np.zeros((P - n, self.C), np.int64)])
            keys_np = np.concatenate(
                [keys_np, np.zeros(P - n, np.int64)])
        tmin = int(np.min(ts)) if len(ts) else None
        if tmin is not None:
            self._min_ts = (tmin if self._min_ts is None
                            else min(self._min_ts, tmin))
        # pre-grow: the append program donates its state buffers (the
        # [cap, L, C] block would otherwise be COPIED per batch — 100s of
        # MB), so a failed insert cannot retry against the original
        # state; growing while the worst case (every key new) still fits
        # under the load threshold keeps inserts infallible instead
        while self._occ + n > 0.6 * self.capacity:
            self._rehash(self.capacity * 2)
        packed = jnp.asarray(packed_np)
        dkeys = jnp.asarray(keys_np)
        table, rows, counts, list_full, insert_failed, occ, failed_rows = \
            _append_prog(self.table, self.rows, self.counts, dkeys,
                         packed, np.int64(n))
        self.table, self.rows, self.counts = table, rows, counts
        full_h, failed_h, occ_h = jax.device_get(
            (list_full, insert_failed, occ))
        self._occ = int(occ_h)
        if bool(full_h):
            raise RuntimeError(
                f"device list overflow: a key exceeded {self.L} live "
                "rows; raise rows_per_key or tighten the retention "
                "window")
        if bool(failed_h):
            # probe-cluster longer than the bounded walk (possible below
            # the load threshold with adversarial key hashes): the batch
            # rows that DID insert are already applied, so grow the table
            # and retry only the failed subset — the mask stays on device
            # unless this rare path runs
            sel = np.flatnonzero(np.asarray(jax.device_get(failed_rows)))
            sel = sel[sel < n]
            self._rehash(self.capacity * 2)
            self.append_batch(keys_np[sel], np.asarray(ts, np.int64)[sel]
                              if len(ts) else np.zeros(0, np.int64),
                              [np.asarray(c)[sel] for c in cols])
        return

    def probe_batch(self, keys: np.ndarray
                    ) -> tuple[np.ndarray, np.ndarray]:
        """(packed rows [B, L_eff, C], counts [B]) for a batch of keys.
        L_eff is the batch's max list length rounded up to a power of two
        (one cached gather program per bucket) — probing keys whose lists
        hold a handful of rows must not gather-and-transfer the full
        [B, rows_per_key, C] block (measured 134 MB/batch in the Q7 join
        at rows_per_key=256 when live lists held <= 4 rows). Mask
        positions >= counts[b] yourself."""
        from ..ops.segment_ops import pow2_ceil

        n = len(keys)
        if n == 0:
            return np.zeros((0, 0, self.C), np.int64), \
                np.zeros(0, np.int32)
        P = pow2_ceil(n)
        keys_np = np.asarray(keys, np.int64)
        if P != n:   # constant shapes (see append_batch)
            keys_np = np.concatenate([keys_np, np.zeros(P - n, np.int64)])
        sc, cnt = _probe_slots(self.table, self.counts,
                               jnp.asarray(keys_np))
        counts = np.asarray(jax.device_get(cnt))[:n]
        mx = int(counts.max()) if len(counts) else 0
        if mx == 0:
            return np.zeros((n, 0, self.C), np.int64), counts
        l_eff = min(pow2_ceil(mx), self.L)
        rows = jax.device_get(_probe_gather(self.rows, sc, l_eff))
        return np.asarray(rows)[:n], counts

    def prune(self, horizon: int) -> None:
        """Drop every row with ts < horizon (watermark cleanup) — one
        device compaction. When dead keys (occupied slots whose lists
        emptied) dominate, the hash table is rebuilt without them so an
        unbounded key domain cannot grow HBM without bound (the host
        plane's per-watermark `del kmap[key]`)."""
        if self._min_ts is not None and self._min_ts >= horizon:
            return      # provably nothing to drop: skip the permutation
        self.rows, self.counts, occ, dead = _prune_prog(
            self.table, self.rows, self.counts, np.int64(horizon), 0)
        self._min_ts = int(horizon)
        occ_h, dead_h = jax.device_get((occ, dead))
        if int(dead_h) > 64 and int(dead_h) * 2 > int(occ_h):
            t = np.asarray(jax.device_get(self.table))
            counts = np.asarray(jax.device_get(self.counts))
            alive = (t != np.int64(EMPTY_KEY)) & (counts > 0)
            slots = np.flatnonzero(alive)
            self._load(t[slots],
                       np.asarray(jax.device_get(self.rows))[slots],
                       counts[slots])

    def _rehash(self, new_capacity: int) -> None:
        t = np.asarray(jax.device_get(self.table))
        occupied = t != np.int64(EMPTY_KEY)
        keys = t[occupied]
        slots = np.flatnonzero(occupied)
        rows = np.asarray(jax.device_get(self.rows))[slots]
        counts = np.asarray(jax.device_get(self.counts))[slots]
        self.capacity = new_capacity
        self._load(keys, rows, counts)

    def _load(self, keys: np.ndarray, rows: np.ndarray,
              counts: np.ndarray) -> None:
        self.table = make_table(self.capacity)
        self.rows = jnp.zeros((self.capacity, self.L, self.C), jnp.int64)
        self.counts = jnp.zeros(self.capacity, jnp.int32)
        self._occ = len(keys)
        if len(keys) == 0:
            return
        self.table, slots, ok = lookup_or_insert(
            self.table, jnp.asarray(np.asarray(keys, np.int64)))
        if not bool(jax.device_get(ok.all())):  # pragma: no cover
            raise RuntimeError("device list rehash overflow")
        self.rows = self.rows.at[slots].set(jnp.asarray(rows))
        self.counts = self.counts.at[slots].set(
            jnp.asarray(counts, jnp.int32))

    # -- checkpointing -------------------------------------------------
    def snapshot(self) -> dict:
        t = np.asarray(jax.device_get(self.table))
        occupied = t != np.int64(EMPTY_KEY)
        keys = t[occupied]
        slots = np.flatnonzero(occupied)
        groups = key_groups_for_hash_batch(hash_batch(keys),
                                           self.max_parallelism)
        return {"kind": "tpu-list", "keys": keys, "key_groups": groups,
                "rows": np.asarray(jax.device_get(self.rows))[slots],
                "counts": np.asarray(jax.device_get(self.counts))[slots],
                "L": self.L, "C": self.C,
                "dtypes": [str(d) for d in self.col_dtypes]}

    @classmethod
    def from_snapshots(cls, key_group_range: KeyGroupRange,
                       max_parallelism: int, snapshots: list[dict],
                       rows_per_key: Optional[int] = None,
                       capacity: int = 1 << 12) -> "DeviceListStore":
        """Rebuild a store purely from its snapshots (the consuming side
        may restore before ever seeing a live batch of that input).
        ``capacity`` honors the operator's pre-sizing so a restore from
        an early (small) checkpoint does not re-walk the rehash ladder."""
        dtypes = [np.dtype(d) for d in snapshots[0]["dtypes"]]
        L = rows_per_key or max(int(s["L"]) for s in snapshots)
        store = cls(key_group_range, max_parallelism, dtypes,
                    capacity=capacity,
                    rows_per_key=max(L, max(int(s["L"])
                                            for s in snapshots)))
        store.restore(snapshots)
        return store

    def restore(self, snapshots: list[dict]) -> None:
        keys_parts, rows_parts, counts_parts = [], [], []
        for snap in snapshots:
            groups = np.asarray(snap["key_groups"])
            sel = np.array([g in self.key_group_range for g in groups],
                           bool)
            if snap["L"] > self.L or snap["C"] != self.C:
                raise RuntimeError(
                    "list-state snapshot shape mismatch: restore with "
                    f"rows_per_key >= {snap['L']} and the same columns")
            keys_parts.append(np.asarray(snap["keys"])[sel])
            r = np.asarray(snap["rows"])[sel]
            if snap["L"] < self.L:   # widen onto this store's row budget
                pad = np.zeros((len(r), self.L - snap["L"], self.C),
                               np.int64)
                r = np.concatenate([r, pad], axis=1)
            rows_parts.append(r)
            counts_parts.append(np.asarray(snap["counts"])[sel])
        keys = (np.concatenate(keys_parts) if keys_parts
                else np.empty(0, np.int64))
        while self.capacity < 2 * max(len(keys), 1):
            self.capacity *= 2
        self._load(
            keys,
            np.concatenate(rows_parts) if rows_parts
            else np.empty((0, self.L, self.C), np.int64),
            np.concatenate(counts_parts) if counts_parts
            else np.empty(0, np.int32))
