"""Queryable state over the network.

Reference: the flink-queryable-state module's server/client split —
KvStateServerImpl.java:38 (a Netty server on each TaskExecutor serving
point reads from live backends) and QueryableStateClient.java:80 (resolves
job + queryable name + key and issues the network read). The in-process
registry (state/queryable.py) stays the source of truth; this module puts
a TCP server in front of it — the seam the in-process module documents as
``KvStateRegistry.lookup``.

Protocol: length-prefixed pickle frames, one request/response per frame:

    ("get", queryable_name, key, namespace) -> ("ok", value_or_None)
                                             | ("err", message)
    ("names",)                              -> ("ok", [name, ...])

Reads are dirty (current state, not checkpoint-consistent) — exactly the
reference's contract.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Optional

from .backend import VOID_NAMESPACE
from .queryable import KvStateRegistry, UnknownKvStateError

__all__ = ["KvStateServer", "RemoteQueryableStateClient"]

_MSG = struct.Struct("<I")


def _send(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_MSG.pack(len(payload)) + payload)


def _recv(sock: socket.socket) -> Optional[Any]:
    head = b""
    while len(head) < _MSG.size:
        chunk = sock.recv(_MSG.size - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = _MSG.unpack(head)
    body = b""
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        if not chunk:
            return None
        body += chunk
    return pickle.loads(body)


class KvStateServer:
    """Serves a job's KvStateRegistry over TCP (reference
    KvStateServerImpl: one server per TaskExecutor; here one per job)."""

    def __init__(self, registry: KvStateRegistry, port: int = 0,
                 host: str = "127.0.0.1", config=None):
        from ..utils import auth

        self.registry = registry
        self._secret = auth.resolve_secret(config)
        auth.check_bind(host, self._secret, "KvStateServer")
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()
        threading.Thread(target=self._accept, name="kvstate-accept",
                         daemon=True).start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @classmethod
    def for_job(cls, job, port: int = 0) -> "KvStateServer":
        registry = getattr(job, "kv_registry", None)
        if registry is None:
            raise ValueError("job has no KvStateRegistry")
        return cls(registry, port=port)

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             name="kvstate-conn", daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        from ..utils import auth

        try:
            # auth preamble precedes the first pickle read
            if not auth.recv_hello(conn, self._secret):
                return
            while not self._stop.is_set():
                msg = _recv(conn)
                if msg is None:
                    return
                try:
                    _send(conn, ("ok", self._handle(msg)))
                except Exception as e:  # noqa: BLE001 - shipped to client
                    _send(conn, ("err", f"{type(e).__name__}: {e}"))
        except OSError:
            pass
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, msg: tuple) -> Any:
        kind = msg[0]
        if kind == "get":
            _, name, key, namespace = msg
            backend, state_name = self.registry.lookup_by_key(name, key)
            return backend.read_raw(state_name, key, namespace)
        if kind == "names":
            return self.registry.names()
        raise ValueError(f"unknown request {kind!r}")

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class RemoteQueryableStateClient:
    """Network twin of QueryableStateClient (reference
    QueryableStateClient.getKvState over the KvStateServer)."""

    def __init__(self, address: str, connect_timeout: float = 5.0,
                 config=None):
        from ..utils import auth

        self._address = address
        self._timeout = connect_timeout
        self._secret = auth.resolve_secret(config)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._connect()

    def _connect(self) -> None:
        from ..utils import auth

        host, port = self._address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=self._timeout)
        self._sock.settimeout(30.0)
        auth.send_hello(self._sock, self._secret)

    def _call(self, msg: tuple) -> Any:
        with self._lock:
            try:
                if self._sock is None:
                    self._connect()
                _send(self._sock, msg)
                resp = _recv(self._sock)
            except (OSError, ConnectionError):
                self._teardown()
                raise
            if resp is None:
                self._teardown()
                raise ConnectionError("kvstate server closed the connection")
        status, payload = resp
        if status == "err":
            if "UnknownKvStateError" in payload:
                raise UnknownKvStateError(payload)
            raise RuntimeError(f"kvstate server error: {payload}")
        return payload

    def _teardown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def get_kv_state(self, queryable_name: str, key: Any,
                     namespace: Any = VOID_NAMESPACE,
                     default: Any = None) -> Any:
        try:
            value = self._call(("get", queryable_name, key, namespace))
        except UnknownKvStateError:
            if queryable_name in self.names():
                return default   # name exists; this key has no state yet
            raise
        return default if value is None else value

    def names(self) -> list[str]:
        return self._call(("names",))

    def close(self) -> None:
        self._teardown()
