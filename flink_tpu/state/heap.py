"""Host in-memory keyed state backend.

Analog of the reference's HashMapStateBackend / HeapKeyedStateBackend
(flink-runtime state/hashmap/HashMapStateBackend.java:75,
state/heap/HeapKeyedStateBackend.java:75). Layout is
``states[name][key_group][(key, namespace)] -> entry`` so snapshots are
naturally partitioned by key group and restore can re-shard by range — the
same property the reference gets from key-group-ordered streams.

Where the reference uses copy-on-write maps for async snapshots, this backend
snapshots synchronously at the barrier (the step loop is micro-batched, so the
pause is one batch boundary); the TPU backend does the async device->host DMA
variant.
"""

from __future__ import annotations

import time
from typing import Any, Iterable, Optional

from ..core.keygroups import KeyGroupRange, assign_to_key_group
from .backend import (
    AggregatingState, KeyedStateBackend, ListState, MapState, ReducingState,
    State, ValueState, register_backend,
)
from .descriptors import AggregatingStateDescriptor, ReducingStateDescriptor, \
    StateDescriptor

__all__ = ["HeapKeyedStateBackend"]


class _Entry:
    __slots__ = ("value", "expiry")

    def __init__(self, value: Any, expiry: Optional[float] = None):
        self.value = value
        self.expiry = expiry


class HeapKeyedStateBackend(KeyedStateBackend):
    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int,
                 **_kwargs):
        super().__init__(key_group_range, max_parallelism)
        # name -> kg -> {(key, ns): _Entry}
        self._states: dict[str, dict[int, dict]] = {}
        self._descriptors: dict[str, StateDescriptor] = {}
        self._handles: dict[str, State] = {}
        # per-state value serializer (None slots = registry default);
        # snapshots record (name, version) per state and restore resolves
        # version skew through registered migrations
        self._serializers: dict[str, Any] = {}

    # -- internals ---------------------------------------------------------
    def _table(self, name: str) -> dict[int, dict]:
        return self._states.setdefault(name, {})

    def _kg_map(self, name: str) -> dict:
        kg = self._current_key_group
        if kg not in self.key_group_range:
            raise KeyError(
                f"Key group {kg} outside backend range {self.key_group_range}")
        return self._table(name).setdefault(kg, {})

    def _get(self, desc: StateDescriptor) -> Any:
        m = self._kg_map(desc.name)
        e = m.get((self._current_key, self._current_namespace))
        if e is None:
            return None
        if e.expiry is not None and e.expiry <= time.time():
            del m[(self._current_key, self._current_namespace)]
            return None
        return e.value

    def _put(self, desc: StateDescriptor, value: Any) -> None:
        expiry = time.time() + desc.ttl.ttl if desc.ttl else None
        self._kg_map(desc.name)[(self._current_key, self._current_namespace)] = \
            _Entry(value, expiry)

    def _remove(self, desc: StateDescriptor) -> None:
        self._kg_map(desc.name).pop(
            (self._current_key, self._current_namespace), None)

    # -- SPI ---------------------------------------------------------------
    def get_partitioned_state(self, descriptor: StateDescriptor) -> State:
        handle = self._handles.get(descriptor.name)
        if handle is None:
            prev = self._descriptors.get(descriptor.name)
            if prev is not None and prev.kind != descriptor.kind:
                raise ValueError(
                    f"State {descriptor.name!r} already registered as {prev.kind}")
            self._descriptors[descriptor.name] = descriptor
            if getattr(descriptor, "serializer", None) is not None:
                self._serializers[descriptor.name] = descriptor.serializer
            handle = _HANDLE_TYPES[descriptor.kind](self, descriptor)
            self._handles[descriptor.name] = handle
            if descriptor.queryable_name and self.kv_registry is not None:
                self.kv_registry.register(descriptor.queryable_name,
                                          descriptor.name, self)
        return handle

    def read_raw(self, state_name: str, key: Any,
                 namespace: Any = None) -> Any:
        import time as _time
        kg = assign_to_key_group(key, self.max_parallelism)
        e = self._table(state_name).get(kg, {}).get((key, namespace))
        if e is None or (e.expiry is not None and e.expiry <= _time.time()):
            return None
        return e.value

    def keys(self, state_name: str, namespace: Any = None) -> Iterable[Any]:
        for kg_map in self._table(state_name).values():
            for (key, ns) in list(kg_map):
                if ns == namespace:
                    yield key

    def namespaces(self, state_name: str) -> Iterable[Any]:
        seen = set()
        for kg_map in self._table(state_name).values():
            for (_key, ns) in kg_map:
                if ns not in seen:
                    seen.add(ns)
                    yield ns

    def entries(self, state_name: str):
        """Yield ((key, namespace), value) across the whole range."""
        for kg_map in self._table(state_name).values():
            for kn, e in kg_map.items():
                yield kn, e.value

    # -- checkpointing -----------------------------------------------------
    def _serializer_for(self, name: str):
        ser = self._serializers.get(name)
        if ser is None:
            from ..core.serializers import registry
            ser = registry.default()
        return ser

    def snapshot(self, checkpoint_id: int) -> dict:
        now = time.time()
        out: dict[str, dict[int, list]] = {}
        for name, table in self._states.items():
            per_kg: dict[int, list] = {}
            for kg, kg_map in table.items():
                items = [(kn, e.value, e.expiry) for kn, e in kg_map.items()
                         if e.expiry is None or e.expiry > now]
                if items:
                    per_kg[kg] = items
            out[name] = per_kg
        # TypeSerializerSnapshot analog: record each state's serializer
        # identity so restore can resolve schema evolution
        sers = {}
        for name in out:
            ser = self._serializer_for(name)
            sers[name] = [ser.name, ser.version]
        return {"kind": "heap", "states": out, "serializers": sers}

    def _value_migration(self, state_name: str, snap_sers: dict):
        """Resolve the migration callable for one state of one snapshot:
        None when versions match; raises with a precise message when no
        path exists (reference resolveSchemaCompatibility ->
        INCOMPATIBLE).

        Restore runs BEFORE open() in the operator lifecycle, so state
        descriptors (and their serializers) are usually not registered on
        this backend yet; the CURRENT serializer for a non-default
        snapshot therefore resolves through the process-global registry
        by the RECORDED name — user serializers register there at import
        (reference: the restored snapshot meets the new serializer
        instance provided by user code)."""
        rec = (snap_sers or {}).get(state_name)
        if rec is None:
            return None                       # pre-versioning snapshot
        sname, sver = rec[0], int(rec[1])
        cur = self._serializers.get(state_name)
        if cur is None:
            from ..core.serializers import registry
            if sname == "pickle":
                cur = registry.default()
            else:
                try:
                    cur = registry.get(sname)
                except KeyError:
                    raise RuntimeError(
                        f"state {state_name!r}: snapshot was written by "
                        f"serializer {sname!r} v{sver}, which is not "
                        "registered in this process "
                        "(core.serializers.registry.register)") from None
        if sname != cur.name:
            raise RuntimeError(
                f"state {state_name!r}: snapshot was written by serializer "
                f"{sname!r} v{sver} but the current serializer is "
                f"{cur.name!r} v{cur.version}; serializer replacement "
                "needs an offline rewrite (state-processor API)")
        if sver == cur.version:
            return None
        if sver > cur.version:
            raise RuntimeError(
                f"state {state_name!r}: snapshot serializer {sname!r} "
                f"v{sver} is NEWER than the running v{cur.version}; "
                "downgrade is not supported")
        from ..core.serializers import registry
        if not registry.has_migration_path(sname, sver, cur.version):
            raise RuntimeError(
                f"state {state_name!r}: serializer {sname!r} snapshot "
                f"v{sver} is incompatible with current v{cur.version} and "
                f"no migration chain v{sver}->v{cur.version} is "
                "registered (registry.register_migration)")
        return (lambda v, _n=sname, _f=sver, _t=cur.version:
                registry.migrate_value(_n, _f, _t, v))

    def restore(self, snapshots: Iterable[dict]) -> None:
        self._states.clear()
        self._handles.clear()
        for snap in snapshots:
            snap_sers = snap.get("serializers")
            for name, per_kg in snap.get("states", {}).items():
                migrate = self._value_migration(name, snap_sers)
                table = self._table(name)
                for kg, items in per_kg.items():
                    kg = int(kg)
                    if kg not in self.key_group_range:
                        continue  # rescaling: not ours
                    m = table.setdefault(kg, {})
                    for kn, value, expiry in items:
                        if migrate is not None:
                            value = migrate(value)
                        m[tuple(kn) if isinstance(kn, list) else kn] = \
                            _Entry(value, expiry)


class _HeapValueState(ValueState):
    def __init__(self, backend: HeapKeyedStateBackend, desc: StateDescriptor):
        self._b, self._d = backend, desc

    def value(self) -> Any:
        v = self._b._get(self._d)
        return self._d.default if v is None else v

    def update(self, value: Any) -> None:
        self._b._put(self._d, value)

    def clear(self) -> None:
        self._b._remove(self._d)


class _HeapListState(ListState):
    def __init__(self, backend: HeapKeyedStateBackend, desc: StateDescriptor):
        self._b, self._d = backend, desc

    def get(self) -> list:
        return self._b._get(self._d) or []

    def add(self, value: Any) -> None:
        cur = self._b._get(self._d)
        if cur is None:
            self._b._put(self._d, [value])
        else:
            cur.append(value)
            self._b._put(self._d, cur)

    def update(self, values: list) -> None:
        self._b._put(self._d, list(values))

    def clear(self) -> None:
        self._b._remove(self._d)


class _HeapReducingState(ReducingState):
    def __init__(self, backend: HeapKeyedStateBackend,
                 desc: ReducingStateDescriptor):
        self._b, self._d = backend, desc
        self._fn = desc.reduce_function

    def get(self) -> Any:
        return self._b._get(self._d)

    def add(self, value: Any) -> None:
        cur = self._b._get(self._d)
        self._b._put(self._d,
                     value if cur is None else self._fn.reduce(cur, value))

    def clear(self) -> None:
        self._b._remove(self._d)


class _HeapAggregatingState(AggregatingState):
    def __init__(self, backend: HeapKeyedStateBackend,
                 desc: AggregatingStateDescriptor):
        self._b, self._d = backend, desc
        self._fn = desc.aggregate_function

    def get(self) -> Any:
        acc = self._b._get(self._d)
        return None if acc is None else self._fn.get_result(acc)

    def get_accumulator(self) -> Any:
        return self._b._get(self._d)

    def add(self, value: Any) -> None:
        acc = self._b._get(self._d)
        if acc is None:
            acc = self._fn.create_accumulator()
        self._b._put(self._d, self._fn.add(value, acc))

    def merge_accumulator(self, other: Any) -> None:
        acc = self._b._get(self._d)
        self._b._put(self._d,
                     other if acc is None else self._fn.merge(acc, other))

    def clear(self) -> None:
        self._b._remove(self._d)


class _HeapMapState(MapState):
    def __init__(self, backend: HeapKeyedStateBackend, desc: StateDescriptor):
        self._b, self._d = backend, desc

    def _map(self) -> dict:
        m = self._b._get(self._d)
        if m is None:
            m = {}
            self._b._put(self._d, m)
        return m

    def get(self, key: Any) -> Any:
        return self._map().get(key)

    def put(self, key: Any, value: Any) -> None:
        m = self._map()
        m[key] = value
        self._b._put(self._d, m)

    def remove(self, key: Any) -> None:
        m = self._map()
        m.pop(key, None)
        self._b._put(self._d, m)

    def contains(self, key: Any) -> bool:
        return key in self._map()

    def items(self):
        return self._map().items()

    def clear(self) -> None:
        self._b._remove(self._d)


_HANDLE_TYPES = {
    "value": _HeapValueState,
    "list": _HeapListState,
    "reducing": _HeapReducingState,
    "aggregating": _HeapAggregatingState,
    "map": _HeapMapState,
}

register_backend("hashmap", HeapKeyedStateBackend)
