"""Changelog state backend: O(delta) checkpoints via a durable state-change
log (DSTL).

Analog of the reference's changelog backend + DSTL (flink-runtime
state/changelog/ChangelogKeyedStateBackend.java:110, flink-dstl
fs/FsStateChangelogStorage.java:57): every state mutation appends a change
record to the log writer (state/dstl.py — buffered, batch-uploaded
segments); a checkpoint ships only (base handle, segment handles past the
base), so checkpoint bytes are proportional to the change rate, not the
state size. Periodically the wrapped backend materializes: the full
snapshot is written ONCE to the changelog store, subsequent checkpoints
share it by handle, and segments covered by the base are deleted
(truncation).

Restore = load the materialized base by handle, then replay segments in
sequence order, filtered to this backend's key-group range — rescaling
works exactly as it does for full snapshots. Old-format inline snapshots
("kind": "changelog") restore too.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Iterable, Optional

from ..core.keygroups import KeyGroupRange
from .backend import register_backend
from .descriptors import StateDescriptor
from .dstl import (
    ChangelogWriter, changelog_storage_for, read_any_base, read_any_segment,
)
from .heap import HeapKeyedStateBackend, _Entry

__all__ = ["ChangelogKeyedStateBackend"]


class ChangelogKeyedStateBackend(HeapKeyedStateBackend):
    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int,
                 config=None, materialization_interval: Optional[int] = None,
                 flush_bytes: int = 1 << 20, **kwargs):
        super().__init__(key_group_range, max_parallelism, **kwargs)
        if materialization_interval is None:
            materialization_interval = 10
            if config is not None:
                from ..core.config import StateOptions
                materialization_interval = config.get(
                    StateOptions.CHANGELOG_MATERIALIZATION_INTERVAL)
        self._mat_interval = max(1, int(materialization_interval))
        self._store = changelog_storage_for(config)
        self._writer = ChangelogWriter(self._store, flush_bytes=flush_bytes)
        self._base_location: Optional[str] = None   # handle to live base
        self._base_seq = 0                          # log seq covered by base
        self._mat_id = 0
        self._checkpoints_since_mat = 0
        # SUBSUMPTION-DRIVEN truncation (reference: DSTL/materialization
        # artifact deletion rides checkpoint-subsumed notifications, never
        # snapshot attempts — a run of FAILED checkpoints must not delete
        # the artifacts of the last COMPLETED one). A superseded
        # generation's base+segments retire into _retired and are deleted
        # only when notify_checkpoint_complete proves every checkpoint the
        # coordinator may still serve references a NEWER generation.
        retained = 1
        if config is not None:
            from ..core.config import CheckpointingOptions
            retained = config.get(CheckpointingOptions.RETAINED)
        self._retained = max(1, int(retained))
        self._retired: list[tuple[int, str, list]] = []  # (gen, base, segs)
        self._ckpt_gen: dict[int, int] = {}     # snapshot cid -> generation
        self._completed_gens: list[tuple[int, int]] = []  # (cid, gen)

    # -- logged mutations --------------------------------------------------
    def _put(self, desc: StateDescriptor, value: Any) -> None:
        super()._put(desc, value)
        payload = pickle.dumps(
            (self._current_key, self._current_namespace, value),
            protocol=pickle.HIGHEST_PROTOCOL)
        self._writer.append(
            ("put", desc.name, self._current_key_group, payload,
             time.time() + desc.ttl.ttl if desc.ttl else None),
            len(payload))

    def _remove(self, desc: StateDescriptor) -> None:
        super()._remove(desc)
        payload = pickle.dumps(
            (self._current_key, self._current_namespace),
            protocol=pickle.HIGHEST_PROTOCOL)
        self._writer.append(
            ("rm", desc.name, self._current_key_group, payload, None),
            len(payload))

    # -- observability -----------------------------------------------------
    @property
    def log_size(self) -> int:
        return self._writer.last_seq - self._base_seq

    @property
    def bytes_uploaded(self) -> int:
        return self._writer.bytes_uploaded

    # -- checkpointing -----------------------------------------------------
    def materialize(self, checkpoint_id: int) -> None:
        """Full snapshot of the wrapped backend written ONCE to the
        changelog store. The previous generation's base + covered segments
        retire; deletion waits for a completion notification proving no
        servable checkpoint still references that generation."""
        import uuid

        prev_gen = self._mat_id
        self._mat_id += 1
        base = super().snapshot(checkpoint_id)
        prev_base = self._base_location
        # id embeds the key-group range + a nonce: parallel subtasks share
        # one store and must never collide on a base location
        base_id = (f"kg{self.key_group_range.start}-"
                   f"{self.key_group_range.end}-m{self._mat_id}-"
                   f"c{checkpoint_id}-{uuid.uuid4().hex[:8]}")
        self._base_location = self._store.write_base(
            base_id, pickle.dumps(base, protocol=pickle.HIGHEST_PROTOCOL))
        self._base_seq = self._writer.last_seq
        self._writer.drop_buffered()   # base covers them; don't upload dead
        covered = self._writer.detach(self._base_seq)
        if prev_base is not None:
            self._retired.append((prev_gen, prev_base, covered))
        else:
            # no checkpoint ever referenced pre-first-materialization
            # segments (snapshot() materializes before returning handles)
            for h in covered:
                self._store.delete_segment(h)
        self._checkpoints_since_mat = 0

    def snapshot(self, checkpoint_id: int) -> dict:
        if self._base_location is None \
                or self._checkpoints_since_mat >= self._mat_interval:
            self.materialize(checkpoint_id)
        self._checkpoints_since_mat += 1
        segments = self._writer.persist(self._base_seq)
        self._ckpt_gen[checkpoint_id] = self._mat_id
        # entries are released ONLY by explicit complete/abort
        # notifications (the coordinator notifies timeouts, declines, and
        # region-restart pauses) — never trimmed by id distance or count,
        # which would drop a still-running savepoint's generation pin and
        # let subsumption delete its base/segments (ADVICE r4). A
        # coordinator crash clears pins implicitly: tasks restart with a
        # freshly restored backend.
        return {"kind": "changelog-dstl",
                "driver": self._store.driver,
                "base": self._base_location,
                "base_seq": self._base_seq,
                "mat_id": self._mat_id,
                "segments": [h.__dict__ for h in segments]}

    # -- subsumption-driven truncation ---------------------------------
    def notify_checkpoint_complete(self, checkpoint_id: int,
                                    is_savepoint: bool = False) -> None:
        gen = self._ckpt_gen.pop(checkpoint_id, None)
        if gen is None:
            return
        if is_savepoint:
            # savepoints are rewritten self-contained at completion (the
            # coordinator inlines base+log) and never participate in the
            # coordinator's regular-checkpoint retention — they must
            # neither pin a generation nor evict a regular checkpoint's
            # pin from the retained window
            return
        self._completed_gens.append((checkpoint_id, gen))
        # the coordinator serves at most the last `retained` completed
        # checkpoints; anything this backend snapshotted before those is
        # subsumed. A retired generation is deletable once the OLDEST
        # still-servable completed checkpoint references a newer one.
        self._completed_gens = self._completed_gens[-self._retained:]
        min_live_gen = min(g for _cid, g in self._completed_gens)
        # in-flight snapshots (triggered, not yet completed/aborted) pin
        # their generation too: a slower concurrent checkpoint may still
        # complete after this one. Abandoned triggers are cleaned by the
        # coordinator's explicit abort notifications (timeouts and
        # region-restart pauses both call notify_checkpoint_aborted) —
        # NOT inferred from checkpoint-id distance, which would also drop
        # a still-running savepoint's pin and let subsumption delete the
        # base/segments out from under savepoint_self_contained.
        if self._ckpt_gen:
            min_live_gen = min(min_live_gen, min(self._ckpt_gen.values()))
        keep = []
        for entry in self._retired:
            if entry[0] < min_live_gen:
                _gen, loc, segments = entry
                self._store.delete_base(loc)
                for h in segments:
                    self._store.delete_segment(h)
            else:
                keep.append(entry)
        self._retired = keep

    def notify_checkpoint_aborted(self, checkpoint_id: int) -> None:
        self._ckpt_gen.pop(checkpoint_id, None)

    def restore(self, snapshots: Iterable[dict]) -> None:
        bases, replogs, plain = [], [], []
        legacy_logs = []
        for snap in snapshots:
            kind = snap.get("kind")
            if kind == "changelog-dstl":
                root = getattr(self._store, "dir", None)
                base_sers = None
                if snap.get("base") is not None:
                    base = pickle.loads(read_any_base(
                        snap["driver"], snap["base"], root))
                    base_sers = base.get("serializers")
                    bases.append(base)
                records: list[tuple[int, Any]] = []
                for h in snap.get("segments", []):
                    records.extend(read_any_segment(h, root))
                replogs.append((snap.get("base_seq", 0), records,
                                base_sers))
            elif kind == "changelog":      # old inline format
                if snap.get("mat") is not None:
                    bases.append(snap["mat"])
                legacy_logs.append(
                    (snap.get("log", []),
                     (snap.get("mat") or {}).get("serializers")))
            else:
                plain.append(snap)         # switching from another backend
        super().restore(bases + plain)
        for base_seq, records, sers in replogs:
            # segments may predate the base (flushed early): replay only
            # records the base does not already cover, in seq order
            # (log values share the base's serializer era: same backend
            # instance wrote both — migrate them identically)
            mig_cache: dict = {}
            for seq, rec in sorted(records):
                if seq > base_seq:
                    self._apply(rec, sers, mig_cache)
        for log, sers in legacy_logs:
            mig_cache = {}
            for rec in log:
                self._apply(rec, sers, mig_cache)
        # restored state is the new base: materialize on first snapshot
        self._base_location = None
        self._base_seq = self._writer.last_seq
        self._checkpoints_since_mat = 0

    def _apply(self, rec: tuple, snap_sers: dict = None,
               mig_cache: dict = None) -> None:
        op, name, kg, payload, expiry = rec
        if int(kg) not in self.key_group_range:
            return
        table = self._table(name).setdefault(int(kg), {})
        if op == "put":
            key, ns, value = pickle.loads(payload)
            # resolve the migration once per (state, snapshot), not per
            # replayed record — the log can be large
            if mig_cache is None:
                mig_cache = {}
            if name not in mig_cache:
                mig_cache[name] = self._value_migration(name, snap_sers)
            migrate = mig_cache[name]
            if migrate is not None:
                value = migrate(value)
            table[(key, ns)] = _Entry(value, expiry)
        else:
            key, ns = pickle.loads(payload)
            table.pop((key, ns), None)


register_backend("changelog", ChangelogKeyedStateBackend)
