"""Changelog state backend: O(delta) checkpoints via a state-change log.

Analog of the reference's changelog backend + DSTL (flink-runtime
state/changelog/ChangelogKeyedStateBackend.java:110, flink-dstl
fs/FsStateChangelogStorage.java:57): every state mutation appends a change
record to a log; a checkpoint ships only the log suffix since the last
materialization plus a handle to the materialized base, so checkpoint cost
is proportional to the change rate, not the state size. Periodically the
wrapped backend materializes (full snapshot) and the log truncates.

Implementation notes vs the reference:
* wraps the heap backend by overriding its _put/_remove choke points;
  change values are serialized at write time (pickle) exactly like DSTL
  serializes into the log — this also guards against later in-place
  mutation of logged references;
* the materialized base is shared BY REFERENCE across the checkpoints
  between two materializations (in-memory storage stores it once; the
  filesystem storage re-serializes it per checkpoint — true file-level
  dedup of the base is future work, the semantic contract is the same);
* restore = restore materialized base, then replay the log in order,
  filtered to this backend's key-group range (rescaling works the same
  way it does for full snapshots).
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Iterable, Optional

from ..core.keygroups import KeyGroupRange
from .backend import register_backend
from .descriptors import StateDescriptor
from .heap import HeapKeyedStateBackend, _Entry

__all__ = ["ChangelogKeyedStateBackend"]


class ChangelogKeyedStateBackend(HeapKeyedStateBackend):
    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int,
                 config=None, materialization_interval: Optional[int] = None,
                 **kwargs):
        super().__init__(key_group_range, max_parallelism, **kwargs)
        if materialization_interval is None:
            materialization_interval = 10
            if config is not None:
                from ..core.config import StateOptions
                materialization_interval = config.get(
                    StateOptions.CHANGELOG_MATERIALIZATION_INTERVAL)
        self._mat_interval = max(1, int(materialization_interval))
        self._log: list[tuple] = []          # change records since mat
        self._mat: Optional[dict] = None     # last materialized snapshot
        self._mat_id = 0
        self._checkpoints_since_mat = 0

    # -- logged mutations --------------------------------------------------
    def _put(self, desc: StateDescriptor, value: Any) -> None:
        super()._put(desc, value)
        self._log.append((
            "put", desc.name, self._current_key_group,
            pickle.dumps((self._current_key, self._current_namespace, value),
                         protocol=pickle.HIGHEST_PROTOCOL),
            time.time() + desc.ttl.ttl if desc.ttl else None))

    def _remove(self, desc: StateDescriptor) -> None:
        super()._remove(desc)
        self._log.append((
            "rm", desc.name, self._current_key_group,
            pickle.dumps((self._current_key, self._current_namespace),
                         protocol=pickle.HIGHEST_PROTOCOL), None))

    # -- checkpointing -----------------------------------------------------
    @property
    def log_size(self) -> int:
        return len(self._log)

    def materialize(self, checkpoint_id: int) -> None:
        """Full snapshot of the wrapped backend; truncates the log
        (reference periodic materialization)."""
        self._mat = super().snapshot(checkpoint_id)
        self._mat_id += 1
        self._log = []
        self._checkpoints_since_mat = 0

    def snapshot(self, checkpoint_id: int) -> dict:
        if self._mat is None \
                or self._checkpoints_since_mat >= self._mat_interval:
            self.materialize(checkpoint_id)
        self._checkpoints_since_mat += 1
        return {"kind": "changelog", "mat_id": self._mat_id,
                "mat": self._mat, "log": list(self._log)}

    def restore(self, snapshots: Iterable[dict]) -> None:
        mats, logs = [], []
        plain = []
        for snap in snapshots:
            if snap.get("kind") == "changelog":
                if snap.get("mat") is not None:
                    mats.append(snap["mat"])
                logs.append(snap.get("log", []))
            else:
                plain.append(snap)  # switching from a non-changelog backend
        super().restore(mats + plain)
        for log in logs:
            self._replay(log)
        # restored state is the new base: materialize lazily on first
        # snapshot (mat=None forces it)
        self._mat = None
        self._log = []
        self._checkpoints_since_mat = 0

    def _replay(self, log: list) -> None:
        for op, name, kg, payload, expiry in log:
            if int(kg) not in self.key_group_range:
                continue
            table = self._table(name).setdefault(int(kg), {})
            if op == "put":
                key, ns, value = pickle.loads(payload)
                table[(key, ns)] = _Entry(value, expiry)
            else:
                key, ns = pickle.loads(payload)
                table.pop((key, ns), None)


register_backend("changelog", ChangelogKeyedStateBackend)
