"""Durable Short-Term Log storage for the changelog backend (DSTL analog).

Reference: flink-dstl-dfs — FsStateChangelogStorage.java:57 (segment files
on a shared FS), BatchingStateChangeUploadScheduler (appends are buffered
and uploaded in batches, not one file per change), StateChangeFsUploader,
and ChangelogKeyedStateBackend.java:110's contract: a checkpoint ships
(materialized-base handle, log-segment handles covering seq > base_seq) —
bytes written per checkpoint are proportional to the CHANGE RATE, while the
base is written once per materialization and shared by reference across
every checkpoint in between.

Model:
* every change record gets a monotonically increasing ``seq``;
* the writer buffers records and flushes a **segment** (immutable blob of
  [from_seq, to_seq] records) when the buffer passes a size threshold or a
  checkpoint persists — the batching that keeps small-file pressure off the
  object store;
* ``persist(base_seq)`` returns handles for all live segments past the
  materialization point; ``detach(base_seq)`` hands covered segments to
  the caller, which OWNS their deferred deletion (retained checkpoints may
  still reference them — see the changelog backend's generation retention);
* materialized bases are stored once per materialization and referenced by
  handle.

Two drivers: filesystem (segments + bases as files) and in-memory (a
process-global table, the MemoryCheckpointStorage twin for tests).
"""

from __future__ import annotations

import os
import pickle
import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

__all__ = ["ChangelogWriter", "FsChangelogStorage", "InMemoryChangelogStorage",
           "SegmentHandle", "changelog_storage_for"]


@dataclass(frozen=True)
class SegmentHandle:
    """Reference to one immutable uploaded segment. ``digest`` carries a
    blake2b checksum of the stored payload (the checkpoint-manifest
    scheme extended to changelog artifacts); readers verify it and raise
    CorruptArtifactError on mismatch. Empty for legacy handles and the
    in-memory driver (whose payload never crosses a device boundary)."""

    segment_id: str
    from_seq: int
    to_seq: int
    driver: str                 # "fs" | "mem"
    location: str = ""          # fs: file path; mem: store key
    digest: str = ""            # blake2b-128 hex of the stored payload


def _segment_digest(payload: bytes) -> str:
    import hashlib
    return hashlib.blake2b(payload, digest_size=16).hexdigest()


def _verified_segment_loads(data: bytes, digest: str, what: str) -> list:
    """Unpickle a segment payload after checking its handle checksum —
    a bit-flipped or truncated changelog segment must surface as a typed
    CorruptArtifactError (→ restore fallback), never as garbage replay
    records or a bare unpickling crash."""
    from ..checkpoint.storage import CorruptArtifactError

    if digest and _segment_digest(data) != digest:
        raise CorruptArtifactError(
            f"changelog segment {what} failed its checksum "
            "(stored bytes do not match the handle digest)")
    try:
        return pickle.loads(data)
    except Exception as e:  # noqa: BLE001 - truncated/garbled payload
        raise CorruptArtifactError(
            f"changelog segment {what} is undecodable "
            f"({type(e).__name__}: {e})") from e


class _Store:
    def write_segment(self, records: list) -> SegmentHandle:
        raise NotImplementedError

    def read_segment(self, handle: SegmentHandle) -> list:
        raise NotImplementedError

    def delete_segment(self, handle: SegmentHandle) -> None:
        raise NotImplementedError

    def write_base(self, base_id: str, payload: bytes) -> str:
        raise NotImplementedError

    def read_base(self, location: str) -> bytes:
        raise NotImplementedError


class FsChangelogStorage(_Store):
    """Segment/base files under a directory (reference
    FsStateChangelogStorage + StateChangeFsUploader)."""

    driver = "fs"

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _resolve(self, location: str) -> str:
        # handles store ROOT-RELATIVE names so a checkpoint directory can
        # be moved/replicated and restored from a different mount path;
        # absolute locations (pre-round-4 snapshots) still resolve as-is
        return (location if os.path.isabs(location)
                else os.path.join(self.dir, location))

    def write_segment(self, records: list) -> SegmentHandle:
        seg_id = uuid.uuid4().hex[:16]
        name = f"seg-{records[0][0]}-{seg_id}"
        path = os.path.join(self.dir, name)
        payload = pickle.dumps(records, protocol=pickle.HIGHEST_PROTOCOL)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        return SegmentHandle(seg_id, records[0][0], records[-1][0],
                             "fs", name, digest=_segment_digest(payload))

    def read_segment(self, handle: SegmentHandle) -> list:
        with open(self._resolve(handle.location), "rb") as f:
            data = f.read()
        return _verified_segment_loads(data, handle.digest, handle.location)

    def delete_segment(self, handle: SegmentHandle) -> None:
        try:
            os.unlink(self._resolve(handle.location))
        except OSError:
            pass

    def write_base(self, base_id: str, payload: bytes) -> str:
        name = f"base-{base_id}"
        path = os.path.join(self.dir, name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        return name

    def read_base(self, location: str) -> bytes:
        with open(self._resolve(location), "rb") as f:
            return f.read()

    def delete_base(self, location: str) -> None:
        try:
            os.unlink(self._resolve(location))
        except OSError:
            pass


# process-global table for the in-memory driver: restore in tests happens in
# the same process, mirroring MemoryCheckpointStorage's scope
_MEM: dict[str, Any] = {}
_MEM_LOCK = threading.Lock()


class InMemoryChangelogStorage(_Store):
    driver = "mem"

    def write_segment(self, records: list) -> SegmentHandle:
        key = f"seg-{uuid.uuid4().hex}"
        with _MEM_LOCK:
            _MEM[key] = list(records)
        return SegmentHandle(key, records[0][0], records[-1][0], "mem", key)

    def read_segment(self, handle: SegmentHandle) -> list:
        with _MEM_LOCK:
            return list(_MEM[handle.location])

    def delete_segment(self, handle: SegmentHandle) -> None:
        with _MEM_LOCK:
            _MEM.pop(handle.location, None)

    def write_base(self, base_id: str, payload: bytes) -> str:
        key = f"base-{base_id}"
        with _MEM_LOCK:
            _MEM[key] = payload
        return key

    def read_base(self, location: str) -> bytes:
        with _MEM_LOCK:
            return _MEM[location]

    def delete_base(self, location: str) -> None:
        with _MEM_LOCK:
            _MEM.pop(location, None)


def _resolve_any(location: str, root: Optional[str]) -> str:
    if os.path.isabs(location) or root is None:
        return location
    return os.path.join(root, location)


def read_any_segment(handle_dict: dict, root: Optional[str] = None) -> list:
    """Reconstruct + read a segment from its serialized handle (restore may
    happen in a fresh process that only has the checkpoint payload). Pure
    read: no storage object is constructed, so restoring from a read-only
    replica of the checkpoint directory works. ``root`` resolves
    root-relative handle locations against the restoring job's changelog
    directory (absolute locations — old snapshots — pass through)."""
    h = SegmentHandle(**handle_dict)
    if h.driver == "fs":
        with open(_resolve_any(h.location, root), "rb") as f:
            data = f.read()
        return _verified_segment_loads(data, h.digest, h.location)
    return InMemoryChangelogStorage().read_segment(h)


def read_any_base(driver: str, location: str,
                  root: Optional[str] = None) -> bytes:
    if driver == "fs":
        with open(_resolve_any(location, root), "rb") as f:
            return f.read()
    return InMemoryChangelogStorage().read_base(location)


def changelog_storage_for(config) -> _Store:
    """Storage driver from config: the checkpoint directory's /changelog
    subdir when file checkpoints are configured, else in-memory."""
    directory = None
    if config is not None:
        from ..core.config import CheckpointingOptions
        directory = config.get(CheckpointingOptions.DIRECTORY)
    if directory:
        return FsChangelogStorage(os.path.join(directory, "changelog"))
    return InMemoryChangelogStorage()


class ChangelogWriter:
    """Buffered, batching appender (reference BatchingStateChangeUpload-
    Scheduler): appends accumulate in memory; a segment uploads when the
    buffer crosses ``flush_bytes`` or a checkpoint calls ``persist``."""

    def __init__(self, store: _Store, flush_bytes: int = 1 << 20):
        self.store = store
        self.flush_bytes = flush_bytes
        self._buf: list[tuple[int, Any]] = []    # [(seq, record)]
        self._buf_bytes = 0
        self._next_seq = 1
        self._segments: list[SegmentHandle] = []
        self.bytes_uploaded = 0                  # observability
        self.segments_uploaded = 0

    @property
    def last_seq(self) -> int:
        return self._next_seq - 1

    def append(self, record: tuple, nbytes: int) -> int:
        seq = self._next_seq
        self._next_seq += 1
        self._buf.append((seq, record))
        self._buf_bytes += nbytes
        if self._buf_bytes >= self.flush_bytes:
            self.flush()
        return seq

    def flush(self) -> None:
        if not self._buf:
            return
        handle = self.store.write_segment(self._buf)
        self._segments.append(handle)
        self.segments_uploaded += 1
        self.bytes_uploaded += self._buf_bytes
        self._buf = []
        self._buf_bytes = 0

    def persist(self, base_seq: int) -> list[SegmentHandle]:
        """Upload the remainder; return handles for every segment holding
        records past ``base_seq`` (what one checkpoint must reference)."""
        self.flush()
        return [h for h in self._segments if h.to_seq > base_seq]

    def detach(self, base_seq: int) -> list[SegmentHandle]:
        """Remove segments covered by ``base_seq`` from the live list
        WITHOUT deleting them — the caller owns their deferred deletion
        (retained checkpoints may still reference them; deleting covered
        segments eagerly is exactly the bug the generation retention in
        the changelog backend exists to prevent)."""
        dead = [h for h in self._segments if h.to_seq <= base_seq]
        self._segments = [h for h in self._segments if h.to_seq > base_seq]
        return dead

    def drop_buffered(self) -> None:
        """Discard buffered (never-uploaded) records: a materialization
        just covered them, so flushing them would upload a dead segment."""
        self._buf = []
        self._buf_bytes = 0
