"""Queryable state: external point reads of live keyed state.

Analog of the reference's flink-queryable-state module (server
KvStateServerImpl.java:38, client QueryableStateClient.java:80, worker-side
registry runtime/query/KvStateRegistry.java): a state marked queryable via
``descriptor.queryable("name")`` registers its backend in the job's
KvStateRegistry; a client resolves (queryable name, key) -> key group ->
owning backend and reads the current value without touching the data path.

In-process by design: the local runtime's tasks are threads, so the client
reads the live backend directly (the MiniCluster shape of the reference's
test client). A network server would sit behind the same registry lookup —
that seam is `KvStateRegistry.lookup`.

Consistency note (same as the reference): reads are dirty — they observe
current state, not a checkpoint-consistent view.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ..core.keygroups import assign_to_key_group
from .backend import VOID_NAMESPACE, KeyedStateBackend

__all__ = ["KvStateRegistry", "QueryableStateClient", "UnknownKvStateError"]


class UnknownKvStateError(KeyError):
    pass


class KvStateRegistry:
    """Worker-side registration of queryable states (reference
    KvStateRegistry.registerKvState)."""

    def __init__(self):
        # queryable name -> list of (backend, internal state name)
        self._entries: dict[str, list[tuple[KeyedStateBackend, str]]] = {}
        self._lock = threading.Lock()

    def register(self, queryable_name: str, state_name: str,
                 backend: KeyedStateBackend) -> None:
        with self._lock:
            entries = self._entries.setdefault(queryable_name, [])
            for b, s in entries:
                if s != state_name:
                    raise ValueError(
                        f"queryable name {queryable_name!r} already bound "
                        f"to state {s!r}; cannot also bind {state_name!r} "
                        "(reference rejects duplicate registrations too)")
                if b is backend:
                    return
            entries.append((backend, state_name))

    def unregister_backend(self, backend: KeyedStateBackend) -> None:
        with self._lock:
            for name in list(self._entries):
                self._entries[name] = [
                    (b, s) for b, s in self._entries[name] if b is not backend]
                if not self._entries[name]:
                    del self._entries[name]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def lookup(self, queryable_name: str,
               key_group: int) -> tuple[KeyedStateBackend, str]:
        with self._lock:
            entries = list(self._entries.get(queryable_name) or ())
        if not entries:
            raise UnknownKvStateError(
                f"no queryable state {queryable_name!r}; registered: "
                f"{self.names()}")
        for backend, state_name in entries:
            if key_group in backend.key_group_range:
                return backend, state_name
        raise UnknownKvStateError(
            f"key group {key_group} of {queryable_name!r} not on this job")

    def lookup_by_key(self, queryable_name: str,
                      key: Any) -> tuple[KeyedStateBackend, str]:
        """Resolve a KEY (not key group) to its owning backend — the single
        entry point clients use."""
        with self._lock:
            entries = list(self._entries.get(queryable_name) or ())
        if not entries:
            raise UnknownKvStateError(
                f"no queryable state {queryable_name!r}; registered: "
                f"{self.names()}")
        kg = assign_to_key_group(key, entries[0][0].max_parallelism)
        for backend, state_name in entries:
            if kg in backend.key_group_range:
                return backend, state_name
        raise UnknownKvStateError(
            f"key group {kg} of {queryable_name!r} not on this job")


class QueryableStateClient:
    """Point reads against a running local job (reference
    QueryableStateClient.getKvState)."""

    def __init__(self, job):
        registry = getattr(job, "kv_registry", None)
        if registry is None:
            raise ValueError("job has no KvStateRegistry (not a local job?)")
        self._registry = registry

    def get_kv_state(self, queryable_name: str, key: Any,
                     namespace: Any = VOID_NAMESPACE,
                     default: Any = None) -> Any:
        try:
            backend, state_name = self._registry.lookup_by_key(
                queryable_name, key)
        except UnknownKvStateError:
            if queryable_name in self._registry.names():
                # name exists but no backend covers this key group yet
                # (registration is lazy per subtask): the key has no state
                return default
            raise
        value = backend.read_raw(state_name, key, namespace)
        return default if value is None else value
