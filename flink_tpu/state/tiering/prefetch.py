"""PrefetchPipeline: stage warm→hot promotions off the mailbox thread.

The backend decides *which* key groups to promote (ResidencyManager);
this pipeline does the expensive part — gathering the groups' rows out of
the host-warm tier and uploading them into a staged device buffer —
on a background thread, double-buffered so one payload can stage while
another waits to be applied.  The mailbox thread only ever:

* enqueues a request (:meth:`request`), and
* polls for a finished payload at a batch boundary (:meth:`poll`),

so promotions land exactly at batch boundaries and the fire path's
scatter-free invariants hold.  Staging is watchdog-bounded and
fault-injectable under site ``tier.prefetch``; a background failure is
re-raised on the mailbox thread at the next poll.  ``cancel()`` (called
on restore/restart) bumps an epoch so in-flight stagings are discarded —
a stale payload can never apply against post-restore state.

This module sits on the tiering hot path (TPU101/JX504 lint): the staging
callback supplied by the backend owns all device interaction; nothing
here touches device values or forces a host sync.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

_SCOPE = "tiering.prefetch"


class PrefetchPipeline:
    """Double-buffered background staging of promotion payloads.

    ``stage_fn(groups) -> payload | None`` is supplied by the backend and
    performs the host-tier gather plus the h2d upload of the staged
    arrays; a ``None`` return means the groups vanished from the warm
    tier in the meantime and the request is dropped.
    """

    def __init__(self, stage_fn: Callable[[np.ndarray], Optional[dict]],
                 *, asynchronous: bool = True, depth: int = 2):
        self._stage_fn = stage_fn
        self._asynchronous = bool(asynchronous)
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._requests: collections.deque = collections.deque()
        self._staged: collections.deque = collections.deque(maxlen=max(1, depth))
        self._pending_groups: set = set()
        self._epoch = 0
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self.staged_total = 0
        self.cancelled_total = 0

    # ------------------------------------------------------------------
    # mailbox-thread API
    # ------------------------------------------------------------------
    def request(self, groups: Sequence[int]) -> int:
        """Queue ``groups`` for staging; returns how many were accepted.

        Groups already queued or staged are skipped, so repeated boundary
        polls do not pile up duplicate work.  In synchronous mode
        (``state.tiering.async-prefetch: false``) staging happens inline,
        which keeps single-threaded test runs fully deterministic.
        """
        with self._lock:
            if self._closed:
                return 0
            fresh = [int(g) for g in groups
                     if int(g) not in self._pending_groups]
            if not fresh:
                return 0
            self._pending_groups.update(fresh)
            self._requests.append((self._epoch, np.asarray(fresh, np.int64)))
            epoch = self._epoch
        if self._asynchronous:
            self._ensure_thread()
            with self._wake:
                self._wake.notify()
        else:
            self._drain_one(epoch)
        return len(fresh)

    def poll(self) -> Optional[dict]:
        """Return a staged payload if one is ready; else ``None``.

        Re-raises any staging failure here, on the mailbox thread, so
        injected persistent faults surface at a batch boundary instead of
        dying silently on the background thread.
        """
        with self._lock:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            while self._staged:
                epoch, groups, payload = self._staged.popleft()
                if epoch != self._epoch:
                    continue
                self._pending_groups.difference_update(int(g) for g in groups)
                return payload
            return None

    def forget(self, groups: Sequence[int]) -> None:
        """Drop ``groups`` from the pending set (payload was discarded)."""
        with self._lock:
            self._pending_groups.difference_update(int(g) for g in groups)

    def cancel(self) -> None:
        """Discard queued and staged work; in-flight stagings expire.

        Called on restore/restart: the epoch bump means a payload staged
        against pre-restore state can never reach :meth:`poll`.
        """
        with self._lock:
            self._epoch += 1
            dropped = len(self._requests) + len(self._staged) + len(
                self._pending_groups)
            self._requests.clear()
            self._staged.clear()
            self._pending_groups.clear()
            self._error = None
            if dropped:
                self.cancelled_total += 1

    def close(self) -> None:
        self.cancel()
        with self._wake:
            self._closed = True
            self._wake.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    @property
    def idle(self) -> bool:
        with self._lock:
            return not (self._requests or self._staged or self._pending_groups)

    # ------------------------------------------------------------------
    # staging (background thread in async mode, inline otherwise)
    # ------------------------------------------------------------------
    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._worker, name="tier-prefetch", daemon=True)
            self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._wake:
                while not self._requests and not self._closed:
                    self._wake.wait(timeout=1.0)
                if self._closed:
                    return
            self._drain_one()

    def _drain_one(self, only_epoch: Optional[int] = None) -> None:
        with self._lock:
            if not self._requests:
                return
            epoch, groups = self._requests.popleft()
            if epoch != self._epoch or (
                    only_epoch is not None and epoch != only_epoch):
                self._pending_groups.difference_update(int(g) for g in groups)
                return
        try:
            payload = self._stage(groups)
        except BaseException as exc:  # surfaced at the next poll()
            with self._lock:
                if epoch == self._epoch:
                    self._error = exc
                    self._pending_groups.difference_update(
                        int(g) for g in groups)
            return
        with self._lock:
            if epoch != self._epoch:
                return
            if payload is None:
                self._pending_groups.difference_update(int(g) for g in groups)
                return
            self._staged.append((epoch, groups, payload))
            self.staged_total += 1

    def _stage(self, groups: np.ndarray) -> Optional[dict]:
        from ...metrics.tracing import TRACER
        from ...runtime.faults import fire_with_retries
        from ...runtime.watchdog import WATCHDOG
        # Fire the fault site before gathering: a transient fault retries
        # with no state mutated, a persistent one aborts the staging and
        # surfaces at the next boundary poll.
        fire_with_retries("tier.prefetch", _SCOPE)
        with TRACER.span("tier", "Prefetch") as sp:
            payload = WATCHDOG.run(
                "tier.prefetch", lambda: self._stage_fn(groups), scope=_SCOPE)
            sp.set_attribute("groups", int(len(groups)))
            sp.set_attribute("keys", 0 if payload is None
                             else int(payload.get("n", 0)))
        return payload
