"""Tiered state residency: device-hot / host-warm paging for 100M+ keys.

The state residency subsystem (ROADMAP open item 3): key cardinality
beyond the HBM budget keeps the HOT key groups device-resident and pages
the rest to the host-warm tier (state/spill.py HostTier), with residency
decided by a decayed frequency+recency policy instead of the bare LRU the
spill tier started with.

* :mod:`policy` — the deterministic 2Q-style heat policy (pure numpy,
  seeded tie-breaks, decay on boundary cadence — never wall clock).
* :mod:`residency` — the :class:`ResidencyManager` driving eviction and
  promotion decisions per backend, plus the process-global registry the
  CLI/REST residency table reads.
* :mod:`prefetch` — the :class:`PrefetchPipeline` staging warm→hot
  promotions off the mailbox thread (double-buffered h2d staging,
  watchdog-bounded under site ``tier.prefetch``); promotions apply only
  at batch boundaries, so the fire path's scatter-free invariants and
  exactly-once semantics hold.
"""

from .policy import TieringPolicy
from .prefetch import PrefetchPipeline
from .residency import (
    RESIDENCY_REGISTRY, ResidencyManager, hit_ratio_series,
    register_residency, residency_table, unregister_residency,
)

__all__ = [
    "TieringPolicy", "PrefetchPipeline", "ResidencyManager",
    "RESIDENCY_REGISTRY", "register_residency", "unregister_residency",
    "residency_table", "hit_ratio_series",
]
