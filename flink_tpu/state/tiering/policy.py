"""Deterministic 2Q-style residency policy over key groups.

Pure numpy, no device state, no wall clock: every input is an explicit
batch/boundary counter, decay runs on a fixed boundary cadence, and all
ties break through one seeded permutation fixed at construction.  Feeding
the same observation sequence therefore yields the same eviction and
promotion order on every run — the property the chaos replay drills
(TPU501) rely on.

Stages follow the classic 2Q split:

* ``COLD`` (0) — never touched, or demoted to the warm tier.
* ``PROBATION`` (1) — touched once; evicted first, by recency alone.
* ``PROTECTED`` (2) — re-touched in a *later* batch than its first
  touch; evicted last, by decayed heat then recency.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

COLD = 0
PROBATION = 1
PROTECTED = 2

_STAGE_NAMES = ("cold", "probation", "protected")


def stage_name(stage: int) -> str:
    """Human-readable stage label for the residency table."""
    return _STAGE_NAMES[int(stage)]


class TieringPolicy:
    """Decayed frequency+recency (2Q) scoring at key-group granularity.

    ``heat`` is the decayed access-frequency estimate, ``last_touch`` the
    batch counter of the most recent access, ``stage`` the 2Q queue the
    group currently sits in.  The policy never looks at device memory; the
    backend feeds it either per-batch group histograms (sync spill path)
    or the merged device touch clock (deferred spill path).
    """

    def __init__(self, max_parallelism: int, *, seed: int = 24243,
                 decay_interval: int = 8, decay_factor: float = 0.5):
        if max_parallelism <= 0:
            raise ValueError("max_parallelism must be positive")
        self.max_parallelism = int(max_parallelism)
        self.decay_interval = max(1, int(decay_interval))
        self.decay_factor = float(decay_factor)
        self.heat = np.zeros(self.max_parallelism, np.float64)
        self.last_touch = np.zeros(self.max_parallelism, np.int64)
        self.first_touch = np.zeros(self.max_parallelism, np.int64)
        self.stage = np.zeros(self.max_parallelism, np.int8)
        # Seeded tie-break: groups with identical (stage, heat, recency)
        # keys order by this fixed permutation, never by dict/hash order.
        self._tiebreak = np.random.default_rng(int(seed)).permutation(
            self.max_parallelism)
        self._boundaries = 0
        self.decays = 0

    # ------------------------------------------------------------------
    # observations
    # ------------------------------------------------------------------
    def touch(self, groups: np.ndarray, batch_no: int,
              counts: Optional[np.ndarray] = None) -> None:
        """Record accesses for ``groups`` during batch ``batch_no``.

        ``groups`` may contain duplicates unless ``counts`` is given, in
        which case ``groups`` must be unique and ``counts`` carries the
        per-group access count.
        """
        if len(groups) == 0:
            return
        groups = np.asarray(groups, np.int64)
        if counts is None:
            groups, counts = np.unique(groups, return_counts=True)
        batch_no = int(batch_no)
        # 2Q transitions: first touch parks a group in probation; a touch
        # in a strictly later batch than the first promotes to protected.
        fresh = self.stage[groups] == COLD
        self.stage[groups[fresh]] = PROBATION
        self.first_touch[groups[fresh]] = batch_no
        again = (self.stage[groups] == PROBATION) & (
            self.first_touch[groups] < batch_no)
        self.stage[groups[again]] = PROTECTED
        self.heat[groups] += counts.astype(np.float64)
        np.maximum.at(self.last_touch, groups,
                      np.full(len(groups), batch_no, np.int64))

    def adopt_clock(self, clock: np.ndarray) -> np.ndarray:
        """Merge a device touch clock (int64[max_parallelism]).

        The deferred spill path keeps an on-device per-group LRU clock;
        the backend syncs it at boundaries and hands it here.  A group
        whose clock advanced since the last adoption counts as one touch
        in that batch.  Returns the boolean mask of advanced groups so the
        caller can account hit ratios.
        """
        clock = np.asarray(clock, np.int64)
        advanced = clock > self.last_touch
        if advanced.any():
            groups = np.nonzero(advanced)[0]
            fresh = self.stage[groups] == COLD
            self.stage[groups[fresh]] = PROBATION
            self.first_touch[groups[fresh]] = clock[groups[fresh]]
            again = (self.stage[groups] == PROBATION) & (
                self.first_touch[groups] < clock[groups])
            self.stage[groups[again]] = PROTECTED
            self.heat[groups] += 1.0
            self.last_touch[groups] = clock[groups]
        return advanced

    def on_boundary(self) -> bool:
        """Advance the boundary cadence; decay heat when it is due.

        Boundaries are checkpoint/fire events, never wall clock, so the
        decay schedule replays identically under chaos (TPU501).
        Returns True when a decay step ran.
        """
        self._boundaries += 1
        if self._boundaries % self.decay_interval != 0:
            return False
        self.heat *= self.decay_factor
        self.decays += 1
        return True

    def demote(self, groups: Sequence[int]) -> None:
        """Mark ``groups`` as paged out to the warm tier (stage COLD)."""
        groups = np.asarray(groups, np.int64)
        if len(groups):
            self.stage[groups] = COLD

    def promote(self, groups: Sequence[int]) -> None:
        """Mark ``groups`` as paged back in (stage PROTECTED).

        A promoted group earned its way back with sustained heat, so it
        re-enters the protected queue, not probation.
        """
        groups = np.asarray(groups, np.int64)
        if len(groups):
            self.stage[groups] = PROTECTED

    # ------------------------------------------------------------------
    # decisions
    # ------------------------------------------------------------------
    def eviction_order(self, candidates: np.ndarray) -> np.ndarray:
        """Order ``candidates`` coldest-first for eviction.

        Probationary groups go first (recency only, 2Q's A1 queue), then
        protected groups by (decayed heat, recency).  ``np.lexsort`` keys
        are listed least significant first; the fixed permutation is the
        final tie-break so the order is total and seeded.
        """
        candidates = np.asarray(candidates, np.int64)
        if len(candidates) == 0:
            return candidates
        protected = (self.stage[candidates] == PROTECTED).astype(np.int8)
        order = np.lexsort((
            self._tiebreak[candidates],
            self.last_touch[candidates],
            self.heat[candidates],
            protected,
        ))
        return candidates[order]

    def promotion_order(self, candidates: np.ndarray,
                        min_heat: float) -> np.ndarray:
        """Order warm ``candidates`` hottest-first, dropping tepid ones."""
        candidates = np.asarray(candidates, np.int64)
        if len(candidates) == 0:
            return candidates
        hot = candidates[self.heat[candidates] >= float(min_heat)]
        if len(hot) == 0:
            return hot
        order = np.lexsort((
            self._tiebreak[hot],
            -self.last_touch[hot],
            -self.heat[hot],
        ))
        return hot[order]
