"""ResidencyManager: per-backend driver for tiered state decisions.

One manager per budgeted :class:`~flink_tpu.state.tpu_backend.TpuKeyedStateBackend`.
It owns the :class:`~flink_tpu.state.tiering.policy.TieringPolicy`, feeds
it the access observations the backend already collects (per-batch group
histograms on the sync spill path, the on-device touch clock on the
deferred path), accounts hot-tier hit ratios into DEVICE_STATS, and
answers the two questions the backend asks:

* which resident groups to *demote* when the HBM budget is exceeded
  (:meth:`eviction_order`), and
* which warm groups to *promote* when there is headroom and sustained
  heat (:meth:`promotion_candidates`).

This module sits on the tiering hot path (TPU101/JX504 lint): it must
stay free of host syncs — everything here is host-side numpy; the backend
hands over plain arrays and applies the answers on device itself.

A process-global registry maps operator names to live managers so the
CLI (``python -m flink_tpu.cli state-residency <job>``) and the REST
endpoint (``/jobs/<job>/state-residency``) can print the per-key-group
residency/heat table of a running job.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ...metrics.device import DEVICE_STATS
from .policy import TieringPolicy, stage_name

# Upper bound on groups promoted per boundary: keeps each staging gather
# and fixed-capacity insert small enough to stay boundary-amortized.
MAX_PROMOTIONS_PER_BOUNDARY = 16

# Per-boundary hit-ratio samples retained per manager: enough to see a
# whole tiny/TIERED bench run's trajectory without unbounded growth.
HIT_RATIO_WINDOW = 64


class ResidencyManager:
    """Tracks heat and residency for one backend's key groups."""

    def __init__(self, max_parallelism: int, budget_slots: int, *,
                 seed: int = 24243, decay_interval: int = 8,
                 decay_factor: float = 0.5, promote_headroom: float = 0.5,
                 promote_min_heat: float = 2.0):
        self.max_parallelism = int(max_parallelism)
        self.budget_slots = int(budget_slots)
        self.promote_headroom = float(promote_headroom)
        self.promote_min_heat = float(promote_min_heat)
        self.policy = TieringPolicy(
            self.max_parallelism, seed=seed,
            decay_interval=decay_interval, decay_factor=decay_factor)
        self._lock = threading.Lock()
        # Cached residency view for the debug table; updated at events,
        # never by syncing the device from here.
        self._spilled_view = np.zeros(self.max_parallelism, bool)
        self._warm_counts_view = np.zeros(self.max_parallelism, np.int64)
        self.evicted_groups = 0
        self.promoted_groups = 0
        self.boundaries = 0
        # per-boundary hot-hit-ratio time series: touches accumulate
        # between boundaries, each boundary seals one sample into the
        # bounded ring (the TIERED 10x-vs-100x anomaly is only visible
        # as a trajectory, not in the run-wide cumulative ratio)
        self._window_hot = 0
        self._window_total = 0
        self._hit_ratio_series: deque = deque(maxlen=HIT_RATIO_WINDOW)

    # ------------------------------------------------------------------
    # observations (fed by the backend)
    # ------------------------------------------------------------------
    def observe(self, groups: np.ndarray, batch_no: int,
                spilled_mask: Optional[np.ndarray]) -> None:
        """Account one batch of per-record key groups (sync spill path)."""
        if len(groups) == 0:
            return
        with self._lock:
            uniq, counts = np.unique(np.asarray(groups, np.int64),
                                     return_counts=True)
            self.policy.touch(uniq, batch_no, counts=counts)
            total = int(counts.sum())
            if spilled_mask is None:
                hot = total
            else:
                hot = int(counts[~spilled_mask[uniq]].sum())
            self._window_hot += hot
            self._window_total += total
            DEVICE_STATS.note_tier_touches(hot, total)

    def adopt_clock(self, clock: np.ndarray,
                    spilled_mask: Optional[np.ndarray]) -> None:
        """Merge the on-device touch clock (deferred spill path)."""
        with self._lock:
            advanced = self.policy.adopt_clock(clock)
            total = int(advanced.sum())
            if total == 0:
                return
            if spilled_mask is None:
                hot = total
            else:
                hot = int((advanced & ~spilled_mask).sum())
            self._window_hot += hot
            self._window_total += total
            DEVICE_STATS.note_tier_touches(hot, total)

    def on_boundary(self) -> bool:
        """Advance the decay cadence at a checkpoint/fire boundary; seals
        the boundary's hot-hit-ratio sample into the bounded ring."""
        with self._lock:
            self.boundaries += 1
            if self._window_total:
                self._hit_ratio_series.append(
                    round(self._window_hot / self._window_total, 4))
                self._window_hot = 0
                self._window_total = 0
            return self.policy.on_boundary()

    def hit_ratio_series(self) -> List[float]:
        """Per-boundary hot-tier hit ratios, oldest first (last
        ``HIT_RATIO_WINDOW`` boundaries that saw any touches)."""
        with self._lock:
            return list(self._hit_ratio_series)

    # ------------------------------------------------------------------
    # decisions (answered to the backend)
    # ------------------------------------------------------------------
    def eviction_order(self, candidates: np.ndarray) -> np.ndarray:
        """Coldest-first ordering of resident ``candidates``."""
        with self._lock:
            return self.policy.eviction_order(candidates)

    def promotion_candidates(self, spilled_mask: np.ndarray,
                             warm_counts: np.ndarray, resident_keys: int,
                             capacity: int) -> np.ndarray:
        """Warm groups worth paging back in, hottest first.

        Greedy under the headroom constraint: the promoted keys plus the
        currently resident keys must stay within ``promote_headroom`` of
        capacity, so a promotion can never itself force an eviction.
        """
        with self._lock:
            warm = np.nonzero(spilled_mask & (warm_counts > 0))[0]
            ranked = self.policy.promotion_order(warm, self.promote_min_heat)
            if len(ranked) == 0:
                return ranked
            room = int(self.promote_headroom * capacity) - int(resident_keys)
            picked: List[int] = []
            for g in ranked[:MAX_PROMOTIONS_PER_BOUNDARY]:
                take = int(warm_counts[g])
                if take > room:
                    continue
                room -= take
                picked.append(int(g))
            return np.asarray(picked, np.int64)

    def note_demoted(self, groups: np.ndarray) -> None:
        with self._lock:
            self.policy.demote(groups)
            self.evicted_groups += len(groups)
            self._spilled_view[np.asarray(groups, np.int64)] = True

    def note_promoted(self, groups: np.ndarray) -> None:
        with self._lock:
            self.policy.promote(groups)
            self.promoted_groups += len(groups)
            self._spilled_view[np.asarray(groups, np.int64)] = False

    # ------------------------------------------------------------------
    # debug view
    # ------------------------------------------------------------------
    def update_view(self, spilled_mask: Optional[np.ndarray],
                    warm_counts: Optional[np.ndarray]) -> None:
        """Refresh the cached residency view from backend-held arrays."""
        with self._lock:
            if spilled_mask is not None:
                self._spilled_view = np.array(spilled_mask, bool, copy=True)
            if warm_counts is not None:
                self._warm_counts_view = np.array(
                    warm_counts, np.int64, copy=True)

    def table_rows(self, include_cold: bool = False) -> List[dict]:
        """Per-key-group rows for the residency/heat debug table."""
        with self._lock:
            pol = self.policy
            rows = []
            for g in range(self.max_parallelism):
                touched = pol.last_touch[g] > 0 or pol.heat[g] > 0
                spilled = bool(self._spilled_view[g])
                if not (touched or spilled or include_cold):
                    continue
                rows.append({
                    "key_group": g,
                    "tier": "warm" if spilled else "hot",
                    "stage": stage_name(pol.stage[g]),
                    "warm_keys": int(self._warm_counts_view[g]),
                    "heat": round(float(pol.heat[g]), 3),
                    "last_touch": int(pol.last_touch[g]),
                })
            return rows


# ----------------------------------------------------------------------
# process-global registry for the CLI / REST residency table
# ----------------------------------------------------------------------
RESIDENCY_REGISTRY: Dict[str, ResidencyManager] = {}
_REGISTRY_LOCK = threading.Lock()


def register_residency(name: str, manager: ResidencyManager) -> None:
    with _REGISTRY_LOCK:
        RESIDENCY_REGISTRY[str(name)] = manager


def unregister_residency(name: str) -> None:
    with _REGISTRY_LOCK:
        RESIDENCY_REGISTRY.pop(str(name), None)


def residency_table(name: Optional[str] = None) -> List[dict]:
    """Rows across registered managers, newest registration last.

    ``name`` filters by substring match against the registered operator
    name (job name, operator name, or ``job/operator``); an empty match
    falls back to every registered manager so the debug table still shows
    something useful when the caller guesses the name wrong.
    """
    with _REGISTRY_LOCK:
        items = list(RESIDENCY_REGISTRY.items())
    if name:
        matched = [(k, m) for k, m in items if str(name) in k]
        if matched:
            items = matched
    rows: List[dict] = []
    for key, manager in items:
        for row in manager.table_rows():
            rows.append({"operator": key, **row})
    return rows


def hit_ratio_series(name: Optional[str] = None) -> Dict[str, List[float]]:
    """Per-boundary hot-hit-ratio series per registered manager (same
    substring matching + fall-back semantics as ``residency_table``)."""
    with _REGISTRY_LOCK:
        items = list(RESIDENCY_REGISTRY.items())
    if name:
        matched = [(k, m) for k, m in items if str(name) in k]
        if matched:
            items = matched
    return {key: manager.hit_ratio_series() for key, manager in items}
