"""Restart backoff strategies.

Analog of the reference's RestartBackoffTimeStrategy family
(flink-runtime executiongraph/failover/: FixedDelayRestartBackoffTimeStrategy,
ExponentialDelayRestartBackoffTimeStrategy:38, FailureRateRestartBackoffTime-
Strategy, NoRestartBackoffTimeStrategy), selected through config exactly like
RestartStrategyOptions.
"""

from __future__ import annotations

import time
from typing import Optional

from ..core.config import Configuration, RuntimeOptions

__all__ = ["RestartStrategy", "NoRestartStrategy", "FixedDelayRestartStrategy",
           "ExponentialDelayRestartStrategy", "FailureRateRestartStrategy",
           "restart_strategy_from_config"]


class RestartStrategy:
    def can_restart(self) -> bool:
        raise NotImplementedError

    def backoff_seconds(self) -> float:
        raise NotImplementedError

    def notify_failure(self) -> None:
        pass

    def notify_recovered(self) -> None:
        """Called after a stretch of healthy running (resets escalation)."""


class NoRestartStrategy(RestartStrategy):
    def can_restart(self) -> bool:
        return False

    def backoff_seconds(self) -> float:
        return 0.0


class FixedDelayRestartStrategy(RestartStrategy):
    def __init__(self, attempts: int, delay: float):
        self.attempts = attempts
        self.delay = delay
        self._failures = 0

    def notify_failure(self) -> None:
        self._failures += 1

    def can_restart(self) -> bool:
        return self._failures <= self.attempts

    def backoff_seconds(self) -> float:
        return self.delay


class ExponentialDelayRestartStrategy(RestartStrategy):
    def __init__(self, initial: float, maximum: float, multiplier: float = 2.0,
                 reset_after: float = 60.0):
        self.initial = initial
        self.maximum = maximum
        self.multiplier = multiplier
        self.reset_after = reset_after
        self._current = initial
        self._last_failure = 0.0

    def notify_failure(self) -> None:
        now = time.time()
        if now - self._last_failure > self.reset_after:
            self._current = self.initial
        else:
            self._current = min(self._current * self.multiplier, self.maximum)
        self._last_failure = now

    def notify_recovered(self) -> None:
        # reset the escalation AND the failure clock: without clearing
        # _last_failure, the first failure AFTER a healthy stretch still
        # lands inside the old reset_after window and escalates straight
        # to initial*multiplier (reference ExponentialDelayRestartBackoff-
        # TimeStrategy resets its whole state on a stable run)
        self._current = self.initial
        self._last_failure = 0.0

    def can_restart(self) -> bool:
        return True

    def backoff_seconds(self) -> float:
        return self._current


class FailureRateRestartStrategy(RestartStrategy):
    """Give up when more than ``max_failures`` within ``interval`` seconds."""

    def __init__(self, max_failures: int, interval: float, delay: float):
        self.max_failures = max_failures
        self.interval = interval
        self.delay = delay
        self._failures: list[float] = []

    def notify_failure(self) -> None:
        self._failures.append(time.time())
        self._prune()

    def _prune(self) -> None:
        cutoff = time.time() - self.interval
        self._failures = [t for t in self._failures if t >= cutoff]

    def can_restart(self) -> bool:
        # prune HERE too: old entries must age out even when no new
        # failure arrives, otherwise a burst permanently poisons the
        # window and the strategy never allows another restart
        self._prune()
        return len(self._failures) <= self.max_failures

    def backoff_seconds(self) -> float:
        return self.delay


def restart_strategy_from_config(config: Configuration) -> RestartStrategy:
    kind = config.get(RuntimeOptions.RESTART_STRATEGY)
    if kind == "none":
        return NoRestartStrategy()
    if kind == "fixed-delay":
        return FixedDelayRestartStrategy(
            config.get(RuntimeOptions.RESTART_ATTEMPTS),
            config.get(RuntimeOptions.RESTART_DELAY))
    if kind == "failure-rate":
        return FailureRateRestartStrategy(
            config.get(RuntimeOptions.FAILURE_RATE_MAX),
            interval=config.get(RuntimeOptions.FAILURE_RATE_INTERVAL),
            delay=config.get(RuntimeOptions.FAILURE_RATE_DELAY))
    return ExponentialDelayRestartStrategy(
        config.get(RuntimeOptions.BACKOFF_INITIAL),
        config.get(RuntimeOptions.BACKOFF_MAX))
