"""Pipelined-region failover calculation.

Analog of the reference's RestartPipelinedRegionFailoverStrategy
(flink-runtime executiongraph/failover/
RestartPipelinedRegionFailoverStrategy.java:110) + the region build in
LogicalPipelinedRegionComputeUtil: a failover REGION is a maximal set of
vertices connected by pipelined edges; a task failure restarts exactly
the regions reachable from it. Every streaming edge here is pipelined
(there is no blocking/batch exchange), so regions are the connected
components of the job graph — one region for a typical connected job,
several for jobs with disconnected pipelines (independent source->sink
chains submitted as one job), which then fail over independently.
"""

from __future__ import annotations

from ..graph.stream_graph import JobGraph

__all__ = ["compute_regions", "affected_vertices", "region_task_ids"]


def compute_regions(job_graph: JobGraph) -> list[set[str]]:
    """Connected components over (pipelined) edges, as vertex-id sets."""
    parent: dict[str, str] = {v: v for v in job_graph.vertices}

    def find(x: str) -> str:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for e in job_graph.edges:
        a, b = find(e.source_vertex), find(e.target_vertex)
        if a != b:
            parent[a] = b
    groups: dict[str, set[str]] = {}
    for v in job_graph.vertices:
        groups.setdefault(find(v), set()).add(v)
    return list(groups.values())


def affected_vertices(regions: list[set[str]],
                      failed_task_ids: list[str]) -> set[str]:
    """Union of the regions containing the failed tasks."""
    failed_vids = {t.rsplit("#", 1)[0] for t in failed_task_ids}
    out: set[str] = set()
    for region in regions:
        if region & failed_vids:
            out |= region
    return out


def region_task_ids(job_graph: JobGraph, vids: set[str]) -> list[str]:
    return [f"{vid}#{s}"
            for vid in vids
            for s in range(job_graph.vertices[vid].parallelism)]
