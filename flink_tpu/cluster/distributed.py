"""Multi-host execution: SPMD deployment over the TCP data plane.

The multi-host shape of the reference's cluster runtime (SURVEY §2.3/§3.1:
JobMaster deploys subtasks to TaskExecutors over RPC, data flows
TaskExecutor⇄TaskExecutor over Netty), re-designed the TPU-native way:
instead of shipping serialized user code to workers, every host runs THE
SAME program (the multi-host JAX/SPMD model — identical script on every
host, `jax.distributed`-style), builds the identical JobGraph locally, and
executes only the subtasks placed on it. No code serialization, no
classloaders — topology agreement comes from program identity, exactly like
a pjit mesh program.

* Placement: subtask (vertex, i) lives on host ``i % n_hosts`` — every
  vertex spreads across hosts, so keyed exchanges genuinely cross the wire.
* Data plane: local edges use in-process channels; cross-host edges use
  transport.py TCP channels with credit backpressure.
* Control plane (host 0 = coordinator, reference JobMaster + heartbeats):
  workers register and heartbeat over a control TCP socket; the coordinator
  triggers distributed checkpoints (workers inject barriers into their
  source subtasks, acks flow back, completion broadcasts notify), detects
  dead workers by heartbeat timeout, and broadcasts cancellation.

Checkpoint snapshots are acknowledged with their task state to the
coordinator, which persists them through the configured CheckpointStorage —
task ids are host-agnostic ("v3#1"), so a restore can re-deploy on any
topology (same key-group math as local rescaling).
"""

from __future__ import annotations

import json
import pickle
import socket
import struct
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..checkpoint.storage import (
    CheckpointNotFoundError, CompletedCheckpoint, CorruptArtifactError,
    FsCheckpointStorage, MemoryCheckpointStorage,
)
from ..core.config import (
    CheckpointingOptions, Configuration, HaOptions, RuntimeOptions,
    StateOptions,
)
from .failover import restart_strategy_from_config
from .ha import FileHaServices, LeaderElectionService, read_leader_record
from .resource_manager import SlotManager, build_schedule
from ..graph.stream_graph import JobGraph
from ..runtime.channels import InputGate, LocalChannel
from ..runtime.watchdog import StallError, TaskStallDetector
from ..runtime.operators.base import OperatorChain, OperatorContext
from ..runtime.stream_task import (
    OneInputStreamTask, SourceStreamTask, StreamTask, TwoInputStreamTask,
)
from ..runtime.writer import RecordWriter
from .local import LocalJob, _make_reader, _side_outputs_map
from .transport import RemoteChannelSender, TransportServer

__all__ = ["CoordinatorContender", "DistributedHost", "run_distributed",
           "subtask_host"]

_MSG = struct.Struct("<I")

#: Sentinel: checkpoints existed but none passed verification — the
#: restart must fail the job, never silently redeploy from scratch.
_NO_VERIFIED_CHECKPOINT = object()


def subtask_host(subtask: int, n_hosts: int) -> int:
    """Placement function — deterministic on every host (SPMD)."""
    return subtask % n_hosts


def _send_msg(sock: socket.socket, obj: dict) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_MSG.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    head = b""
    while len(head) < _MSG.size:
        chunk = sock.recv(_MSG.size - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = _MSG.unpack(head)
    body = b""
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        if not chunk:
            return None
        body += chunk
    return pickle.loads(body)


@dataclass
class _WorkerState:
    host_id: int
    sock: socket.socket
    last_heartbeat: float
    finished: bool = False
    # the worker's vertex-id -> uid map: SPMD graphs are structurally
    # identical but generated vertex ids may differ (process-global
    # counter when several graphs are built in one process), so snapshot
    # task ids are canonicalized through uids
    uids: dict = None
    # serializes sends to this worker's socket: broadcasts originate from
    # several coordinator threads and a large inline-checkpoint restart
    # payload must not interleave with control frames
    send_lock: threading.Lock = field(default_factory=threading.Lock)
    # latest per-group watermark minima shipped with this worker's
    # heartbeat (cross-host watermark alignment)
    wm_minima: dict = field(default_factory=dict)


class _Coordinator:
    """Host-0 control plane: registration, heartbeats, checkpoints,
    completion (reference JobMaster + CheckpointCoordinator + heartbeat
    services, collapsed onto one control socket per worker)."""

    def __init__(self, n_hosts: int, config: Configuration, port: int = 0,
                 ha: Optional[FileHaServices] = None, token: int = -1,
                 job_id: str = "job", owner: str = "coord"):
        self.n_hosts = n_hosts
        self.config = config
        # coordinator failover (docs/ROBUSTNESS.md, 'Coordinator
        # failover'): with an HA service attached, every trigger,
        # completion, and restart is journaled under this leader's
        # fencing ``token``; a REFUSED write means a successor holds a
        # higher token — this coordinator is a zombie and deposes itself
        # instead of committing anything the successor will replay
        self.ha = ha
        self.token = token
        self.job_id = job_id
        self.owner = owner
        self._closed = False
        self._deposed = threading.Event()
        self._takeover: Optional[dict] = None
        self._worker_addrs: dict[int, Any] = {}
        self.on_deposed: Optional[Callable[[], None]] = None
        self.on_crash: Optional[Callable[[], None]] = None
        directory = config.get(CheckpointingOptions.DIRECTORY)
        self.storage = (FsCheckpointStorage(directory, config=config)
                        if directory else MemoryCheckpointStorage())
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", port))
        self._srv.listen(n_hosts + 4)
        self.port = self._srv.getsockname()[1]
        self._workers: dict[int, _WorkerState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.failed: Optional[str] = None
        self._next_cid = 1
        self._pending_acks: dict[int, dict[str, dict]] = {}
        self._pending_hosts: dict[int, set[int]] = {}
        # root SpanBuilder per in-flight checkpoint: its context rides the
        # trigger broadcast so worker-side Align/Snapshot spans join the
        # coordinator's tree across the transport boundary
        self._pending_spans: dict[int, Any] = {}
        self.completed: list[CompletedCheckpoint] = []
        self._vertex_parallelism: dict[str, int] = {}
        self._vertex_uids: dict[str, str] = {}
        # distributed failover (reference RestartPipelinedRegionFailover-
        # Strategy + backoff): epoch counts execution attempts; on worker
        # death the job redeploys over the survivors from the latest
        # completed checkpoint instead of cancelling
        self.epoch = 0
        self.restarts = 0
        self._strategy = restart_strategy_from_config(config)
        self._expected: set[int] = set(range(n_hosts))
        # slot registry + blocklist (reference ResourceManager/SlotManager +
        # BlocklistHandler): registrations carry slot counts; a dead worker
        # is blocklisted so a zombie re-registration never rejoins placement
        self.resources = SlotManager()
        self._all_done_sent = False
        self._restart_inflight = False
        # derived from the configured heartbeat interval AT CONSTRUCTION
        # (same formula run() passes to monitor()): a worker dying before
        # monitor() starts is now detected with the configured window, not
        # a hard-coded 5 s that a short interval was supposed to shrink
        self._hb_timeout = (
            3 * config.get(RuntimeOptions.HEARTBEAT_INTERVAL) + 2.0)
        self._last_restart_ts = 0.0
        # bounded failure history (FailureHandlingResult analog): worker
        # failure reports and restart decisions, oldest evicted first
        from collections import deque
        self.failure_history: deque = deque(maxlen=64)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="coord-accept", daemon=True)
        self._accept_thread.start()

    def set_topology(self, jg: JobGraph) -> None:
        self._vertex_parallelism = {vid: v.parallelism
                                    for vid, v in jg.vertices.items()}
        self._vertex_uids = {vid: v.uid for vid, v in jg.vertices.items()
                             if v.uid}

    # -- worker connections ------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_worker, args=(conn,),
                             name="coord-worker", daemon=True).start()

    def _fence(self, conn: socket.socket, host_id, msg_epoch,
               kind: str, terminal: bool = True) -> None:
        """Reject a deposed attempt's control message with an explicit
        ``fenced`` reply (reference JobMaster fencing tokens): the zombie
        learns it lost ownership and cancels its local attempt instead of
        retrying into the void. ``terminal=False`` marks an informational
        fence for a stale message from a worker that is NOT blocklisted
        (e.g. a pre-restart report racing the epoch bump) — the worker
        must not cancel the attempt it is still a healthy member of."""
        from ..metrics.device import DEVICE_STATS
        DEVICE_STATS.note_zombie_fenced("coordinator")
        with self._lock:
            self.failure_history.append({
                "timestamp": time.time(), "kind": "zombie-fenced",
                "host": host_id, "epoch": msg_epoch,
                "current_epoch": self.epoch, "message": kind})
        try:
            _send_msg(conn, {"type": "fenced", "epoch": self.epoch,
                             "terminal": terminal})
        except OSError:
            pass

    def _serve_worker(self, conn: socket.socket) -> None:
        host_id = None
        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn)
                if msg is None:
                    return
                kind = msg["type"]
                sender = msg.get("host_id", host_id)
                if (sender is not None
                        and self.resources.blocklist.is_blocked(sender)):
                    # a blocklisted host is a deposed attempt by
                    # definition: every message kind is fenced, and a
                    # zombie re-registration never rejoins placement
                    self._fence(conn, sender, msg.get("epoch"), kind)
                    continue
                if kind == "register":
                    host_id = msg["host_id"]
                    with self._lock:
                        prev = self._workers.get(host_id)
                        w = _WorkerState(host_id, conn, time.time(),
                                         uids=msg.get("uids") or {})
                        if prev is not None and prev.sock is conn:
                            # re-registration for a new attempt over the
                            # SAME connection: keep the send lock — a
                            # broadcast thread may already hold it
                            w.send_lock = prev.send_lock
                        self._workers[host_id] = w
                        self._all_done_sent = False
                    self.resources.register_worker(host_id,
                                                   msg.get("slots", 1))
                    if msg.get("data_addr") is not None:
                        with self._lock:
                            self._worker_addrs[host_id] = msg["data_addr"]
                elif kind == "heartbeat":
                    with self._lock:
                        w = self._workers.get(msg["host_id"])
                        if w:
                            w.last_heartbeat = time.time()
                            w.wm_minima = msg.get("wm_minima", {})
                elif kind == "ack":
                    self._on_ack(msg)
                elif kind == "decline":
                    with self._lock:
                        self._pending_acks.pop(msg["checkpoint_id"], None)
                        self._pending_hosts.pop(msg["checkpoint_id"], None)
                        sp = self._pending_spans.pop(
                            msg["checkpoint_id"], None)
                    if sp is not None:
                        sp.set_attribute("aborted", True).set_attribute(
                            "declined_by", msg.get("host_id")).finish()
                elif kind == "finished":
                    with self._lock:
                        # a stale pre-restart completion must not mark the
                        # redeployed attempt finished (it would fake
                        # all_finished and stop checkpointing)
                        if msg.get("epoch", self.epoch) == self.epoch:
                            w = self._workers.get(msg["host_id"])
                            if w:
                                w.finished = True
                elif kind == "failed":
                    with self._lock:
                        stale = (msg.get("epoch", 0) < self.epoch
                                 or self.failed is not None)
                        if not stale:
                            self.failure_history.append({
                                "timestamp": time.time(),
                                "host": msg["host_id"],
                                "epoch": msg.get("epoch", 0),
                                "kind": "task-failure",
                                "error": msg.get("error", "unknown")})
                    if stale:
                        # a previous attempt's report, already handled —
                        # answer with a non-terminal fence so the sender
                        # can tell "ignored as stale" from a lost message
                        self._fence(conn, msg["host_id"],
                                    msg.get("epoch", 0), "failed",
                                    terminal=False)
                    elif not self._maybe_restart(
                            [], f"task failure on host {msg['host_id']}: "
                                f"{msg.get('error', 'unknown')}"):
                        with self._lock:
                            self.failed = msg.get("error", "unknown")
                        self.broadcast({"type": "cancel"})
        except OSError:
            pass

    def broadcast(self, msg: dict) -> None:
        from ..runtime.watchdog import StallError, stall_bounded
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            def _send(w=w):
                with w.send_lock:
                    _send_msg(w.sock, msg)
            try:
                # deadline-bounded (site rpc.send): a worker whose socket
                # accepts a byte per minute must not wedge the control
                # plane — a stalled send is skipped like a severed one,
                # and the worker's missed heartbeats finish the job
                stall_bounded("rpc.send", _send,
                              scope=f"coord->host{w.host_id}", retries=0)
            except (OSError, StallError):
                pass

    # -- checkpointing -----------------------------------------------------
    def trigger_checkpoint(self, is_savepoint: bool = False) -> int:
        """Returns the checkpoint id, or -1 when not all hosts have
        registered yet (triggering early would complete with a subset of
        the tasks — not a consistent snapshot)."""
        from ..metrics.tracing import TRACER
        with self._lock:
            if not set(self._workers) >= self._expected:
                return -1
            cid = self._next_cid
            self._next_cid += 1
            self._pending_acks[cid] = {}
            self._pending_hosts[cid] = set(self._workers)
            span = None
            if TRACER.enabled:
                span = (TRACER.span("checkpoint", "Checkpoint")
                        .set_attribute("checkpointId", cid)
                        .set_attribute("savepoint", is_savepoint)
                        .set_attribute("hosts", len(self._pending_hosts[cid])))
                self._pending_spans[cid] = span
        if self.ha is not None and not self._journal_ha("trigger"):
            # fenced: a successor leads. Roll the trigger back — the
            # journaled next_cid the successor adopted already covers this
            # cid, so its checkpoint directories can never collide with a
            # zombie's in-flight ones
            with self._lock:
                self._pending_acks.pop(cid, None)
                self._pending_hosts.pop(cid, None)
                orphan = self._pending_spans.pop(cid, None)
            if orphan is not None:
                orphan.set_attribute("aborted", True) \
                      .set_attribute("fenced", True).finish()
            return -1
        self.broadcast({"type": "trigger_checkpoint", "checkpoint_id": cid,
                        "savepoint": is_savepoint,
                        "trace": span.context.to_wire() if span else None})
        return cid

    def _canonical_snapshots(self, host_id: int, snapshots: dict) -> dict:
        """Remap a worker's snapshot task ids onto THIS coordinator's
        vertex ids via operator uids, so one checkpoint never mixes two
        processes' generated ids for the same operator."""
        with self._lock:
            w = self._workers.get(host_id)
            worker_uids = dict(w.uids) if w and w.uids else {}
        if not worker_uids:
            return snapshots
        uid_to_canonical = {uid: vid for vid, uid in self._vertex_uids.items()}
        out = {}
        for task_id, snap in snapshots.items():
            vid, sub = task_id.rsplit("#", 1)
            uid = worker_uids.get(vid)
            canonical = uid_to_canonical.get(uid, vid) if uid else vid
            out[f"{canonical}#{sub}"] = snap
        return out

    def _on_ack(self, msg: dict) -> None:
        # a zombie attempt's checkpoint ack must never complete a
        # checkpoint for the current attempt (split-brain: its snapshots
        # describe deposed state); the pending-ack table alone does not
        # protect against this because checkpoint ids keep counting up
        if msg.get("epoch", self.epoch) != self.epoch:
            return
        cid = msg["checkpoint_id"]
        complete = None
        snapshots = self._canonical_snapshots(msg["host_id"],
                                              msg["snapshots"])
        with self._lock:
            epoch = self.epoch
            if cid not in self._pending_acks:
                return
            self._pending_acks[cid].update(snapshots)
            self._pending_hosts[cid].discard(msg["host_id"])
            if not self._pending_hosts[cid]:
                snaps = self._pending_acks.pop(cid)
                sp = msg.get("savepoint", False)
                if sp:
                    from ..checkpoint.coordinator import \
                        savepoint_self_contained
                    snaps = savepoint_self_contained(snaps, self.config)
                complete = CompletedCheckpoint(
                    checkpoint_id=cid, timestamp=time.time(),
                    task_snapshots=snaps,
                    is_savepoint=sp,
                    vertex_parallelism=dict(self._vertex_parallelism),
                    vertex_uids=dict(self._vertex_uids))
                del self._pending_hosts[cid]
        if complete is not None and self.ha is not None:
            # cheap fence check BEFORE the (possibly large) store: a
            # deposed leader must not even write the artifact, let alone
            # complete the checkpoint
            lease = self.ha._lease_token()
            if lease is not None and lease > self.token:
                with self._lock:
                    orphan = self._pending_spans.pop(cid, None)
                if orphan is not None:
                    orphan.set_attribute("aborted", True) \
                          .set_attribute("fenced", True).finish()
                self._depose(f"deposed before storing checkpoint {cid}")
                return
        if complete is not None:
            from ..metrics.tracing import TRACER
            with self._lock:
                root_sb = self._pending_spans.pop(cid, None)
            store_sb = (TRACER.span("checkpoint", "Store",
                                    parent=root_sb.context)
                        .set_attribute("checkpointId", cid)
                        if root_sb is not None else None)
            try:
                complete = self.storage.store(complete)
            except Exception as e:  # noqa: BLE001 - storage outage
                # tolerate the failed WRITE: the job runs on against its
                # previous completed checkpoint (reference tolerable
                # checkpoint failures); record the event and move on
                with self._lock:
                    self.failure_history.append({
                        "timestamp": time.time(), "checkpoint": cid,
                        "kind": "checkpoint-write-failure",
                        "error": f"{type(e).__name__}: {e}"})
                if store_sb is not None:
                    store_sb.set_attribute("error", True).finish()
                    root_sb.set_attribute("error", True).finish()
                return
            if store_sb is not None:
                store_sb.finish()
            with self._lock:
                if self.epoch != epoch:
                    # a restart was arranged while this checkpoint was in
                    # storage.store: the restore candidate was chosen
                    # WITHOUT it, so completing it now would commit sink
                    # output the restored attempt is about to replay —
                    # discard the orphan instead of breaking exactly-once
                    self.failure_history.append({
                        "timestamp": time.time(), "checkpoint": cid,
                        "kind": "checkpoint-superseded",
                        "epoch": epoch, "current_epoch": self.epoch})
                    if root_sb is not None:
                        root_sb.set_attribute("aborted", True).finish()
                    return
                self.completed.append(complete)
            if self.ha is not None:
                # fenced commit point: the checkpoint pointer and journal
                # must land under OUR token before any worker is told to
                # commit — a refusal means a successor exists, and its
                # restore would replay the sink output this notification
                # would have committed
                ok = self.ha.put_checkpoint(
                    self.job_id, self.token,
                    {"checkpoint_id": cid,
                     "external_path": complete.external_path,
                     "timestamp": complete.timestamp})
                ok = ok and self._journal_ha(f"checkpoint-{cid}-complete")
                if not ok:
                    with self._lock:
                        if complete in self.completed:
                            self.completed.remove(complete)
                    if root_sb is not None:
                        root_sb.set_attribute("aborted", True) \
                               .set_attribute("fenced", True).finish()
                    self._depose(f"checkpoint {cid} completion fenced")
                    return
            # stamped with the epoch CAPTURED at ack time (not re-read:
            # a concurrent bump would stamp the new epoch and defeat the
            # workers' gate) so a worker that restarted between the ack
            # and this fan-out drops the notification instead of
            # committing a deposed attempt's pending output
            self.broadcast({"type": "checkpoint_complete",
                            "checkpoint_id": cid,
                            "epoch": epoch,
                            "savepoint": complete.is_savepoint})
            if root_sb is not None:
                (TRACER.span("checkpoint", "Notify", parent=root_sb.context)
                 .set_attribute("checkpointId", cid)
                 .set_attribute("hosts", self.n_hosts)
                 .finish())
                root_sb.finish()

    # -- coordinator failover (HA) ----------------------------------------
    def _journal_locked(self) -> dict:
        """Everything a successor needs to take over the RUNNING job
        (caller holds ``self._lock``): attempt epoch, the next checkpoint
        id, expected hosts + slots, worker data addresses, and the last
        few completed-checkpoint pointers (metadata only — the artifacts
        live in shared checkpoint storage)."""
        live = sorted(self._workers)
        from ..core.config import AotOptions
        return {
            "epoch": self.epoch,
            "next_cid": self._next_cid,
            # journaled next to the checkpoint pointers so a successor
            # master can warm-start the AOT executable cache before it
            # redeploys (compile-storm-free recovery)
            "aot_dir": str(self.config.get(AotOptions.DIR) or ""),
            "restarts": self.restarts,
            "expected": sorted(self._expected),
            "slots": self.resources.slots_map(live),
            "worker_addrs": dict(self._worker_addrs),
            "completed": [
                {"checkpoint_id": c.checkpoint_id,
                 "external_path": c.external_path,
                 "is_savepoint": c.is_savepoint}
                for c in self.completed[-8:] if c.external_path],
        }

    def _journal_ha(self, event: str) -> bool:
        """Journal takeover state into the HA store under this leader's
        fencing token. Returns False — after deposing this coordinator —
        when the write was refused (a successor holds a higher token)."""
        if self.ha is None:
            return True
        with self._lock:
            journal = self._journal_locked()
        if self.ha.put_journal(self.job_id, self.token, journal):
            return True
        self._depose(f"journal write fenced at {event}")
        return False

    def _depose(self, reason: str) -> None:
        """A fenced HA write revealed a successor: this coordinator is a
        zombie. Stop leading NOW — close the server and every worker
        control socket so the workers re-resolve the leader record and
        re-register with the successor. The job is NOT failed: it keeps
        running under the new leader."""
        if self._deposed.is_set():
            return
        self._deposed.set()
        from ..metrics.device import DEVICE_STATS
        DEVICE_STATS.note_zombie_fenced("coordinator-deposed")
        with self._lock:
            self.failure_history.append({
                "timestamp": time.time(), "kind": "leader-deposed",
                "token": self.token, "reason": reason})
        if self.on_deposed is not None:
            try:
                self.on_deposed()
            except Exception:  # noqa: BLE001 - best-effort notification
                pass
        self.close()

    def crash(self) -> None:
        """Simulated leader kill (site coord.crash / test hook): drop the
        server and every worker control socket with no cleanup and no HA
        release — exactly what SIGKILL leaves behind. ``on_crash`` lets
        the owning contender stop renewing its lease so a standby must
        steal it the hard way."""
        if self.on_crash is not None:
            try:
                self.on_crash()
            except Exception:  # noqa: BLE001 - crash must not half-fail
                pass
        self.close()

    def adopt_journal(self, journal: dict) -> None:
        """Resume a predecessor's job state after winning the election:
        attempt epoch (hot takeover keeps it — the data-plane edge keys
        and transport fencing are epoch-derived, so bumping it would kill
        live channels; the LEASE token is the fencing epoch that bumped),
        the checkpoint-id counter (so this leader's chk-N directories
        never collide with a zombie's in-flight ones), expected hosts,
        and the retained completed-checkpoint pointers."""
        with self._lock:
            self.epoch = int(journal.get("epoch", self.epoch))
            self._next_cid = max(self._next_cid,
                                 int(journal.get("next_cid", 1)))
            self.restarts = int(journal.get("restarts", 0))
            expected = journal.get("expected")
            if expected:
                self._expected = {int(h) for h in expected}
            self._worker_addrs = dict(journal.get("worker_addrs") or {})
        if isinstance(self.storage, FsCheckpointStorage):
            adopted = []
            for rec in journal.get("completed", []):
                path = rec.get("external_path")
                if not path:
                    continue
                try:
                    cp = self.storage.load(path, resolve=False)
                except (OSError, CheckpointNotFoundError,
                        CorruptArtifactError):
                    continue  # verified-candidate walk handles the rest
                adopted.append(cp)
            with self._lock:
                known = {c.checkpoint_id for c in self.completed}
                for cp in adopted:
                    if cp.checkpoint_id not in known:
                        self.completed.append(cp)
                self.completed.sort(key=lambda c: c.checkpoint_id)

    def arm_takeover(self, expected: set[int], t0: float,
                     span: Any = None) -> None:
        """Start the takeover clock: ``monitor`` resolves it HOT the
        moment every expected worker has re-registered, or falls back to
        a fenced restore when ``ha.takeover-timeout`` expires first."""
        deadline = t0 + float(self.config.get(HaOptions.TAKEOVER_TIMEOUT))
        with self._lock:
            self._takeover = {"expected": set(expected), "t0": t0,
                              "deadline": deadline, "span": span}

    def _resolve_takeover(self) -> None:
        with self._lock:
            tk = self._takeover
            if tk is None:
                return
            missing = tk["expected"] - set(self._workers)
            if missing and time.time() < tk["deadline"]:
                return
            self._takeover = None
        from ..metrics.device import DEVICE_STATS
        from ..metrics.tracing import dump_flight_recorder
        took_ms = (time.time() - tk["t0"]) * 1000.0
        mode = "hot" if not missing else "restore"
        DEVICE_STATS.note_coordinator_failover(took_ms, mode)
        span = tk.get("span")
        if span is not None:
            (span.set_attribute("mode", mode)
                 .set_attribute("missing", sorted(missing))
                 .set_attribute("took_ms", round(took_ms, 1))
                 .finish())
        dump_flight_recorder("failover", mode=mode, token=self.token,
                             epoch=self.epoch, took_ms=round(took_ms, 1),
                             missing=sorted(missing))
        with self._lock:
            self.failure_history.append({
                "timestamp": time.time(), "kind": "takeover", "mode": mode,
                "token": self.token, "took_ms": round(took_ms, 1),
                "missing": sorted(missing)})
        if missing:
            # workers died alongside the old leader: declare them dead
            # and fall back to a fenced global restore from the latest
            # verified checkpoint — exactly-once either way
            from ..runtime.watchdog import WATCHDOG
            WATCHDOG.note_stall(
                "ha.takeover",
                float(self.config.get(HaOptions.TAKEOVER_TIMEOUT)),
                scope="coordinator")
            reason = (f"takeover: worker(s) {sorted(missing)} did not "
                      "re-register within ha.takeover-timeout")
            if not self._maybe_restart(sorted(missing), reason):
                with self._lock:
                    self.failed = reason
                self.broadcast({"type": "cancel"})

    # -- failover ----------------------------------------------------------
    def _verified_candidate_locked(self):
        """Newest completed checkpoint whose on-disk artifact verifies
        (caller holds ``self._lock``). Corrupt candidates are counted,
        recorded in the failure history (kind ``corrupt-artifact``),
        quarantined (``<dir>.corrupt``), and dropped from the retained
        list — the walk falls back to the next-oldest. Returns None when
        no checkpoint ever completed (restart from scratch is legitimate
        then), or the ``_NO_VERIFIED_CHECKPOINT`` sentinel when
        checkpoints existed but every one failed verification."""
        from ..metrics.device import DEVICE_STATS

        verify = self.config.get(CheckpointingOptions.VERIFY_ON_RESTORE)
        quarantine = self.config.get(
            CheckpointingOptions.QUARANTINE_CORRUPT)
        dropped = 0
        while self.completed:
            cand = self.completed[-1]
            if (not verify
                    or not isinstance(self.storage, FsCheckpointStorage)
                    or not cand.external_path):
                break
            try:
                self.storage.verify_checkpoint(cand.external_path)
            except (CorruptArtifactError, CheckpointNotFoundError) as e:
                dropped += 1
                self.completed.pop()
                DEVICE_STATS.note_verify_failure("checkpoint.restore")
                self.failure_history.append({
                    "timestamp": time.time(), "kind": "corrupt-artifact",
                    "checkpoint": cand.checkpoint_id,
                    "path": cand.external_path,
                    "error": f"{type(e).__name__}: {e}"})
                if quarantine:
                    self.storage.quarantine(cand)
                continue
            break
        if not self.completed and dropped:
            return _NO_VERIFIED_CHECKPOINT
        cp = self.completed[-1] if self.completed else None
        if dropped and cp is not None:
            DEVICE_STATS.note_restore_fallback("checkpoint.restore")
            self.failure_history.append({
                "timestamp": time.time(), "kind": "restore-fallback",
                "checkpoint": cp.checkpoint_id, "skipped": dropped})
        return cp

    def _maybe_restart(self, dead: list[int], reason: str) -> bool:
        """Redeploy the job over the surviving workers from the latest
        completed checkpoint (reference region failover collapsed to
        whole-job: every surviving host restarts its subtasks; the dead
        host's subtasks move to survivors via the shared placement
        function). Returns False when the strategy is exhausted/disabled —
        caller falls back to fail+cancel. The actual restart runs on its
        own thread: it first waits out the heartbeat window so 'which
        hosts are alive' is settled truth, not a race with the failure
        report (a task failure often precedes the peer's heartbeat
        expiry)."""
        with self._lock:
            if self._restart_inflight:
                return True  # a restart is already being arranged
            self._strategy.notify_failure()
            if not self._strategy.can_restart():
                return False
            self._restart_inflight = True
        threading.Thread(target=self._do_restart, args=(list(dead), reason),
                         name="coord-restart", daemon=True).start()
        return True

    def _do_restart(self, dead: list[int], reason: str) -> None:
        grace = max(self._strategy.backoff_seconds(), self._hb_timeout)
        time.sleep(grace)
        now = time.time()
        with self._lock:
            stale = [w.host_id for w in self._workers.values()
                     if not w.finished
                     and now - w.last_heartbeat > self._hb_timeout]
            for d in set(dead) | set(stale):
                w = self._workers.pop(d, None)
                if w is not None:
                    try:
                        w.sock.close()
                    except OSError:
                        pass
                self.resources.unregister_worker(d)
                self.resources.blocklist.block(d, reason)
            live = sorted(h for h in self._workers
                          if not self.resources.blocklist.is_blocked(h))
            if not live:
                self._restart_inflight = False
                self.failed = f"{reason}; no surviving workers"
                self.broadcast({"type": "cancel"})
                return
            self.epoch += 1
            self.restarts += 1
            self._last_restart_ts = now
            self.failure_history.append({
                "timestamp": now, "kind": "restart", "epoch": self.epoch,
                "reason": reason, "live_hosts": list(live)})
            epoch = self.epoch
            # abandoned checkpoints die with the deposed attempt
            orphan_spans = list(self._pending_spans.values())
            self._pending_spans.clear()
            self._expected = set(live)
            self._all_done_sent = False
            self._pending_acks.clear()
            self._pending_hosts.clear()
            for w in self._workers.values():
                w.finished = False
            cp = self._verified_candidate_locked()
            self._restart_inflight = False
        if self.ha is not None and not self._journal_ha("restart"):
            # deposed: the successor owns the restart decision
            for sp in orphan_spans:
                sp.set_attribute("aborted", True).finish()
            return
        from ..metrics.tracing import TRACER, dump_flight_recorder
        for sp in orphan_spans:
            sp.set_attribute("aborted", True).finish()
        dump_flight_recorder("job-restart", epoch=epoch, cause=reason,
                             live_hosts=list(live))
        restart_sb = (TRACER.span("restart", "JobRestart")
                      .set_attribute("epoch", epoch)
                      .set_attribute("reason", reason)
                      .set_attribute("live_hosts", list(live)))
        if cp is _NO_VERIFIED_CHECKPOINT:
            # checkpoints existed but none verifies: redeploying from
            # scratch would replay the whole stream past committed output
            # — fail the job with the typed corruption error instead
            with self._lock:
                self.failed = (f"{reason}; CorruptArtifactError: all "
                               "retained checkpoints failed verification")
            self.broadcast({"type": "cancel"})
            restart_sb.set_attribute("error", True).finish()
            return
        msg = {"type": "restart", "epoch": epoch, "live_hosts": live,
               "slots": self.resources.slots_map(live),
               "reason": reason, "checkpoint_path": None, "checkpoint": None}
        if cp is not None:
            if cp.external_path:
                msg["checkpoint_path"] = cp.external_path
            else:
                msg["checkpoint"] = cp  # in-memory storage: ship it inline
        self.broadcast(msg)
        (restart_sb
         .set_attribute("restored",
                        cp.checkpoint_id if cp is not None else None)
         .finish())

    # -- liveness ----------------------------------------------------------
    def monitor(self, heartbeat_timeout: float) -> None:
        """Heartbeat-timeout failure detection (reference
        HeartbeatManagerImpl): a dead worker triggers redeploy-from-
        checkpoint under the configured restart strategy, job failure
        when restarts are disabled/exhausted. Also announces global
        completion (all_done) so workers that finished early stay
        available for failover until the whole job is done."""
        from ..runtime.faults import FAULTS
        self._hb_timeout = heartbeat_timeout
        while not self._stop.is_set():
            time.sleep(heartbeat_timeout / 3)
            if FAULTS.enabled and FAULTS.check("coord.crash"):
                # chaos drill: the leader dies mid-flight — every socket
                # drops and (via on_crash) its lease stops renewing, so a
                # standby steals leadership and takes the job over
                self.crash()
                return
            self._resolve_takeover()
            now = time.time()
            # cross-host watermark alignment: combine live workers' group
            # minima, broadcast the global view (reference SourceCoordinator
            # announceCombinedWatermark over the OperatorCoordinator RPC)
            combined: dict[str, int] = {}
            with self._lock:
                for w in self._workers.values():
                    if w.finished:
                        continue  # stale minima must not hold the group back
                    for g, m in (w.wm_minima or {}).items():
                        combined[g] = min(m, combined.get(g, m))
            # broadcast even when empty: workers REPLACE their remote view,
            # so a finished group's stale minimum stops constraining anyone
            self.broadcast({"type": "wm_alignment", "minima": combined})
            with self._lock:
                dead = [w.host_id for w in self._workers.values()
                        if not w.finished
                        and now - w.last_heartbeat > heartbeat_timeout]
            if (not dead and self.restarts and self._last_restart_ts
                    and now - self._last_restart_ts > 2 * heartbeat_timeout):
                # a healthy stretch after a restart resets the restart
                # strategy's escalation (backoff returns to initial) —
                # without this, one bad hour a week escalates forever
                self._strategy.notify_recovered()
                with self._lock:
                    self._last_restart_ts = 0.0
            if dead and self.failed is None:
                if not self._maybe_restart(
                        dead, f"worker(s) {dead} missed heartbeats"):
                    with self._lock:
                        self.failed = f"worker(s) {dead} missed heartbeats"
                    self.broadcast({"type": "cancel"})
            if self.all_finished():
                with self._lock:
                    send = not self._all_done_sent
                    self._all_done_sent = True
                if send:
                    self.broadcast({"type": "all_done"})

    def all_finished(self) -> bool:
        with self._lock:
            return (set(self._workers) >= self._expected
                    and all(w.finished for w in self._workers.values()))

    def close(self) -> None:
        """Idempotent teardown: safe from the contender's revoke path,
        the depose path, crash(), and host shutdown all at once. Closes
        the listening socket (releasing the port immediately — a standby
        promoted on the same host must never hit EADDRINUSE) AND every
        worker control socket, so connected workers notice leadership
        loss at once instead of waiting out a heartbeat window."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            workers = list(self._workers.values())
        self._stop.set()
        # shutdown() wakes the thread blocked in accept(); without it the
        # blocked syscall keeps a kernel reference to the socket and the
        # port stays bound past close() — the EADDRINUSE a promoted
        # standby on the same host would hit
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        if threading.current_thread() is not self._accept_thread:
            self._accept_thread.join(timeout=1.0)
        for w in workers:
            try:
                w.sock.close()
            except OSError:
                pass


class CoordinatorContender:
    """A would-be coordinator master: contends for leadership over the
    job's HA dir and, when granted, promotes a fresh ``_Coordinator`` on
    its own port, publishes the fenced leader record so workers can find
    it, adopts the predecessor's journal, and resolves the takeover —
    HOT when every journaled worker re-registers within
    ``ha.takeover-timeout`` (no restart, checkpointing simply resumes),
    fenced restore from the latest verified checkpoint otherwise. Run
    one per would-be master process (the reference's Dispatcher /
    JobMaster leader contender, SURVEY §2.3, collapsed onto the file
    lease). SPMD applies to masters too: every contender builds the
    identical JobGraph locally, so no topology ships through the HA
    store beyond the journal's numbers."""

    def __init__(self, jg: JobGraph, config: Configuration, ha_dir: str,
                 n_hosts: int, owner: Optional[str] = None,
                 job_id: Optional[str] = None, coordinator_port: int = 0):
        self.jg = jg
        self.config = config
        self.n_hosts = n_hosts
        self.owner = owner or f"coord-{uuid.uuid4().hex[:6]}"
        self.job_id = job_id or getattr(jg, "name", None) or "job"
        self.ha = FileHaServices(ha_dir)
        self._port = coordinator_port
        self.coordinator: Optional[_Coordinator] = None
        self._coord_lock = threading.Lock()
        self._stop = threading.Event()
        self._started = False
        self._killed = False
        self._lease_timeout = float(config.get(HaOptions.LEASE_TIMEOUT))
        self.election = LeaderElectionService(
            ha_dir, self.owner, self._lease_timeout,
            on_grant=self._on_grant, on_revoke=self._on_revoke)

    def start(self) -> "CoordinatorContender":
        if self._started:
            return self
        self._started = True
        self.ha.announce_standby(self.owner)
        threading.Thread(target=self._presence_loop,
                         name=f"standby-{self.owner}", daemon=True).start()
        self.election.start()
        return self

    def _presence_loop(self) -> None:
        while not self._stop.is_set():
            self.ha.announce_standby(self.owner)
            self._stop.wait(max(self._lease_timeout, 0.5))

    def _on_grant(self, token: int) -> None:
        from ..metrics.device import DEVICE_STATS
        from ..metrics.tracing import TRACER
        DEVICE_STATS.note_leader_election("coordinator")
        t0 = time.time()
        journal = self.ha.get_journal(self.job_id)
        coord = _Coordinator(self.n_hosts, self.config, port=self._port,
                             ha=self.ha, token=token, job_id=self.job_id,
                             owner=self.owner)
        coord.set_topology(self.jg)
        coord.on_deposed = self.election.step_down
        coord.on_crash = self.kill  # coord.crash = full master death
        if journal:
            coord.adopt_journal(journal)
            # the journal carries the AOT cache location next to the
            # checkpoint pointers: warm the successor's executable cache
            # now so post-takeover redeploys never trigger a compile storm
            jdir = journal.get("aot_dir")
            if jdir:
                from ..core.config import AotOptions
                self.config.set(AotOptions.ENABLED, True)
                self.config.set(AotOptions.DIR, jdir)
            from ..runtime.aot import AOT
            AOT.configure(self.config)
            AOT.warmup()
        addr = f"127.0.0.1:{coord.port}"
        if not self.ha.publish_leader_record(token, addr, self.owner):
            # a successor was elected past us (we stalled between the
            # grant and here): never lead on a stale token
            coord.close()
            self.election.step_down()
            return
        span = None
        if journal and TRACER.enabled:
            span = (TRACER.span("ha", "Takeover")
                    .set_attribute("owner", self.owner)
                    .set_attribute("token", token)
                    .set_attribute("epoch", coord.epoch))
        if journal:
            # a predecessor ran this job: resolve hot-vs-restore against
            # ITS expected-host set
            coord.arm_takeover(set(coord._expected), t0, span=span)
        # first journal write under the new token claims the job — and
        # proves the fence: any older leader's next write now loses
        if not coord._journal_ha("takeover-grant"):
            self.election.step_down()
            return
        with self._coord_lock:
            self.coordinator = coord
        hb_timeout = (
            3 * self.config.get(RuntimeOptions.HEARTBEAT_INTERVAL) + 2.0)
        threading.Thread(target=coord.monitor, args=(hb_timeout,),
                         name=f"coord-monitor-{self.owner}",
                         daemon=True).start()
        interval = self.config.get(CheckpointingOptions.INTERVAL)
        if interval and interval > 0:
            def periodic():
                while not (self._stop.is_set() or coord._stop.is_set()):
                    time.sleep(interval)
                    if coord.all_finished() or coord._stop.is_set():
                        return
                    coord.trigger_checkpoint()
            threading.Thread(target=periodic,
                             name=f"coord-periodic-{self.owner}",
                             daemon=True).start()

    def _on_revoke(self) -> None:
        with self._coord_lock:
            coord, self.coordinator = self.coordinator, None
        if coord is not None:
            coord.close()

    def kill(self) -> None:
        """Simulated master death (tests / site coord.crash): drop every
        socket and stop renewing the lease WITHOUT releasing it — the
        standbys must steal it the hard way, exactly as after SIGKILL."""
        self._killed = True
        self._stop.set()
        self.election.stop(release=False)
        with self._coord_lock:
            coord, self.coordinator = self.coordinator, None
        if coord is not None:
            coord.close()

    def stop(self) -> None:
        """Graceful shutdown: releases the lease so a standby is granted
        immediately instead of after the full lease timeout."""
        self._stop.set()
        self.election.stop(release=True)
        self.ha.withdraw_standby(self.owner)
        with self._coord_lock:
            coord, self.coordinator = self.coordinator, None
        if coord is not None:
            coord.close()

    def run(self, timeout: float = 120.0) -> dict:
        """Contend and block until the job completes — under this master
        or any successor. Returns the published result record."""
        self.start()
        deadline = time.time() + timeout
        try:
            while time.time() < deadline and not self._stop.is_set():
                done = self.ha.get_result(self.job_id)
                if done is not None:
                    return done
                with self._coord_lock:
                    coord = self.coordinator
                if coord is not None:
                    if coord.failed is not None:
                        raise RuntimeError(coord.failed)
                    if coord.all_finished():
                        # let the monitor's all_done broadcast land so
                        # finished workers exit their stay-available loop
                        settle = time.time() + 2.0
                        while (not coord._all_done_sent
                               and time.time() < settle):
                            time.sleep(0.05)
                        result = {"status": "done", "owner": self.owner,
                                  "epoch": coord.epoch,
                                  "restarts": coord.restarts,
                                  "checkpoints": len(coord.completed)}
                        self.ha.put_result(self.job_id, coord.token,
                                           result)
                        return result
                time.sleep(0.05)
            if self._killed:
                raise RuntimeError(f"master {self.owner} was killed")
            raise TimeoutError(
                f"job {self.job_id} not done within {timeout}s")
        finally:
            if not self._killed:
                self.stop()


class DistributedHost:
    """One host's slice of a distributed job (SPMD: every host constructs
    this from the same JobGraph)."""

    def __init__(self, jg: JobGraph, config: Configuration, host_id: int,
                 n_hosts: int, coordinator_addr: Optional[str] = None,
                 data_port: int = 0, coordinator_port: int = 0,
                 ha_dir: Optional[str] = None):
        self.jg = jg
        self.config = config
        self.host_id = host_id
        self.n_hosts = n_hosts
        self.transport = TransportServer(port=data_port)
        # coordinator failover: with an HA dir (arg or ha.dir), NO host
        # embeds a coordinator — masters are separate CoordinatorContender
        # processes, and this worker resolves whoever currently leads
        # through the fenced leader record instead of a fixed address
        self._ha_dir = ha_dir or (config.get(HaOptions.DIR) or None)
        self.coordinator: Optional[_Coordinator] = None
        if host_id == 0 and self._ha_dir is None:
            self.coordinator = _Coordinator(n_hosts, config,
                                            port=coordinator_port)
            self.coordinator.set_topology(jg)
        self._coord_addr = coordinator_addr
        self._closed = False
        # set once this host announced "finished" for the current attempt:
        # after a control reconnect (e.g. a leader takeover) the new
        # coordinator must re-learn completion or all_done never fires
        self._announced_finished = threading.Event()
        self._ctrl: Optional[socket.socket] = None
        self.job: Optional[LocalJob] = None
        self._cancelled = threading.Event()
        # failover state: the control loop records a restart order and the
        # run loop redeploys; all_done releases finished workers
        self._restart_intent: Optional[dict] = None
        self._restart_event = threading.Event()
        self._all_done = threading.Event()
        self._redeploying = threading.Event()
        self._pending_ckpts: dict[int, tuple[int, bool]] = {}
        self._intent_lock = threading.Lock()
        # local recovery (reference TaskLocalStateStore /
        # LocalRecoveryConfig): keep the snapshots THIS host acked so a
        # failover restart restores surviving subtasks from the local copy
        # instead of re-reading checkpoint storage; keyed state for
        # RELOCATED subtasks still loads remotely. In-memory is the right
        # scope here: survivors restart within the same process.
        self._local_recovery = bool(config.get(StateOptions.LOCAL_RECOVERY))
        self._local_snapshots: dict[int, dict[str, dict]] = {}  # cid -> map
        self.local_restores = 0     # observability: tasks restored locally
        # control-socket sends originate from the heartbeat thread, the
        # checkpoint listener AND the run loop: serialize the frames
        self._ctrl_lock = threading.Lock()
        # partition tolerance: the attempt epoch this host is running
        # (stamped on every outgoing control message so the coordinator
        # can fence zombies), whether the coordinator fenced US, and a
        # lock so the heartbeat and control threads don't both redial
        self._epoch = 0
        self.fenced = False
        self._ctrl_reconnect_lock = threading.Lock()

    @property
    def data_address(self) -> tuple[str, int]:
        return self.transport.host, self.transport.port

    # -- deployment --------------------------------------------------------
    def deploy(self, peer_data_addrs: dict[int, tuple[str, int]],
               live_hosts: Optional[list[int]] = None, epoch: int = 0,
               restored: Optional[dict] = None,
               slots: Optional[dict[int, int]] = None) -> LocalJob:
        """Instantiate ONLY this host's subtasks; wire cross-host edges
        through the transport (the Execution.deploy analog, but locality-
        filtered by the shared placement function). ``live_hosts`` narrows
        placement to the surviving hosts after a failover (a dead host's
        subtasks move to survivors deterministically); ``epoch`` tags the
        transport streams so a restarted deployment never reads a previous
        attempt's in-flight data; ``restored`` maps task ids to checkpoint
        snapshots; ``slots`` weights placement by per-host slot capacity
        (resource_manager.build_schedule — a 2-slot host takes twice the
        subtasks of a 1-slot host)."""
        jg, config = self.jg, self.config
        from ..runtime.faults import FAULTS
        from ..runtime.watchdog import WATCHDOG
        FAULTS.configure(config)
        WATCHDOG.configure(config)
        # same on-by-default tracing wiring as the local deploy path
        from ..metrics.device import set_compile_tracer
        from ..metrics.tracing import TRACER
        TRACER.configure(config)
        set_compile_tracer(TRACER if TRACER.enabled else None)
        from ..parallel.plan import MESH_RUNTIME
        MESH_RUNTIME.configure(config)
        # device-time ledger (same wiring as deploy_local)
        from ..metrics.profiler import DEVICE_LEDGER
        DEVICE_LEDGER.configure(config)
        # multi-tenant isolation (same wiring as deploy_local)
        from .isolation import ISOLATION
        ISOLATION.configure(config)
        ISOLATION.register_job(jg.name)
        # compile-storm-free recovery: pre-load persisted AOT executables
        # before any subtask builds a program, so a freshly (re)started
        # worker process serves warm programs instead of recompiling
        from ..runtime.aot import AOT
        AOT.configure(config)
        AOT.warmup()
        if any(e.feedback for e in jg.edges):
            raise NotImplementedError(
                "iterations (feedback edges) run on the local deployment "
                "only; the distributed SPMD deploy does not wire back "
                "edges yet")
        job = LocalJob(jg, config)
        # adopt the attempt epoch on the data plane: from here on the
        # transport fences HELLOs from older (deposed) attempts
        self._epoch = epoch
        self.transport.set_epoch(epoch)
        from ..core.config import NetworkOptions
        net_kwargs = dict(
            epoch=epoch,
            reconnect_timeout=float(
                config.get(NetworkOptions.RECONNECT_TIMEOUT)),
            reconnect_backoff=float(
                config.get(NetworkOptions.RECONNECT_BACKOFF)),
            replay_capacity=int(
                config.get(NetworkOptions.REPLAY_BUFFER)))
        aligned = config.get(CheckpointingOptions.MODE) == "exactly-once"
        live = live_hosts or list(range(self.n_hosts))
        schedule = (build_schedule({h: slots.get(h, 1) for h in live})
                    if slots else list(live))

        def place(sub: int) -> int:
            return schedule[sub % len(schedule)]

        def edge_key(ei: int, src_sub: int, dst_sub: int) -> str:
            return f"E{epoch}:e{ei}:{src_sub}:{dst_sub}"

        # channels for edges touching this host
        channels: dict[tuple[int, int, int], Any] = {}
        for ei, e in enumerate(jg.edges):
            src_v = jg.vertices[e.source_vertex]
            dst_v = jg.vertices[e.target_vertex]
            for s in range(src_v.parallelism):
                for d in range(dst_v.parallelism):
                    s_here = place(s) == self.host_id
                    d_here = place(d) == self.host_id
                    if s_here and d_here:
                        channels[(ei, s, d)] = LocalChannel()
                    elif s_here:
                        host, port = peer_data_addrs[place(d)]
                        channels[(ei, s, d)] = RemoteChannelSender(
                            host, port, edge_key(ei, s, d), **net_kwargs)
                    elif d_here:
                        channels[(ei, s, d)] = self.transport.channel(
                            edge_key(ei, s, d))

        from ..core.config import WatchdogOptions
        bp_stall = float(config.get(
            WatchdogOptions.BACKPRESSURE_STALL_TIMEOUT))
        from ..metrics.core import TaskMetrics
        for vid, vertex in jg.vertices.items():
            out_edges = [(ei, e) for ei, e in enumerate(jg.edges)
                         if e.source_vertex == vid]
            in_edges = [(ei, e) for ei, e in enumerate(jg.edges)
                        if e.target_vertex == vid]
            for sub in range(vertex.parallelism):
                if place(sub) != self.host_id:
                    continue
                task_id = f"{vid}#{sub}"
                ctx = OperatorContext(
                    task_name=vertex.name, subtask_index=sub,
                    parallelism=vertex.parallelism,
                    max_parallelism=vertex.max_parallelism,
                    config=config, metrics=None, operator_id=vid,
                    kv_registry=job.kv_registry)
                writers, side_writers = [], {}
                for ei, e in out_edges:
                    dst_par = jg.vertices[e.target_vertex].parallelism
                    w = RecordWriter(
                        [channels[(ei, sub, d)] for d in range(dst_par)],
                        e.partitioner_factory(), sub,
                        stall_timeout=bp_stall)
                    if e.side_tag is None:
                        writers.append(w)
                    else:
                        side_writers.setdefault(e.side_tag, []).append(w)

                if vertex.kind == "source":
                    src_node = vertex.chained_nodes[0]
                    chain_ops = [n.operator_factory()
                                 for n in vertex.chained_nodes[1:]]
                    task = SourceStreamTask(
                        task_id, ctx, src_node.source,
                        _make_reader(src_node, sub, vertex.parallelism),
                        src_node.watermark_strategy, None, writers, job,
                        config)
                    task.side_writers = side_writers
                    if chain_ops:
                        task.chain = OperatorChain(
                            chain_ops, ctx, task.make_tail_output(),
                            side_outputs=_side_outputs_map(side_writers,
                                                           None))
                    job.source_tasks[task_id] = task
                elif vertex.kind == "two_input":
                    per_input: list[list] = [[], []]
                    for ei, e in in_edges:
                        src_par = jg.vertices[e.source_vertex].parallelism
                        for s in range(src_par):
                            per_input[e.target_input].append(
                                channels[(ei, s, sub)])
                    ops = [n.operator_factory()
                           for n in vertex.chained_nodes]
                    task = TwoInputStreamTask.__new__(TwoInputStreamTask)
                    StreamTask.__init__(task, task_id, ctx, writers, job,
                                        config, side_writers=side_writers)
                    task.gates = [InputGate(per_input[0], aligned=aligned),
                                  InputGate(per_input[1], aligned=aligned)]
                    task._gate_barrier = [None, None]
                    task._unaligned_pending = None
                    task._restored_inflight = [[], []]
                    task.chain = OperatorChain(
                        ops, ctx, task.make_tail_output(),
                        side_outputs=_side_outputs_map(side_writers, None))
                else:
                    in_channels = []
                    for ei, e in in_edges:
                        src_par = jg.vertices[e.source_vertex].parallelism
                        for s in range(src_par):
                            in_channels.append(channels[(ei, s, sub)])
                    gate = InputGate(in_channels, aligned=aligned)
                    ops = [n.operator_factory()
                           for n in vertex.chained_nodes]
                    task = OneInputStreamTask.__new__(OneInputStreamTask)
                    StreamTask.__init__(task, task_id, ctx, writers, job,
                                        config, side_writers=side_writers)
                    task.gate = gate
                    task._restored_inflight = []
                    task._unaligned_pending = None
                    task.chain = OperatorChain(
                        ops, ctx, task.make_tail_output(),
                        side_outputs=_side_outputs_map(side_writers, None))
                job.tasks[task_id] = task
                if restored:
                    snap = restored.get(task_id)
                    if snap:
                        task.restore_state(snap)
        self.job = job
        return job

    # -- control-plane client ---------------------------------------------
    def _uid_map(self) -> dict:
        return {vid: v.uid for vid, v in self.jg.vertices.items() if v.uid}

    def _parsed_slot_counts(self) -> Optional[list[int]]:
        """Strictly parse taskmanager.slots-per-host; one shared parser so
        initial placement, registration, and restart placement can never
        disagree about a host's capacity."""
        raw = self.config.get(RuntimeOptions.SLOTS_PER_HOST)
        if not raw:
            return None
        counts = []
        for part in str(raw).split(","):
            part = part.strip()
            try:
                n = int(part)
            except ValueError:
                raise ValueError(
                    f"taskmanager.slots-per-host: bad entry {part!r} in "
                    f"{raw!r} (want comma-separated non-negative ints)")
            if n < 0:
                raise ValueError(
                    f"taskmanager.slots-per-host: negative slot count {n}")
            counts.append(n)
        return counts

    def _config_slots(self, live: list[int]) -> dict[int, int]:
        """SPMD-shared per-host slot map (identical config on every host =>
        identical schedule): slots-per-host when set, else num-task-slots
        uniformly — which under the interleaved schedule reproduces the
        unweighted live[sub % len(live)] placement exactly."""
        counts = self._parsed_slot_counts()
        uniform = self.config.get(RuntimeOptions.NUM_TASK_SLOTS)
        if counts is None:
            return {h: uniform for h in live}
        return {h: (counts[h] if h < len(counts) else uniform) for h in live}

    def _my_slots(self) -> int:
        return self._config_slots([self.host_id])[self.host_id]

    def _ctrl_send(self, msg: dict) -> None:
        """Deadline-bounded control send (site rpc.send): a stalled frame
        raises StallError, which every caller treats exactly like a
        severed connection (OSError) — the lock is taken INSIDE the
        supervised call, so an abandoned worker finishing a stuck sendall
        still serializes against the next frame (no interleaving)."""
        from ..runtime.watchdog import stall_bounded

        def _send():
            with self._ctrl_lock:
                _send_msg(self._ctrl, msg)

        stall_bounded("rpc.send", _send,
                      scope=f"host{self.host_id}->coord", retries=0)

    def _max_restart_wait(self) -> float:
        """Upper bound on how long the coordinator may take to broadcast a
        restart order: its grace = max(strategy backoff, heartbeat window),
        both derivable from the shared SPMD config."""
        cfg = self.config
        kind = cfg.get(RuntimeOptions.RESTART_STRATEGY)
        if kind == "fixed-delay":
            backoff = cfg.get(RuntimeOptions.RESTART_DELAY)
        elif kind == "exponential-delay":
            backoff = cfg.get(RuntimeOptions.BACKOFF_MAX)
        elif kind == "failure-rate":
            backoff = cfg.get(RuntimeOptions.FAILURE_RATE_DELAY)
        else:
            backoff = 0.0
        hb = 3 * cfg.get(RuntimeOptions.HEARTBEAT_INTERVAL) + 2.0
        return max(backoff, hb) + 10.0

    def _takeover_timeout(self) -> float:
        return (float(self.config.get(HaOptions.TAKEOVER_TIMEOUT))
                if self._ha_dir else 0.0)

    def _resolve_coord_addr(self) -> Optional[str]:
        """The coordinator's CURRENT address: re-read from the fenced
        leader record when an HA dir is configured (a takeover moves the
        coordinator to a fresh port), the fixed construction-time address
        otherwise."""
        if self._ha_dir:
            rec = read_leader_record(self._ha_dir)
            if rec is not None:
                self._coord_addr = rec["address"]
        return self._coord_addr

    def _register_msg(self) -> dict:
        return {"type": "register", "host_id": self.host_id,
                "epoch": self._epoch, "uids": self._uid_map(),
                "slots": self._my_slots(),
                "data_addr": tuple(self.data_address)}

    def _connect_control(self) -> None:
        deadline = time.time() + max(30.0, self._takeover_timeout())
        while True:
            addr = self._resolve_coord_addr()
            try:
                if addr is None:
                    raise OSError("no leader record published yet")
                host, port = addr.split(":")
                self._ctrl = socket.create_connection((host, int(port)),
                                                      timeout=5.0)
                break
            except OSError:
                if time.time() >= deadline:
                    raise
                time.sleep(0.1)
        self._ctrl_send(self._register_msg())
        threading.Thread(target=self._control_loop, name="worker-control",
                         daemon=True).start()
        threading.Thread(target=self._heartbeat_loop,
                         name="worker-heartbeat", daemon=True).start()

    def _reconnect_control(self, observed_sock,
                           kind: str = "control-reconnect") -> bool:
        """Redial the coordinator after a severed control socket, bounded
        by ``net.reconnect-timeout`` (0 disables: fail fast into the
        heartbeat-timeout failover). Re-registers on the new connection
        so coordinator broadcasts flow to it. Returns False when the
        caller should fall back to the old severed-connection behavior
        (stop and let the coordinator's heartbeat window decide)."""
        from ..core.config import NetworkOptions
        from .transport import _note_net_event
        with self._ctrl_reconnect_lock:
            if self._ctrl is not observed_sock:
                return True  # another thread already healed it
            if (self._cancelled.is_set() or self.fenced
                    or not self._coord_addr):
                return False
            timeout = float(self.config.get(NetworkOptions.RECONNECT_TIMEOUT))
            if timeout <= 0:
                return False
            if self._ha_dir:
                # a leader election may be in progress: the deadline must
                # outlive the lease-steal + promotion gap, so the worker
                # is still dialing when the successor publishes its record
                timeout = max(timeout, self._takeover_timeout())
            net_deadline = time.monotonic() + timeout
            while True:
                # re-resolve EVERY attempt: after a takeover the old
                # address is permanently dead — redialing it forever would
                # turn a survivable failover into a lost worker
                addr = self._resolve_coord_addr()
                try:
                    if addr is None:
                        raise OSError("no leader record published yet")
                    host, port = addr.split(":")
                    sock = socket.create_connection((host, int(port)),
                                                    timeout=5.0)
                    break
                except OSError:
                    if time.monotonic() >= net_deadline:
                        from ..runtime.watchdog import WATCHDOG
                        WATCHDOG.note_stall("net.reconnect", timeout,
                                            scope=f"host{self.host_id}-ctrl")
                        return False
                    time.sleep(0.1)
            with self._ctrl_lock:
                old, self._ctrl = self._ctrl, sock
            try:
                old.close()
            except OSError:
                pass
            try:
                self._ctrl_send(self._register_msg())
                if (self._announced_finished.is_set()
                        and not self._redeploying.is_set()):
                    # the previous leader knew this host finished; the
                    # new one must too, or all_done never fires
                    self._ctrl_send({"type": "finished",
                                     "host_id": self.host_id,
                                     "epoch": self._epoch})
            except (OSError, StallError):
                return False
            from ..metrics.device import DEVICE_STATS
            DEVICE_STATS.note_net_reconnect("control")
            _note_net_event(kind, host=self.host_id)
            return True

    def _make_listener(self):
        acks: dict[int, dict] = {}
        self._pending_ckpts: dict[int, tuple[int, bool]] = {}
        pending = self._pending_ckpts  # cid -> (await_n, sp)

        def listener(kind, task_id, cid, payload):
            if kind == "ack":
                acks.setdefault(cid, {})[task_id] = payload
                if cid in pending and len(acks[cid]) == pending[cid][0]:
                    snaps = acks.pop(cid)
                    if self._local_recovery:
                        # stash a PICKLED copy: snapshot dicts share value
                        # references with live state (heap lists keep
                        # mutating after the barrier), and a local restore
                        # must see barrier-time state, not future state.
                        # Keyed by UID#sub — generated vertex ids are a
                        # process-global counter and never comparable
                        # across graphs (the same trap the coordinator's
                        # ack canonicalization exists for)
                        uid_of = self._uid_map()
                        by_uid = {}
                        for tid, snap in snaps.items():
                            vid, sub = tid.rsplit("#", 1)
                            by_uid[f"{uid_of.get(vid, vid)}#{sub}"] = snap
                        self._local_snapshots[cid] = pickle.dumps(
                            by_uid, protocol=pickle.HIGHEST_PROTOCOL)
                        # safety cap only: real pruning happens on the
                        # checkpoint_complete broadcast — pruning by ack
                        # order could evict the copy for the latest
                        # COMPLETED checkpoint under later acks whose
                        # checkpoints never complete
                        for old in sorted(self._local_snapshots)[:-8]:
                            del self._local_snapshots[old]
                    self._ctrl_send({
                        "type": "ack", "host_id": self.host_id,
                        "epoch": self._epoch,
                        "checkpoint_id": cid,
                        "savepoint": pending[cid][1],
                        "snapshots": snaps})
                    del pending[cid]
            else:
                self._ctrl_send({"type": "decline",
                                 "host_id": self.host_id,
                                 "epoch": self._epoch,
                                 "checkpoint_id": cid})

        return listener

    def _control_loop(self) -> None:
        while not self._cancelled.is_set():
            sock = self._ctrl
            try:
                msg = _recv_msg(sock)
            except OSError:
                msg = None
            if msg is None:
                if self._cancelled.is_set() or self._all_done.is_set():
                    return
                # severed control socket: heal it within the grace window
                # instead of going silent until the heartbeat timeout
                if not self._reconnect_control(sock):
                    return
                continue
            try:
                self._handle_control(msg)
            except (OSError, StallError):
                # a reply send failed; the recv above notices the severed
                # socket on the next turn and runs the reconnect path
                pass

    def _handle_control(self, msg: dict) -> None:
        if msg["type"] == "trigger_checkpoint":
            cid = msg["checkpoint_id"]
            if (self.job is not None and not self._redeploying.is_set()
                    and not self.job.tasks):
                # zero subtasks placed here (slot-weighted placement
                # can starve a host): ack with an empty snapshot so
                # the checkpoint never waits on us — this host is
                # "trivially done" but must not decline
                self._ctrl_send({"type": "ack",
                                 "host_id": self.host_id,
                                 "epoch": self._epoch,
                                 "checkpoint_id": cid,
                                 "savepoint": msg["savepoint"],
                                 "snapshots": {}})
                return
            if (self._redeploying.is_set() or self.job is None
                    or self.job._done.is_set()):
                # mid-failover or already finished: this attempt
                # cannot snapshot — decline so the pending
                # checkpoint never waits on us forever
                self._ctrl_send({"type": "decline",
                                 "host_id": self.host_id,
                                 "epoch": self._epoch,
                                 "checkpoint_id": cid})
                return
            from ..core.elements import CheckpointBarrier
            self._pending_ckpts[cid] = (len(self.job.tasks),
                                        msg["savepoint"])
            barrier = CheckpointBarrier(
                cid, is_savepoint=msg["savepoint"],
                trace=msg.get("trace"))
            for t in self.job.source_tasks.values():
                t.trigger_checkpoint(barrier)
        elif msg["type"] == "checkpoint_complete":
            # epoch-gated: a notification for a DEPOSED attempt (this
            # host restarted between its ack and the fan-out, or a
            # zombie window under split-brain) must not commit pending
            # output — duplicate/foreign commits break exactly-once
            if (msg.get("epoch", self._epoch) != self._epoch
                    or self._redeploying.is_set() or self.job is None):
                return
            cid = msg["checkpoint_id"]
            # prune local-recovery copies on COMPLETION (reference
            # confirms checkpoints before pruning local state):
            # everything older than the newest completed cid can
            # never be restored
            if self._local_recovery:
                for old in [c for c in self._local_snapshots
                            if c < cid]:
                    del self._local_snapshots[old]
            sp = msg.get("savepoint", False)
            for t in self.job.tasks.values():
                t.execute_in_mailbox(
                    lambda t=t, c=cid, s=sp:
                    t.chain.notify_checkpoint_complete(
                        c, is_savepoint=s)
                    if getattr(t, "chain", None) else None)
        elif msg["type"] == "restart":
            with self._intent_lock:
                self._restart_intent = msg
            self._redeploying.set()
            self._restart_event.set()
            if self.job is not None:
                self.job.cancel()
        elif msg["type"] == "wm_alignment":
            job = self.job
            if job is not None and not self._redeploying.is_set():
                job.watermark_alignment.set_remote_minima(
                    msg["minima"])
        elif msg["type"] == "fenced":
            # the coordinator deposed this attempt (zombie fencing):
            # record it; a TERMINAL fence cancels the local attempt so
            # a split-brain worker stops producing instead of running
            # to completion on stale membership
            from ..metrics.device import DEVICE_STATS
            from .transport import _note_net_event
            self.fenced = True
            DEVICE_STATS.note_zombie_fenced("worker")
            _note_net_event("zombie-fenced", host=self.host_id,
                            epoch=self._epoch,
                            coordinator_epoch=msg.get("epoch"))
            if msg.get("terminal", True):
                self._cancelled.set()
                if self.job is not None:
                    self.job.cancel()
        elif msg["type"] == "all_done":
            self._all_done.set()
        elif msg["type"] == "cancel":
            self._cancelled.set()
            if self.job is not None:
                self.job.cancel()

    def _heartbeat_loop(self) -> None:
        from ..runtime.faults import FAULTS
        interval = self.config.get(RuntimeOptions.HEARTBEAT_INTERVAL)
        while not self._cancelled.is_set():
            if FAULTS.enabled and FAULTS.check("net.zombie"):
                # zombie drill: this host looks dead to the coordinator
                # (no beats) while its data plane keeps flowing — the
                # check must come BEFORE the send so the reconnect
                # reflex below never fires either (a zombie does not
                # notice it was partitioned)
                time.sleep(interval)
                continue
            if FAULTS.enabled and FAULTS.check("rpc.heartbeat"):
                # drop-style fault site: this beat is lost on the wire;
                # enough consecutive drops and the coordinator declares
                # the worker dead and redeploys — the chaos path for the
                # heartbeat-timeout failover
                time.sleep(interval)
                continue
            job = self.job
            minima = (job.watermark_alignment.local_minima()
                      if job is not None else {})
            sock = self._ctrl
            try:
                self._ctrl_send({"type": "heartbeat",
                                 "host_id": self.host_id,
                                 "epoch": self._epoch,
                                 "wm_minima": minima})
            except (OSError, StallError):
                # a stalled control socket is a severed one: attempt ONE
                # immediate reconnect inside the grace window before
                # falling back to the coordinator's heartbeat-timeout
                # failover (emits a heartbeat-reconnect event on success)
                if not self._reconnect_control(sock,
                                               kind="heartbeat-reconnect"):
                    return
                continue
            time.sleep(interval)

    # -- run ---------------------------------------------------------------
    def _load_restore_map(self, intent: dict) -> Optional[dict]:
        """task_id -> snapshot for a restart order (checkpoint shipped
        inline for in-memory storage, loaded from shared storage by path
        otherwise; None = restart from scratch). With local recovery on,
        tasks whose acked snapshot for this checkpoint id is still held
        locally restore from the local copy — relocated subtasks (a dead
        host's work moving here) still come from the checkpoint."""
        cp = intent.get("checkpoint")
        path = intent.get("checkpoint_path")
        storage = None
        if cp is None and path:
            storage = FsCheckpointStorage(str(path).rsplit("/", 1)[0],
                                          config=self.config)
            # metadata only; chunk reads happen per task AFTER local
            # substitution so locally-covered tasks never touch storage
            cp = storage.load(path, resolve=False)
        if cp is None:
            return None
        from ..checkpoint.coordinator import build_restore_map

        local_blob = (self._local_snapshots.get(cp.checkpoint_id)
                      if self._local_recovery else None)
        substituted: set = set()
        if local_blob:
            # substitute local ack copies at the INPUT of the restore
            # mapping: build_restore_map transforms ack-shaped snapshots
            # into restore-shaped entries (keyed merges, operator-state
            # redistribution), so local copies must replace the
            # checkpoint's task snapshots BEFORE that transformation, not
            # its output. Matching runs through UID#sub (the stash key) ->
            # the checkpoint's canonical vertex ids.
            local = pickle.loads(local_blob)
            uid_to_canonical = {uid: vid for vid, uid
                                in (cp.vertex_uids or {}).items()}
            snaps = dict(cp.task_snapshots)
            for key, snap in local.items():
                uid, sub = key.rsplit("#", 1)
                cvid = uid_to_canonical.get(uid)
                if cvid is not None and f"{cvid}#{sub}" in snaps:
                    snaps[f"{cvid}#{sub}"] = snap
                    substituted.add(f"{cvid}#{sub}")
                    self.local_restores += 1
            chunk_dir = getattr(cp, "_chunk_dir", None)
            cp = CompletedCheckpoint(
                checkpoint_id=cp.checkpoint_id, timestamp=cp.timestamp,
                task_snapshots=snaps, is_savepoint=cp.is_savepoint,
                vertex_parallelism=cp.vertex_parallelism,
                vertex_uids=cp.vertex_uids,
                external_path=cp.external_path)
            cp._chunk_dir = chunk_dir
        if storage is not None:
            # materialize the rest (relocated subtasks etc.); substituted
            # tasks skip their chunk reads — the actual I/O local recovery
            # saves
            storage.resolve_tasks(cp, skip=substituted)
        return build_restore_map(cp, self.jg)

    def run(self, peer_data_addrs: dict[int, tuple[str, int]],
            timeout: Optional[float] = 300.0) -> LocalJob:
        deadline = (time.time() + timeout) if timeout else None

        def remaining() -> Optional[float]:
            return None if deadline is None else max(deadline - time.time(),
                                                     0.01)

        if self.coordinator is not None and self._coord_addr is None:
            # host 0 participates as a worker too, over loopback — its task
            # acks flow through the same control path as everyone else's
            self._coord_addr = f"127.0.0.1:{self.coordinator.port}"
        if self._coord_addr is not None or self._ha_dir:
            self._connect_control()
        if self.coordinator is not None:
            hb_timeout = 3 * self.config.get(
                RuntimeOptions.HEARTBEAT_INTERVAL) + 2.0
            threading.Thread(target=self.coordinator.monitor,
                             args=(hb_timeout,), name="coord-monitor",
                             daemon=True).start()
            interval = self.config.get(CheckpointingOptions.INTERVAL)
            if interval and interval > 0:
                def periodic():
                    while not self._cancelled.is_set():
                        time.sleep(interval)
                        if self.coordinator.all_finished():
                            return
                        self.coordinator.trigger_checkpoint()
                threading.Thread(target=periodic, name="coord-periodic",
                                 daemon=True).start()
        restart_enabled = self.config.get(
            RuntimeOptions.RESTART_STRATEGY) != "none"
        live = sorted(peer_data_addrs)
        slots = self._config_slots(live)
        epoch, restored = 0, None
        job = None
        detector = None
        from ..core.config import WatchdogOptions
        stall_timeout = float(self.config.get(
            WatchdogOptions.TASK_STALL_TIMEOUT))
        try:
            while True:
                self._restart_event.clear()
                with self._intent_lock:
                    intent = self._restart_intent
                    self._restart_intent = None
                if intent is not None:
                    self._announced_finished.clear()
                    if job is not None:
                        for t in job.tasks.values():
                            t.cancel()
                        for t in job.tasks.values():
                            t.join(5.0)
                    epoch = intent["epoch"]
                    live = [h for h in intent["live_hosts"]
                            if h in peer_data_addrs]
                    if self.host_id not in live:
                        break
                    slots = intent.get("slots") or slots
                    try:
                        restored = self._load_restore_map(intent)
                    except CorruptArtifactError as e:
                        # the artifact went bad between the coordinator's
                        # verification and this read (or corruption raced
                        # the restart): NEVER deploy with partial/garbage
                        # state — report the failure so the coordinator
                        # re-runs its verified-candidate walk and orders a
                        # restart from an older checkpoint
                        if self._ctrl is None:
                            raise
                        try:
                            self._ctrl_send({
                                "type": "failed", "host_id": self.host_id,
                                "epoch": epoch,
                                "error": f"corrupt restore artifact: {e}"})
                        except (OSError, StallError):
                            raise e
                        wait_s = self._max_restart_wait()
                        if remaining() is not None:
                            wait_s = min(wait_s, remaining())
                        if not self._restart_event.wait(wait_s):
                            raise
                        continue
                job = self.deploy(peer_data_addrs, live_hosts=live,
                                  epoch=epoch, restored=restored, slots=slots)
                job.checkpoint_listener = self._make_listener()
                # per-attempt task-progress supervision: a stalled subtask
                # on THIS host fails its task; the failure report reaches
                # the coordinator, which redeploys over the live hosts
                # from the latest checkpoint — the same path a crashed
                # task takes
                if detector is not None:
                    detector.stop()
                detector = TaskStallDetector(job, stall_timeout).start()
                self._redeploying.clear()
                if epoch > 0 and self._ctrl is not None:
                    # announce readiness for the new attempt
                    self._ctrl_send(self._register_msg())
                job.start()
                try:
                    job.wait(remaining())
                except TimeoutError:
                    raise
                except RuntimeError as e:
                    if self._restart_intent is None:
                        # a genuine task failure on THIS host: report it;
                        # the coordinator decides restart vs fail
                        if restart_enabled and self._ctrl is not None:
                            try:
                                self._ctrl_send({"type": "failed",
                                                 "host_id": self.host_id,
                                                 "epoch": epoch,
                                                 "error": str(e)})
                            except (OSError, StallError):
                                raise e
                            wait_s = self._max_restart_wait()
                            if remaining() is not None:
                                wait_s = min(wait_s, remaining())
                            if not self._restart_event.wait(wait_s):
                                raise
                        else:
                            raise
                if self._cancelled.is_set():
                    break
                if self._restart_intent is not None:
                    continue
                # finished this attempt normally
                if self._ctrl is not None:
                    self._announced_finished.set()
                    try:
                        self._ctrl_send({"type": "finished",
                                         "host_id": self.host_id,
                                         "epoch": epoch})
                    except (OSError, StallError):
                        pass
                if not restart_enabled or self._ctrl is None:
                    break
                # stay available for failover until the WHOLE job is done
                while not (self._all_done.is_set()
                           or self._cancelled.is_set()
                           or self._restart_event.wait(0.05)):
                    if deadline is not None and time.time() >= deadline:
                        break
                if self._restart_intent is None:
                    break
        finally:
            if detector is not None:
                detector.stop()
            self._cancelled.set()
        return job

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._cancelled.set()
        self.transport.close()
        if self.coordinator is not None:
            self.coordinator.close()
        if self._ctrl is not None:
            try:
                self._ctrl.close()
            except OSError:
                pass


def run_distributed(jg: JobGraph, config: Configuration, host_id: int,
                    n_hosts: int, coordinator_addr: Optional[str],
                    peer_data_addrs: dict[int, tuple[str, int]],
                    data_port: int = 0,
                    timeout: Optional[float] = 300.0,
                    ha_dir: Optional[str] = None) -> LocalJob:
    """Convenience wrapper: construct, run, close. Address discovery (who
    listens where) is the caller's rendezvous concern — tests use a shared
    file, production would use the cluster manager's pod DNS."""
    host = DistributedHost(jg, config, host_id, n_hosts, coordinator_addr,
                           data_port, ha_dir=ha_dir)
    try:
        return host.run(peer_data_addrs, timeout)
    finally:
        host.close()
