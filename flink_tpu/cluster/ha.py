"""High availability: leader election + HA metadata stores + HA supervision.

Reference semantics (SURVEY §2.3): DefaultLeaderElectionService.java:50 with
ZooKeeper/Kubernetes lease drivers, AbstractHaServices, JobGraphStore,
JobResultStore (flink-runtime leaderelection/, highavailability/). A TPU
deployment has no ZooKeeper; the coordination substrate is the shared
filesystem the checkpoints already live on (GCS/NFS in production, a tmpdir
in tests):

* **Leadership** is a lease *directory* acquired with atomic ``os.mkdir``
  (the one FS primitive that is create-exclusive everywhere), renewed by
  rewriting a heartbeat file, and stolen after expiry by atomically renaming
  the stale lease away — only one stealer's ``os.rename`` wins.
* **Fencing**: every grant increments a monotonic epoch (the reference's
  leader session id, ZooKeeperLeaderElectionDriver's znode czxid). Store
  writes carry the writer's token and lose against a higher recorded token,
  so a deposed leader's late write cannot clobber its successor's.
* **HA stores** persist the job graph, the latest-completed-checkpoint
  pointer, and the job result — everything a fresh leader needs to resume a
  job after the previous master died (Dispatcher recovery path,
  Dispatcher.java:514 + SessionDispatcherLeaderProcess).
"""

from __future__ import annotations

import fcntl
import json
import os
import pickle
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Callable, Optional

try:  # job graphs carry closure-based operator factories: cloudpickle
    import cloudpickle as _graph_pickle  # serializes what pickle cannot
except ImportError:  # pragma: no cover - cloudpickle ships in the image
    _graph_pickle = pickle

__all__ = ["LeaderElectionService", "FileHaServices", "HaJobSupervisor",
           "read_leader_record", "leader_info"]


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{uuid.uuid4().hex[:8]}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


@contextmanager
def _flocked(lock_path: str):
    """Serialize a read-check-write critical section across processes.
    flock is the compare-and-swap stand-in for the file-based driver; a
    production object-store driver would use generation-match CAS (GCS
    if-generation-match / etcd txn) for the same sections."""
    fd = os.open(lock_path, os.O_CREAT | os.O_RDWR)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


class _Lease:
    """mkdir-based lease with steal-on-expiry and fencing epochs."""

    def __init__(self, ha_dir: str, owner: str, lease_timeout: float):
        self.dir = os.path.join(ha_dir, "leader.lock")
        self.epoch_file = os.path.join(ha_dir, "leader.epoch")
        self.flock_file = os.path.join(ha_dir, "leader.flock")
        self.owner = owner
        self.timeout = lease_timeout
        self.token: int = -1
        os.makedirs(ha_dir, exist_ok=True)

    def _owner_file(self) -> str:
        return os.path.join(self.dir, "owner")

    def _bump_epoch(self) -> int:
        # single writer: only the freshly-granted leader calls this
        cur = 0
        try:
            with open(self.epoch_file) as f:
                cur = int(f.read().strip() or 0)
        except (OSError, ValueError):
            pass
        _atomic_write(self.epoch_file, str(cur + 1).encode())
        return cur + 1

    def _read_owner(self) -> Optional[dict]:
        try:
            with open(self._owner_file()) as f:
                return json.loads(f.read())
        except (OSError, ValueError):
            return None

    @staticmethod
    def _lease_fault() -> bool:
        """Visit the ``ha.lease`` fault site: a trip fails this renew or
        steal attempt (a ``!hang@MS`` trip sleeps instead — the GC-pause
        analog that lets the lease expire under a live leader)."""
        from ..runtime.faults import FAULTS
        if not FAULTS.enabled:
            return False
        return FAULTS.check("ha.lease")

    def try_acquire(self) -> bool:
        """Acquire or steal; the whole check-steal-grant sequence runs under
        the flock so a stale leader's concurrent renew cannot interleave
        with a steal (every owner-file mutation shares the lock)."""
        if self._lease_fault():
            return False
        with _flocked(self.flock_file):
            try:
                os.mkdir(self.dir)
            except FileExistsError:
                holder = self._read_owner()
                if (holder is not None
                        and time.time() - holder["ts"] < self.timeout):
                    return False
                if holder is None:
                    # just-created lease whose owner file hasn't landed yet:
                    # grant the same grace window, keyed off the dir mtime
                    try:
                        age = time.time() - os.stat(self.dir).st_mtime
                    except OSError:
                        return False
                    if age < self.timeout:
                        return False
                # expired: steal by renaming the stale lease away
                tomb = f"{self.dir}.dead.{uuid.uuid4().hex[:8]}"
                try:
                    os.rename(self.dir, tomb)
                except OSError:
                    return False
                try:
                    for name in os.listdir(tomb):
                        os.unlink(os.path.join(tomb, name))
                    os.rmdir(tomb)
                except OSError:
                    pass
                try:
                    os.mkdir(self.dir)
                except FileExistsError:
                    return False
            self.token = self._bump_epoch()
            return self._write_owner()

    def _write_owner(self) -> bool:
        try:
            _atomic_write(self._owner_file(),
                          json.dumps({"owner": self.owner, "token": self.token,
                                      "ts": time.time()}).encode())
        except OSError:
            return False
        return True

    def renew(self) -> bool:
        """Heartbeat; returns False when leadership was lost (stolen).
        Read-verify-write runs under the flock, so a renew can never land
        inside a successor's freshly stolen lease; a missing owner file
        means we were renamed away — treated as loss, never re-written."""
        if self._lease_fault():
            return False
        with _flocked(self.flock_file):
            holder = self._read_owner()
            if holder is None or holder["token"] != self.token:
                return False
            return self._write_owner()

    def release(self) -> None:
        with _flocked(self.flock_file):
            holder = self._read_owner()
            if holder is None or holder["token"] != self.token:
                return
            tomb = f"{self.dir}.dead.{uuid.uuid4().hex[:8]}"
            try:
                os.rename(self.dir, tomb)
                for name in os.listdir(tomb):
                    os.unlink(os.path.join(tomb, name))
                os.rmdir(tomb)
            except OSError:
                pass

    def current_token(self) -> int:
        holder = self._read_owner()
        return holder["token"] if holder else -1


class LeaderElectionService:
    """Contender loop with grant/revoke callbacks (reference
    DefaultLeaderElectionService.java:50). ``start()`` spawns a daemon that
    keeps contending; on grant it invokes ``on_grant(token)``, then renews
    at timeout/3 cadence; a failed renewal (lease stolen after a stall)
    invokes ``on_revoke()`` and goes back to contending."""

    def __init__(self, ha_dir: str, owner: str, lease_timeout: float = 2.0,
                 on_grant: Optional[Callable[[int], None]] = None,
                 on_revoke: Optional[Callable[[], None]] = None):
        self._lease = _Lease(ha_dir, owner, lease_timeout)
        self.owner = owner
        self.on_grant = on_grant
        self.on_revoke = on_revoke
        self._stop = threading.Event()
        self._is_leader = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # test hook: while set, the leader stops renewing (simulates a GC
        # pause / partitioned master) without stopping the service
        self.suspend_renewal = threading.Event()

    @property
    def token(self) -> int:
        return self._lease.token

    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def wait_for_leadership(self, timeout: Optional[float] = None) -> bool:
        return self._is_leader.wait(timeout)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"leader-elect-{self.owner}")
        self._thread.start()

    def _run(self) -> None:
        period = self._lease.timeout / 3
        while not self._stop.is_set():
            if not self._is_leader.is_set():
                if self._lease.try_acquire():
                    self._is_leader.set()
                    if self.on_grant is not None:
                        self.on_grant(self._lease.token)
                else:
                    self._stop.wait(period)
                continue
            self._stop.wait(period)
            if self._stop.is_set():
                break
            if self.suspend_renewal.is_set():
                continue
            if not self._lease.renew():
                self._is_leader.clear()
                if self.on_revoke is not None:
                    self.on_revoke()

    def step_down(self) -> None:
        """Drop leadership immediately (e.g. the holder learned through a
        fenced store write that a successor exists) without waiting for the
        next failed renewal. The contender loop keeps running and may be
        re-granted later with a fresh token."""
        self._is_leader.clear()

    def stop(self, release: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
        if self._is_leader.is_set():
            self._is_leader.clear()
            if release:
                self._lease.release()


class FileHaServices:
    """HA metadata stores on a shared directory, with fenced writes
    (reference AbstractHaServices: job graph store + checkpoint recovery
    factory + JobResultStore)."""

    def __init__(self, ha_dir: str):
        self.dir = ha_dir
        for sub in ("jobs", "checkpoints", "results", "journal", "standbys"):
            os.makedirs(os.path.join(ha_dir, sub), exist_ok=True)

    # -- leader record (fenced) --------------------------------------------
    # The address half of leadership: the lease says WHO leads, the record
    # says WHERE to dial. Workers resolve the coordinator through this
    # instead of a fixed address, so a standby promoted on a new port is
    # reachable the moment it publishes.
    def publish_leader_record(self, token: int, address: str,
                              owner: str) -> bool:
        """Publish ``address`` as the coordinator endpoint for fencing
        ``token``. Fenced like every HA write: refused when a higher token
        already published or a successor holds the lease."""
        path = os.path.join(self.dir, "leader.record")
        with _flocked(path + ".lock"):
            lease = self._lease_token()
            if lease is not None and lease > token:
                return False
            existing = read_leader_record(self.dir)
            if existing is not None and existing["token"] > token:
                return False
            _atomic_write(path, json.dumps(
                {"token": token, "address": address, "owner": owner,
                 "ts": time.time()}).encode())
            return True

    def get_leader_record(self) -> Optional[dict]:
        return read_leader_record(self.dir)

    # -- coordinator journal (fenced) --------------------------------------
    # Everything a successor needs to take over a RUNNING job: topology id,
    # attempt epoch, next checkpoint id, expected hosts + slots, worker
    # address map, and the last few completed-checkpoint pointers.
    def put_journal(self, job_id: str, token: int, journal: dict) -> bool:
        path = os.path.join(self.dir, "journal", f"{job_id}.pkl")
        with _flocked(path + ".lock"):
            lease = self._lease_token()
            if lease is not None and lease > token:
                return False
            existing = self._read(path)
            if existing is not None and existing["token"] > token:
                return False
            _atomic_write(path, pickle.dumps(
                {"token": token, "journal": journal},
                pickle.HIGHEST_PROTOCOL))
            return True

    def get_journal(self, job_id: str) -> Optional[dict]:
        rec = self._read(os.path.join(self.dir, "journal", f"{job_id}.pkl"))
        return rec["journal"] if rec else None

    # -- standby presence --------------------------------------------------
    def announce_standby(self, owner: str) -> None:
        """Heartbeat this contender's presence for the leader surface
        (``cli leader`` / REST); purely informational, never fenced."""
        try:
            _atomic_write(os.path.join(self.dir, "standbys", f"{owner}.json"),
                          json.dumps({"owner": owner,
                                      "ts": time.time()}).encode())
        except OSError:
            pass

    def withdraw_standby(self, owner: str) -> None:
        try:
            os.unlink(os.path.join(self.dir, "standbys", f"{owner}.json"))
        except OSError:
            pass

    def list_standbys(self, ttl: float = 10.0) -> list[str]:
        out = []
        root = os.path.join(self.dir, "standbys")
        try:
            names = os.listdir(root)
        except OSError:
            return out
        now = time.time()
        for name in names:
            try:
                with open(os.path.join(root, name)) as f:
                    rec = json.loads(f.read())
                if now - rec["ts"] < ttl:
                    out.append(rec["owner"])
            except (OSError, ValueError, KeyError):
                continue
        return sorted(out)

    # -- job graphs --------------------------------------------------------
    def put_job_graph(self, job_id: str, job_graph: Any) -> None:
        _atomic_write(os.path.join(self.dir, "jobs", f"{job_id}.pkl"),
                      _graph_pickle.dumps(job_graph,
                                          pickle.HIGHEST_PROTOCOL))

    def get_job_graph(self, job_id: str) -> Optional[Any]:
        try:
            with open(os.path.join(self.dir, "jobs", f"{job_id}.pkl"),
                      "rb") as f:
                return pickle.loads(f.read())
        except OSError:
            return None

    def list_jobs(self) -> list[str]:
        return sorted(n[:-4] for n in os.listdir(os.path.join(self.dir, "jobs"))
                      if n.endswith(".pkl"))

    def remove_job(self, job_id: str) -> None:
        for sub in ("jobs", "checkpoints", "results"):
            try:
                os.unlink(os.path.join(self.dir, sub, f"{job_id}.pkl"))
            except OSError:
                pass

    # -- latest-checkpoint pointer (fenced) --------------------------------
    def put_checkpoint(self, job_id: str, token: int, checkpoint: Any) -> bool:
        """Record the latest completed checkpoint under fencing ``token``.
        Returns False (write refused) when a higher token already wrote —
        the caller has been deposed. Check+write is one flocked critical
        section, so a deposed leader's in-flight write cannot land after
        (and clobber) the successor's higher-token record."""
        path = os.path.join(self.dir, "checkpoints", f"{job_id}.pkl")
        with _flocked(path + ".lock"):
            lease = self._lease_token()
            if lease is not None and lease > token:
                return False  # a successor leads, even if it hasn't written
            existing = self._read(path)
            if existing is not None and existing["token"] > token:
                return False
            _atomic_write(path, pickle.dumps(
                {"token": token, "checkpoint": checkpoint},
                pickle.HIGHEST_PROTOCOL))
            return True

    def get_checkpoint(self, job_id: str) -> Optional[Any]:
        rec = self._read(os.path.join(self.dir, "checkpoints",
                                      f"{job_id}.pkl"))
        return rec["checkpoint"] if rec else None

    # -- AOT executable-cache pointer --------------------------------------
    # Recorded next to the checkpoint pointer so a successor master can
    # warm-start the persistent AOT cache BEFORE it redeploys (compile-
    # storm-free recovery). Never fenced: the location is immutable job
    # config, not attempt state, so a late write cannot mislead anyone.
    def put_aot_dir(self, job_id: str, directory: str) -> None:
        try:
            _atomic_write(
                os.path.join(self.dir, "checkpoints", f"{job_id}.aot.json"),
                json.dumps({"aot_dir": directory}).encode())
        except OSError:
            pass

    def get_aot_dir(self, job_id: str) -> str:
        try:
            with open(os.path.join(self.dir, "checkpoints",
                                   f"{job_id}.aot.json")) as f:
                return str(json.loads(f.read()).get("aot_dir") or "")
        except (OSError, ValueError):
            return ""

    # -- job results -------------------------------------------------------
    def put_result(self, job_id: str, token: int, result: dict) -> bool:
        path = os.path.join(self.dir, "results", f"{job_id}.pkl")
        with _flocked(path + ".lock"):
            lease = self._lease_token()
            if lease is not None and lease > token:
                return False
            existing = self._read(path)
            if existing is not None and existing["token"] > token:
                return False
            _atomic_write(path, pickle.dumps(
                {"token": token, "result": result}, pickle.HIGHEST_PROTOCOL))
            return True

    def get_result(self, job_id: str) -> Optional[dict]:
        rec = self._read(os.path.join(self.dir, "results", f"{job_id}.pkl"))
        return rec["result"] if rec else None

    def _lease_token(self) -> Optional[int]:
        """The fencing token of the CURRENT lease holder (None when no
        leader): fenced writes also lose against a successor that holds
        the lease but hasn't written its first record yet."""
        try:
            with open(os.path.join(self.dir, "leader.lock", "owner")) as f:
                return json.loads(f.read())["token"]
        except (OSError, ValueError, KeyError):
            return None

    @staticmethod
    def _read(path: str) -> Optional[dict]:
        try:
            with open(path, "rb") as f:
                return pickle.loads(f.read())
        except OSError:
            return None
        except Exception:  # noqa: BLE001 - corrupt/truncated record
            # an unreadable HA record is treated like a missing one: the
            # recovery path falls back to scanning the retained checkpoint
            # directories on disk (HaJobSupervisor._verified_restore)
            return None


def read_leader_record(ha_dir: str) -> Optional[dict]:
    """The published leader record ({token, address, owner, ts}) or None.
    Pure read — safe from any process (workers resolving the coordinator,
    the CLI, REST) without constructing ``FileHaServices``."""
    try:
        with open(os.path.join(ha_dir, "leader.record")) as f:
            rec = json.loads(f.read())
        if not isinstance(rec, dict) or "address" not in rec:
            return None
        return rec
    except (OSError, ValueError):
        return None


def leader_info(ha_dir: str, standby_ttl: float = 10.0) -> dict:
    """One-shot snapshot of the leadership surface for ``cli leader`` and
    REST ``GET /jobs/<name>/leader``: the current lease holder, fencing
    epoch, lease age, published coordinator address, and live standbys."""
    info: dict[str, Any] = {"ha_dir": ha_dir, "leader": None, "epoch": -1,
                            "lease_age": None, "address": None,
                            "standbys": [], "standby_count": 0}
    try:
        with open(os.path.join(ha_dir, "leader.lock", "owner")) as f:
            holder = json.loads(f.read())
        info["leader"] = holder.get("owner")
        info["epoch"] = holder.get("token", -1)
        ts = holder.get("ts")
        if ts is not None:
            info["lease_age"] = max(0.0, time.time() - ts)
    except (OSError, ValueError):
        pass
    rec = read_leader_record(ha_dir)
    if rec is not None:
        info["address"] = rec["address"]
        if info["leader"] is None:
            # lease gone (leader died, not yet stolen): the record still
            # names the last known coordinator and its epoch
            info["leader"] = rec.get("owner")
            info["epoch"] = rec.get("token", info["epoch"])
    try:
        standbys = FileHaServices(ha_dir).list_standbys(ttl=standby_ttl)
    except OSError:
        standbys = []
    info["standbys"] = [s for s in standbys if s != info["leader"]]
    info["standby_count"] = len(info["standbys"])
    return info


class HaJobSupervisor:
    """One master contender: waits for leadership, recovers the job from the
    HA stores, supervises it (JobSupervisor underneath), and persists every
    completed checkpoint so the NEXT leader resumes where this one died —
    the Dispatcher/JobMaster failover loop
    (SessionDispatcherLeaderProcess -> Dispatcher.submitJob recovery).

    Run one instance per would-be master process; kill the leader and a
    standby takes over from the last completed checkpoint."""

    def __init__(self, ha: FileHaServices, job_id: str, config,
                 owner: Optional[str] = None, lease_timeout: float = 2.0):
        self.ha = ha
        self.job_id = job_id
        self.config = config
        self.owner = owner or f"master-{uuid.uuid4().hex[:6]}"
        self.election = LeaderElectionService(ha.dir, self.owner,
                                              lease_timeout)
        self.supervisor = None  # JobSupervisor while leading
        self._killed = threading.Event()
        self._fenced = threading.Event()  # a put_checkpoint was refused

    def submit(self, job_graph: Any) -> None:
        """Persist the job graph so any leader can recover it (reference
        JobGraphStore.putJobGraph) — plus the AOT cache location, so a
        successor warms compiled executables before it redeploys."""
        self.ha.put_job_graph(self.job_id, job_graph)
        from ..core.config import AotOptions
        aot_dir = str(self.config.get(AotOptions.DIR) or "")
        if aot_dir:
            self.ha.put_aot_dir(self.job_id, aot_dir)

    def kill(self) -> None:
        """Simulate master death: stop renewing the lease and abandon the
        running attempt WITHOUT releasing (a clean release would be a
        graceful shutdown, not a failure)."""
        self._killed.set()
        self.election.stop(release=False)
        sup = self.supervisor
        if sup is not None and sup.current_job is not None:
            sup.current_job.cancel()

    def _verified_restore(self, restore):
        """Verify the HA checkpoint pointer's on-disk artifact before a
        fresh leader resumes from it; on corruption — or when the HA
        record itself was unreadable (``restore is None`` with retained
        checkpoints on disk) — quarantine and walk backward through the
        retained checkpoint directories, newest first, restoring the
        first that verifies. Raises CorruptArtifactError when retained
        checkpoints exist but none verifies (a leader must never resume a
        job on garbage — or silently-reset — state)."""
        from ..checkpoint.storage import (
            CheckpointNotFoundError, CorruptArtifactError,
            FsCheckpointStorage, retained_checkpoint_dirs,
        )
        from ..core.config import CheckpointingOptions
        from ..metrics.device import DEVICE_STATS

        if not self.config.get(CheckpointingOptions.VERIFY_ON_RESTORE):
            return restore
        pointer_path = (getattr(restore, "external_path", None)
                        if restore is not None else None)
        root = (os.path.dirname(pointer_path.rstrip("/")) if pointer_path
                else self.config.get(CheckpointingOptions.DIRECTORY))
        if not root or not os.path.isdir(root):
            return restore  # in-memory checkpoints: nothing on disk
        storage = FsCheckpointStorage(root, config=self.config)
        quarantine = self.config.get(CheckpointingOptions.QUARANTINE_CORRUPT)
        candidates = sorted(retained_checkpoint_dirs(root), reverse=True)
        if not candidates and pointer_path:
            candidates = [(restore.checkpoint_id, pointer_path)]
        skipped = 0
        for cid, path in candidates:
            try:
                storage.verify_checkpoint(path)
                if pointer_path and os.path.abspath(path) == \
                        os.path.abspath(pointer_path):
                    cp = restore  # pointer record already holds the state
                else:
                    cp = storage.load(path)
                if skipped:
                    DEVICE_STATS.note_restore_fallback("ha.restore")
                return cp
            except (CorruptArtifactError, CheckpointNotFoundError):
                skipped += 1
                DEVICE_STATS.note_verify_failure("ha.restore")
                if quarantine:
                    storage.quarantine(path)
                continue
        if skipped:
            raise CorruptArtifactError(
                f"HA recovery of job {self.job_id}: all {skipped} retained "
                "checkpoints failed verification")
        return restore

    def run(self, timeout: float = 60.0) -> dict:
        """Contend; when leading, recover + supervise to completion.
        Returns the job result dict ({"status": "done", ...})."""
        from .scheduler import JobSupervisor

        self.election.start()
        deadline = time.time() + timeout
        try:
            while not self._killed.is_set():
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(f"no leadership within {timeout}s")
                if not self.election.wait_for_leadership(min(remaining, 0.5)):
                    done = self.ha.get_result(self.job_id)
                    if done is not None:
                        return done  # someone else finished it
                    continue
                token = self.election.token
                done = self.ha.get_result(self.job_id)
                if done is not None:
                    return done
                jg = self.ha.get_job_graph(self.job_id)
                if jg is None:
                    raise RuntimeError(f"job {self.job_id} not in HA store")
                restore = self._verified_restore(
                    self.ha.get_checkpoint(self.job_id))
                # compile-storm-free recovery: warm the AOT executable
                # cache (location recorded next to the checkpoint pointer)
                # before redeploying, so takeover never recompiles
                from ..core.config import AotOptions
                from ..runtime.aot import AOT
                jdir = self.ha.get_aot_dir(self.job_id)
                if jdir and not str(self.config.get(AotOptions.DIR) or ""):
                    self.config.set(AotOptions.ENABLED, True)
                    self.config.set(AotOptions.DIR, jdir)
                AOT.configure(self.config)
                AOT.warmup()
                self.supervisor = JobSupervisor(jg, self.config)
                orig_deploy = self.supervisor._deploy

                def deploy_with_ha_hook(restore_cp, _orig=orig_deploy,
                                        _token=token):
                    job = _orig(restore_cp)
                    coord = self.supervisor.coordinator
                    orig_complete = coord._complete

                    def complete_and_publish(p):
                        orig_complete(p)
                        if p.completed is not None:
                            if not self.ha.put_checkpoint(
                                    self.job_id, _token, p.completed):
                                # fenced out: a new leader took over — the
                                # cancelled attempt must NOT read as a
                                # clean finish (flag checked after run())
                                self._fenced.set()
                                job.cancel()
                    coord._complete = complete_and_publish
                    return job

                self.supervisor._deploy = deploy_with_ha_hook
                try:
                    job = self.supervisor.run(
                        timeout=max(deadline - time.time(), 1.0),
                        initial_restore=restore)
                except (RuntimeError, TimeoutError):
                    if self._fenced.is_set():
                        # a successor exists: drop leadership NOW — waiting
                        # for the next failed renewal would let this loop
                        # redeploy the job concurrently with the successor
                        self._fenced.clear()
                        self.election.step_down()
                        continue
                    if self._killed.is_set() or not self.election.is_leader():
                        continue  # deposed mid-run; standby path
                    raise
                if self._killed.is_set():
                    break
                if self._fenced.is_set() or not self.election.is_leader():
                    # deposed mid-run: the attempt ended via fencing cancel,
                    # not completion — drop leadership and rejoin the
                    # standbys; never publish "done" for a job that still
                    # runs elsewhere
                    self._fenced.clear()
                    self.election.step_down()
                    continue
                result = {"status": "done", "owner": self.owner,
                          "attempts": self.supervisor.attempt}
                self.ha.put_result(self.job_id, token, result)
                return result
            raise RuntimeError(f"master {self.owner} was killed")
        finally:
            self.election.stop(release=not self._killed.is_set())
