"""Web dashboard, flamegraph sampling, history server.

Reference: flink-runtime-web (Angular dashboard over the REST API),
runtime/webmonitor/threadinfo/ (ThreadInfoSample -> VertexFlameGraph), and
runtime/webmonitor/history/ (HistoryServer archiving completed jobs). The
TPU-native build keeps the same architecture — a dashboard that is a pure
REST client — but ships it as ONE self-contained HTML page (no build
toolchain, no framework): topology, task states, checkpoint stats and an
on-demand flamegraph, polling the endpoints cluster/rest.py already serves.

Flamegraphs sample the PYTHON stacks of the job's task threads via
``sys._current_frames()`` at a fixed rate and fold them into the d3-flame
trie {name, value, children} (the reference samples JVM threads through
ThreadMXBean — same shape, different VM). The host-side Python stack is
where this framework's overhead lives (XLA kernels show as the dispatch
frame), so this is the profiling view that matters for the hot loop.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Optional

__all__ = ["sample_flamegraph", "archive_job", "HistoryServer",
           "DASHBOARD_HTML"]


# -- flamegraph -------------------------------------------------------------

def _fold(root: dict, stack: list[str]) -> None:
    root["value"] += 1
    node = root
    for frame in stack:
        for child in node["children"]:
            if child["name"] == frame:
                node = child
                break
        else:
            child = {"name": frame, "value": 0, "children": []}
            node["children"].append(child)
            node = child
        node["value"] += 1


def sample_flamegraph(job, duration_s: float = 1.0,
                      hz: float = 50.0) -> dict:
    """Sample the job's task threads; returns a d3-flamegraph trie."""
    idents: dict[int, str] = {}
    for task_id, task in job.tasks.items():
        th = getattr(task, "_thread", None)
        if th is not None and th.is_alive():
            idents[th.ident] = task_id
    root = {"name": "root", "value": 0, "children": []}
    samples = 0
    deadline = time.time() + duration_s
    period = 1.0 / hz
    while time.time() < deadline and idents:
        frames = sys._current_frames()
        for ident, task_id in idents.items():
            frame = frames.get(ident)
            if frame is None:
                continue
            stack: list[str] = []
            while frame is not None:
                code = frame.f_code
                stack.append(f"{code.co_name} "
                             f"({os.path.basename(code.co_filename)}:"
                             f"{frame.f_lineno})")
                frame = frame.f_back
            stack.reverse()
            _fold(root, [task_id] + stack)
            samples += 1
        time.sleep(period)
    root["samples"] = samples
    return root


# -- history server ---------------------------------------------------------

def archive_job(archive_dir: str, name: str, job,
                coordinator=None) -> str:
    """Write a completed job's terminal view to the archive (reference
    HistoryServerArchivist / FsJobArchivist)."""
    os.makedirs(archive_dir, exist_ok=True)
    vertices = []
    for vid, v in job.job_graph.vertices.items():
        vertices.append({"id": vid, "name": v.name, "uid": v.uid,
                         "parallelism": v.parallelism})
    checkpoints = []
    if coordinator is not None:
        checkpoints = list(getattr(coordinator, "stats", []))
    from ..metrics.device import DEVICE_STATS
    archive = {"name": name,
               "state": "FAILED" if job.failed else "FINISHED",
               "archived_at": time.time(),
               "tasks": len(job.tasks),
               "vertices": vertices,
               "checkpoints": checkpoints,
               # terminal device-path accounting rides the archive so a
               # history view can still answer "did it recompile?"
               "device_metrics": DEVICE_STATS.snapshot()}
    path = os.path.join(archive_dir, f"{name}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(archive, f)
    os.replace(tmp, path)
    return path


class HistoryServer:
    """Serves archived completed jobs (reference
    runtime/webmonitor/history/HistoryServer.java): GET /history lists,
    GET /history/<name> returns one archive."""

    def __init__(self, archive_dir: str, port: int = 0,
                 host: str = "127.0.0.1"):
        self.archive_dir = archive_dir
        self._requested_port = port
        self._host = host
        self._server = None
        self.port: Optional[int] = None

    def _list(self) -> list[dict]:
        out = []
        try:
            names = sorted(os.listdir(self.archive_dir))
        except OSError:
            return []
        for n in names:
            if not n.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.archive_dir, n)) as f:
                    a = json.load(f)
                out.append({"name": a["name"], "state": a["state"],
                            "archived_at": a["archived_at"]})
            except (OSError, ValueError, KeyError):
                continue
        return out

    def _get(self, name: str) -> Optional[dict]:
        path = os.path.join(self.archive_dir, f"{name}.json")
        if os.path.basename(path) != f"{name}.json" or "/" in name:
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def start(self) -> int:
        import http.server

        hs = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _reply(self, code, payload, ctype="application/json"):
                body = (payload if isinstance(payload, bytes)
                        else json.dumps(payload).encode())
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                parts = [p for p in self.path.split("/") if p]
                if parts == ["history"] or parts == []:
                    self._reply(200, hs._list())
                elif len(parts) == 2 and parts[0] == "history":
                    a = hs._get(parts[1])
                    self._reply(200 if a else 404,
                                a or {"error": "no such archive"})
                else:
                    self._reply(404, {"error": "unknown path"})

            def log_message(self, *args):
                pass

        from ..utils.httpd import ThreadedHTTPServer
        self._server = ThreadedHTTPServer(Handler, self._requested_port,
                                          self._host, "history-server")
        self.port = self._server.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop()
            self._server = None


# -- dashboard (single self-contained page; a pure REST client) -------------

DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>flink-tpu</title><style>
body{font:13px/1.5 system-ui,sans-serif;margin:0;background:#0f1320;
color:#dfe6f4}
h1{font-size:15px;margin:0;padding:10px 16px;background:#161b2e;
border-bottom:1px solid #273052}
h1 small{color:#7c89ad;font-weight:400;margin-left:8px}
section{margin:14px 16px}
h2{font-size:13px;color:#9fb0d8;margin:0 0 6px}
table{border-collapse:collapse;width:100%;background:#141930;
border:1px solid #273052}
th,td{padding:5px 10px;text-align:left;border-bottom:1px solid #222a49}
th{color:#8fa1c7;font-weight:600;font-size:12px}
.ok{color:#6fe3a1}.run{color:#7cb5ff}.bad{color:#ff7d7d}
.bar{display:inline-block;height:9px;background:#7cb5ff;border-radius:2px;
vertical-align:middle}
button{background:#27407a;color:#dfe6f4;border:0;border-radius:4px;
padding:4px 10px;cursor:pointer}
#flame div{overflow:hidden;white-space:nowrap;font-size:10px;
border-radius:2px;margin-top:1px;padding:0 3px;color:#081020;
background:#e8a33d;cursor:default}
</style></head><body>
<h1>flink-tpu <small>streaming dashboard</small></h1>
<section><h2>Jobs</h2><table id="jobs"><thead><tr>
<th>name</th><th>state</th><th>tasks</th><th>running</th></tr></thead>
<tbody></tbody></table></section>
<section><h2>Topology</h2><table id="topo"><thead><tr>
<th>vertex</th><th>name</th><th>parallelism</th><th>subtasks</th>
</tr></thead><tbody></tbody></table></section>
<section><h2>Checkpoints</h2><table id="ckpts"><thead><tr>
<th>id</th><th>savepoint</th><th>duration (s)</th><th>tasks</th>
</tr></thead><tbody></tbody></table></section>
<section><h2>Device path</h2><table id="dev"><thead><tr>
<th>compiles</th><th>cache hits</th><th>compile ms</th>
<th>h2d MB</th><th>d2h MB</th><th>max busy</th><th>max backpressure</th>
</tr></thead><tbody></tbody></table></section>
<section><h2>Flamegraph
<button onclick="flame()">sample 1s</button></h2>
<div id="flame"></div></section>
<script>
let current=null;
async function j(p){const r=await fetch(p);return r.json()}
function cls(s){return s==="RUNNING"?"run":s==="FAILED"?"bad":"ok"}
async function refresh(){
  const jobs=await j('/jobs');
  const tb=document.querySelector('#jobs tbody');tb.innerHTML='';
  for(const job of jobs){
    if(!current)current=job.name;
    tb.insertAdjacentHTML('beforeend',
      `<tr><td>${job.name}</td><td class=${cls(job.state)}>${job.state}
       </td><td>${job.tasks}</td><td>${job.running_tasks}</td></tr>`)}
  if(!current)return;
  const d=await j('/jobs/'+current);
  const tt=document.querySelector('#topo tbody');tt.innerHTML='';
  for(const v of (d.vertices||[])){
    const subs=v.subtasks.map(s=>
      `<span class=${cls(s.state)}>&#9632;</span>`).join(' ');
    tt.insertAdjacentHTML('beforeend',
      `<tr><td>${v.id}</td><td>${v.name}</td><td>${v.parallelism}</td>
       <td>${subs}</td></tr>`)}
  const cs=await j('/jobs/'+current+'/checkpoints');
  const tc=document.querySelector('#ckpts tbody');tc.innerHTML='';
  for(const c of cs.slice(-12).reverse()){
    tc.insertAdjacentHTML('beforeend',
      `<tr><td>${c.id}</td><td>${c.savepoint||false}</td>
       <td>${(c.duration_s||0).toFixed(3)}</td><td>${c.tasks||''}</td>
       </tr>`)}
  const m=await j('/metrics/snapshot');
  const mb=b=>((b||0)/1048576).toFixed(1);
  let busy=0,bp=0;
  for(const k in m){
    if(k.endsWith('busyTimeRatio'))busy=Math.max(busy,m[k]);
    if(k.endsWith('backPressuredTimeMsPerSecond'))bp=Math.max(bp,m[k]/1e3);}
  document.querySelector('#dev tbody').innerHTML=
    `<tr><td>${m['device.compiles']||0}</td>
     <td>${m['device.compile_cache_hits']||0}</td>
     <td>${(m['device.compile_ms']||0).toFixed(0)}</td>
     <td>${mb(m['device.h2d_bytes'])}</td>
     <td>${mb(m['device.d2h_bytes'])}</td>
     <td>${(100*busy).toFixed(0)}%</td>
     <td>${(100*bp).toFixed(0)}%</td></tr>`;
}
function renderFlame(node,total,el,depth){
  if(!total)return;
  const w=100*node.value/total;
  if(w<0.5)return;
  const d=document.createElement('div');
  d.style.width=w+'%';d.style.marginLeft=(depth*4)+'px';
  d.title=node.name+' — '+node.value+' samples';
  d.textContent=node.name;
  el.appendChild(d);
  for(const c of (node.children||[]))renderFlame(c,total,el,depth+1);
}
async function flame(){
  if(!current)return;
  const el=document.getElementById('flame');
  el.innerHTML='<em>sampling…</em>';
  const f=await j('/jobs/'+current+'/flamegraph');
  el.innerHTML='';
  renderFlame(f,f.value,el,0);
}
refresh();setInterval(refresh,2000);
</script></body></html>"""
