"""REST status endpoint for a running local job.

Analog of the reference's web monitor / REST API (flink-runtime
rest/RestServerEndpoint.java:86, WebMonitorEndpoint.java:194, handlers under
rest/handler/job/ incl. savepoint triggering SavepointHandlers.java:115),
reduced to the operationally useful slice:

    GET  /                        -> single-page web dashboard (webui.py)
    GET  /jobs                    -> running job overview
    GET  /jobs/<name>             -> vertices, parallelism, task states
    GET  /jobs/<name>/checkpoints -> completed checkpoint stats
    GET  /jobs/<name>/exceptions  -> bounded failure history (task
                                     failures, restarts, failed
                                     checkpoint writes — the reference's
                                     JobExceptionsHandler analog)
    GET  /jobs/<name>/flamegraph  -> sampled task-thread flamegraph trie
    GET  /jobs/<name>/traces      -> retained completed spans (causal
                                     tracing; metrics/tracing.py)
    GET  /jobs/<name>/flight-recorder -> flight-recorder dump records +
                                     the live ring's tail (post-mortems)
    GET  /jobs/<name>/profile     -> device-time ledger profile: top-k
                                     hot programs, per-operator device-
                                     time shares, recompile attribution
                                     (``?top=K`` bounds the program list)
    POST /jobs/<name>/savepoints  -> trigger a savepoint, returns its path
    GET  /metrics                 -> prometheus text exposition (always
                                     includes the device-path scope:
                                     compiles / cache hits / transfers)
    GET  /metrics/snapshot        -> flat JSON snapshot of the registry
                                     plus the device-path counters (what
                                     the dashboard's device panel polls)
"""

from __future__ import annotations

import http.server
import json
from typing import Any, Optional

__all__ = ["RestEndpoint"]


class RestEndpoint:
    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 metrics_registry=None, savepoint_timeout_s: float = 60.0):
        self._host = host
        self._requested_port = port
        self._jobs: dict[str, Any] = {}          # name -> LocalJob
        self._coordinators: dict[str, Any] = {}  # name -> coordinator
        self._ha_dirs: dict[str, str] = {}       # name -> HA dir (failover)
        self.metrics_registry = metrics_registry
        self.savepoint_timeout_s = savepoint_timeout_s
        self._server = None
        self.port: Optional[int] = None

    # -- registration ------------------------------------------------------
    def register_job(self, name: str, job, coordinator=None,
                     ha_dir: Optional[str] = None) -> None:
        self._jobs[name] = job
        if coordinator is not None:
            self._coordinators[name] = coordinator
        if ha_dir:
            self._ha_dirs[name] = ha_dir

    # -- views -------------------------------------------------------------
    def _job_overview(self) -> list[dict]:
        out = []
        for name, job in self._jobs.items():
            running = sum(1 for t in job.tasks.values() if t.is_alive)
            out.append({"name": name,
                        "state": ("FAILED" if job.failed
                                  else "RUNNING" if running else "FINISHED"),
                        "tasks": len(job.tasks), "running_tasks": running})
        return out

    def _job_detail(self, name: str) -> Optional[dict]:
        job = self._jobs.get(name)
        if job is None:
            return None
        vertices = []
        for vid, v in job.job_graph.vertices.items():
            subtasks = []
            for sub in range(v.parallelism):
                tid = f"{vid}#{sub}"
                t = job.tasks.get(tid)
                attempts = getattr(job, "executions", {}).get(tid, [])
                cur = attempts[-1] if attempts else None
                subtasks.append({
                    "subtask": sub,
                    "state": (cur["state"] if cur else
                              "RUNNING" if (t and t.is_alive)
                              else "FINISHED"),
                    "attempt": cur["attempt"] if cur else 1,
                    "attempts": attempts})
            vertices.append({"id": vid, "name": v.name, "uid": v.uid,
                             "parallelism": v.parallelism,
                             "subtasks": subtasks})
        return {"name": name, "vertices": vertices}

    def _checkpoints(self, name: str) -> Optional[list]:
        coord = self._coordinators.get(name)
        if coord is None:
            return []
        stats = {s["id"]: s for s in getattr(coord, "stats", [])}
        return [{"id": c.checkpoint_id, "savepoint": c.is_savepoint,
                 "external_path": c.external_path,
                 "duration_s": stats.get(c.checkpoint_id, {}).get(
                     "duration_s"),
                 "tasks": stats.get(c.checkpoint_id, {}).get("tasks")}
                for c in getattr(coord, "_completed", [])]

    @staticmethod
    def _job_scoped(events, name: str):
        """Bulkhead filter for process-global event streams: a job's
        exception surface shows its OWN events plus unattributed ones
        (pre-task plumbing with no dispatch context) — never another
        tenant's failures (docs/ROBUSTNESS.md, 'Multi-tenant
        isolation')."""
        return (dict(e) for e in events
                if not e.get("job") or e.get("job") == name)

    def _exceptions(self, name: str) -> Optional[dict]:
        """Bounded failure history (the reference's JobExceptionsHandler /
        exception-history endpoint): task failures, restart decisions,
        degradations, stall detections — newest first — plus any failed
        checkpoint writes from the coordinator's stats and the process-
        global watchdog's stall and fault-injection events (deadline
        expiries absorbed by retry or the degradation ladder never reach
        a task failure, but the operator debugging a slow job still
        needs to see them). All process-global streams are job-scoped:
        one tenant's damage never appears under another's name."""
        job = self._jobs.get(name)
        if job is None:
            return None
        entries = list(getattr(job, "failure_history", ()) or ())
        coord = self._coordinators.get(name)
        for s in getattr(coord, "stats", []) or []:
            if s.get("failed"):
                entries.append({"timestamp": None, "kind":
                                "checkpoint-write-failure",
                                "checkpoint": s.get("id"),
                                "error": s.get("error")})
        from ..runtime.watchdog import WATCHDOG
        entries.extend(self._job_scoped(WATCHDOG.events, name))
        # transport-plane events (reconnects, fenced zombies, socket
        # errors the accept/receive/credit paths used to swallow): the
        # operator diagnosing a flapping partition sees them here
        from .transport import NET_EVENTS
        entries.extend(self._job_scoped(NET_EVENTS, name))
        # AOT executable-cache degradations (corrupt artifacts quarantined,
        # version skew, store/load fallbacks): every silent fall-back to
        # live compilation stays visible to the operator here
        from ..runtime.aot import AOT
        entries.extend(self._job_scoped(AOT.events, name))
        entries.sort(key=lambda e: e.get("timestamp") or 0, reverse=True)
        return {"name": name, "entries": entries}

    def _flamegraph(self, name: str) -> Optional[dict]:
        job = self._jobs.get(name)
        if job is None:
            return None
        from .webui import sample_flamegraph
        return sample_flamegraph(job, duration_s=1.0)

    def _traces(self, name: str) -> Optional[dict]:
        """Retained completed spans from the process-global tracer —
        checkpoint trees, device steps, net/restart episodes. The
        ``chrome=1`` rendering (trace-event JSON) happens client-side in
        the CLI; this endpoint ships raw span dicts."""
        if name not in self._jobs:
            return None
        from ..metrics.tracing import TRACER
        return {"name": name,
                "spans": [s.to_dict() for s in TRACER.retained_spans()]}

    def _state_residency(self, name: str) -> Optional[dict]:
        """Per-key-group residency/heat rows of the job's tiered keyed
        state (empty when no operator runs under an HBM budget). Rows
        come from the process-global residency registry the budgeted
        window operators register into at setup."""
        if name not in self._jobs:
            return None
        from ..state.tiering import hit_ratio_series, residency_table
        return {"name": name, "rows": residency_table(name),
                # per-boundary hot-hit-ratio trajectory (bounded ring):
                # the cumulative ratio hides phase changes, the series
                # shows them
                "hit_ratio_series": hit_ratio_series(name)}

    def _profile(self, name: str, top: int = 10) -> Optional[dict]:
        """Device-time ledger view of one job: top-``top`` hot programs
        (with cost-model achieved-vs-estimated), per-operator device-time
        shares, and the recompile-attribution records. Served from the
        process-global ledger; empty-but-valid when profiling is off."""
        if name not in self._jobs:
            return None
        from ..metrics.profiler import DEVICE_LEDGER
        return DEVICE_LEDGER.profile(job=name, top=top)

    def _flight_recorder(self, name: str) -> Optional[dict]:
        """Post-mortem surface: the dump records written so far (stalls,
        restarts, corrupt artifacts, zombie fences) plus the live ring's
        tail, so an operator can fetch the black box without shelling
        into the host."""
        if name not in self._jobs:
            return None
        from ..metrics.tracing import FLIGHT_RECORDER
        return {"name": name,
                "dumps": list(self._job_scoped(FLIGHT_RECORDER.dumps,
                                               name)),
                "recent": FLIGHT_RECORDER.snapshot()[-64:]}

    def _quota(self, name: str) -> Optional[dict]:
        """One job's admission-quota/bulkhead view (cluster/isolation.py):
        weight, deficit, device-time share, breaker state, and the
        rejected/shed counters. Valid-but-inactive jobs report
        ``{"enabled": False}`` when isolation is off."""
        if name not in self._jobs:
            return None
        from .isolation import ISOLATION
        view = ISOLATION.quota_view(name)
        if view is None:
            return {"name": name, "enabled": ISOLATION.enabled}
        view["enabled"] = ISOLATION.enabled
        return view

    def _leader(self, name: str) -> Optional[dict]:
        """Who leads this job's coordinator election (cluster/ha.py):
        current leader owner, fencing epoch, lease age and the announced
        standby roster. 404s for jobs registered without an HA dir —
        a fixed-coordinator job has no leader to report."""
        ha_dir = self._ha_dirs.get(name)
        if name not in self._jobs or ha_dir is None:
            return None
        from .ha import leader_info
        info = leader_info(ha_dir)
        info["name"] = name
        return info

    def _metrics_registry(self):
        """The bound registry, or a lazily-created one carrying only the
        process-global device scope — /metrics must expose compile and
        transfer accounting even for endpoints started without a job
        registry."""
        from ..metrics.device import bind_device_metrics
        from ..metrics.profiler import bind_ledger_metrics

        if self.metrics_registry is None:
            from ..metrics.core import MetricRegistry
            self.metrics_registry = MetricRegistry()
        bind_device_metrics(self.metrics_registry)
        bind_ledger_metrics(self.metrics_registry)
        return self.metrics_registry

    def _metrics_snapshot(self) -> dict:
        from ..metrics.device import DEVICE_STATS
        from ..runtime.watchdog import PROGRESS

        snap = {k: v for k, v in self._metrics_registry().snapshot().items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)}
        snap.update({f"device.{k}": v
                     for k, v in DEVICE_STATS.snapshot().items()})
        # per-task stall-supervision surface: wall-clock since each live
        # subtask's last progress-epoch bump
        snap.update({f"task.{tid}.last_progress_age_ms": age
                     for tid, age in PROGRESS.ages_ms().items()})
        # device-time ledger rollups (per-job device/compile ms) when
        # profiling is on — the dashboard's device panel polls this
        from ..metrics.profiler import DEVICE_LEDGER
        if DEVICE_LEDGER.enabled:
            led = DEVICE_LEDGER.snapshot()
            snap["profiler.device_ms_total"] = led["device_ms_total"]
            snap["profiler.compile_ms_total"] = led["compile_ms_total"]
            snap["profiler.dispatches_total"] = led["dispatches_total"]
            for job, row in led["jobs"].items():
                snap[f"profiler.job.{job}.device_ms"] = row["device_ms"]
                snap[f"profiler.job.{job}.compile_ms"] = row["compile_ms"]
        # multi-tenant quota/bulkhead gauges when isolation is on: the
        # per-job device-time share, breaker state (0 closed / 1 open or
        # half-open), and the rejection/shed counters
        from .isolation import ISOLATION
        if ISOLATION.enabled:
            for job, row in ISOLATION.snapshot()["jobs"].items():
                pre = f"isolation.job.{job}"
                snap[f"{pre}.device_time_share"] = row["device_time_share"]
                snap[f"{pre}.breaker_open"] = int(row["breaker"] != "closed")
                snap[f"{pre}.admissions_rejected_total"] = \
                    row["admissions_rejected_total"]
                snap[f"{pre}.shed_records_total"] = row["shed_records_total"]
                snap[f"{pre}.bulkhead_trips_total"] = \
                    row["bulkhead_trips_total"]
        return snap

    def _trigger_savepoint(self, name: str) -> tuple[int, dict]:
        coord = self._coordinators.get(name)
        job = self._jobs.get(name)
        if coord is None:
            return 409, {"error": "job has no checkpoint coordinator"}
        if job is not None and not any(t.is_alive
                                       for t in job.tasks.values()):
            # a barrier into finished tasks can never be acknowledged;
            # fail fast instead of blocking the handler for the timeout
            return 409, {"error": "job is not running"}
        sp = coord.trigger_savepoint(timeout=self.savepoint_timeout_s)
        return 200, {"id": sp.checkpoint_id,
                     "external_path": sp.external_path}

    # -- server ------------------------------------------------------------
    def start(self) -> int:
        endpoint = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path, _, query = self.path.partition("?")
                parts = [p for p in path.split("/") if p]
                if parts == []:
                    from .webui import DASHBOARD_HTML
                    body = DASHBOARD_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif (len(parts) == 3 and parts[0] == "jobs"
                      and parts[2] == "flamegraph"):
                    fg = endpoint._flamegraph(parts[1])
                    self._reply(200 if fg else 404,
                                fg or {"error": "no such job"})
                elif parts == ["jobs"]:
                    self._reply(200, endpoint._job_overview())
                elif len(parts) == 2 and parts[0] == "jobs":
                    detail = endpoint._job_detail(parts[1])
                    self._reply(200 if detail else 404,
                                detail or {"error": "no such job"})
                elif (len(parts) == 3 and parts[0] == "jobs"
                      and parts[2] == "checkpoints"):
                    self._reply(200, endpoint._checkpoints(parts[1]))
                elif (len(parts) == 3 and parts[0] == "jobs"
                      and parts[2] == "exceptions"):
                    exc = endpoint._exceptions(parts[1])
                    self._reply(200 if exc else 404,
                                exc or {"error": "no such job"})
                elif (len(parts) == 3 and parts[0] == "jobs"
                      and parts[2] == "traces"):
                    tr = endpoint._traces(parts[1])
                    self._reply(200 if tr else 404,
                                tr or {"error": "no such job"})
                elif (len(parts) == 3 and parts[0] == "jobs"
                      and parts[2] == "state-residency"):
                    sr = endpoint._state_residency(parts[1])
                    self._reply(200 if sr else 404,
                                sr or {"error": "no such job"})
                elif (len(parts) == 3 and parts[0] == "jobs"
                      and parts[2] == "profile"):
                    from urllib.parse import parse_qs
                    try:
                        top = int(parse_qs(query).get("top", ["10"])[0])
                    except ValueError:
                        top = 10
                    prof = endpoint._profile(parts[1], top=top)
                    self._reply(200 if prof else 404,
                                prof or {"error": "no such job"})
                elif (len(parts) == 3 and parts[0] == "jobs"
                      and parts[2] == "flight-recorder"):
                    fr = endpoint._flight_recorder(parts[1])
                    self._reply(200 if fr else 404,
                                fr or {"error": "no such job"})
                elif (len(parts) == 3 and parts[0] == "jobs"
                      and parts[2] == "quota"):
                    q = endpoint._quota(parts[1])
                    self._reply(200 if q else 404,
                                q or {"error": "no such job"})
                elif (len(parts) == 3 and parts[0] == "jobs"
                      and parts[2] == "leader"):
                    ldr = endpoint._leader(parts[1])
                    self._reply(200 if ldr else 404,
                                ldr or {"error": "no such job"})
                elif parts == ["metrics", "snapshot"]:
                    self._reply(200, endpoint._metrics_snapshot())
                elif parts == ["metrics"]:
                    from ..metrics.reporters import prometheus_text
                    body = prometheus_text(
                        endpoint._metrics_registry()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._reply(404, {"error": "unknown path"})

            def do_POST(self):  # noqa: N802
                parts = [p for p in self.path.split("/") if p]
                if (len(parts) == 3 and parts[0] == "jobs"
                        and parts[2] == "savepoints"):
                    try:
                        code, payload = endpoint._trigger_savepoint(parts[1])
                        self._reply(code, payload)
                    except Exception as e:  # noqa: BLE001 - return to client
                        self._reply(500, {"error": repr(e)})
                else:
                    self._reply(404, {"error": "unknown path"})

            def log_message(self, *args):
                pass

        from ..utils.httpd import ThreadedHTTPServer
        self._server = ThreadedHTTPServer(Handler, self._requested_port,
                                          self._host, "rest-endpoint")
        self.port = self._server.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop()
            self._server = None
