"""Session-cluster dispatcher + job-submission client.

Reference semantics (SURVEY §2.3/§3.1): a client serializes the JobGraph
and POSTs it to a standing cluster's Dispatcher (Dispatcher.submitJob:514
behind RestServerEndpoint); the dispatcher spawns one master per job
(JobManagerRunner -> JobMaster), tracks execution, and serves status/
cancel/savepoint calls. Here the standing process is a ``Dispatcher``
serving HTTP:

    POST /jobs                        body = cloudpickled (JobGraph, config)
                                      -> {"job_id": ...}
    GET  /jobs                        -> [{job_id, name, state}]
    GET  /jobs/<id>                   -> {state, error?, attempts}
    POST /jobs/<id>/cancel            -> {"state": "CANCELLED"}
    POST /jobs/<id>/savepoints        -> {"id", "external_path"}

and the client is ``ClusterClient`` — build a pipeline locally, then
``ClusterClient(addr).submit(env)`` instead of ``env.execute()``
(reference ClusterClient/RestClusterClient). Job graphs ship as
cloudpickle exactly like the reference ships serialized JobGraphs in the
submit body; each accepted job runs under its own JobSupervisor thread
(restart strategies + checkpointing per the job's config), and completed
jobs can be archived for the history server.
"""

from __future__ import annotations

import json
import pickle
import threading
import time
import urllib.request
import uuid
from typing import Any, Optional

try:
    import cloudpickle as _pickle
except ImportError:  # pragma: no cover - cloudpickle ships in the image
    _pickle = pickle

__all__ = ["Dispatcher", "ClusterClient"]


class _JobRun:
    TERMINAL = ("FINISHED", "FAILED", "CANCELLED")

    def __init__(self, job_id: str, name: str):
        self.job_id = job_id
        self.name = name
        self.state = "CREATED"     # CREATED/RUNNING/FINISHED/FAILED/CANCELLED
        self.error: Optional[str] = None
        self.supervisor = None
        self.thread: Optional[threading.Thread] = None
        self.started_at = time.time()
        self.lock = threading.Lock()   # guards state transitions

    def transition(self, to: str, only_from: Optional[tuple] = None) -> bool:
        with self.lock:
            if self.state in self.TERMINAL:
                return False
            if only_from is not None and self.state not in only_from:
                return False
            self.state = to
            return True


class Dispatcher:
    """Standing session cluster: accepts serialized job graphs and runs
    each under its own supervisor."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 archive_dir: Optional[str] = None,
                 job_timeout_s: float = 3600.0, config=None):
        from ..utils import auth

        self._host = host
        self._requested_port = port
        self._secret = auth.resolve_secret(config)
        auth.check_bind(host, self._secret, "Dispatcher")
        self.archive_dir = archive_dir
        self.job_timeout_s = job_timeout_s
        self._jobs: dict[str, _JobRun] = {}
        self._lock = threading.Lock()
        self._server = None
        self.port: Optional[int] = None

    # -- job lifecycle -----------------------------------------------------
    def submit(self, job_graph, config, restore=None) -> str:
        """``restore`` starts the job from a shipped savepoint/checkpoint
        (the client's --from-savepoint path; reference 'run -s')."""
        from .scheduler import JobSupervisor

        job_id = uuid.uuid4().hex[:12]
        run = _JobRun(job_id, getattr(job_graph, "name", "job"))
        run.supervisor = JobSupervisor(job_graph, config)
        with self._lock:
            self._jobs[job_id] = run

        def drive():
            if not run.transition("RUNNING", only_from=("CREATED",)):
                return  # cancelled before the thread was scheduled
            try:
                run.supervisor.run(timeout=self.job_timeout_s,
                                   initial_restore=restore)
                run.transition("FINISHED")
            except Exception as e:  # noqa: BLE001 - recorded for the client
                if run.transition("FAILED"):
                    run.error = f"{type(e).__name__}: {e}"
            finally:
                # cancelled runs carry partial results: never archive them
                # as a clean completion
                if (self.archive_dir and run.supervisor.current_job
                        and run.state != "CANCELLED"):
                    from .webui import archive_job
                    try:
                        archive_job(self.archive_dir,
                                    f"{run.name}-{job_id}",
                                    run.supervisor.current_job,
                                    run.supervisor.coordinator)
                    except OSError:
                        pass

        run.thread = threading.Thread(target=drive, daemon=True,
                                      name=f"job-{job_id}")
        run.thread.start()
        return job_id

    def cancel(self, job_id: str) -> Optional[bool]:
        """True = cancelled; False = already terminal (a finished/failed
        job keeps its state); None = no such job."""
        run = self._jobs.get(job_id)
        if run is None:
            return None
        if not run.transition("CANCELLED"):
            return False
        sup = run.supervisor
        if sup is not None:
            # the flag closes the deploy window (current_job not yet
            # assigned): the supervisor checks it right after deploying
            sup.cancel_requested = True
            # stop the supervisor's restart loop from resurrecting it
            sup.restart_strategy = _NeverRestart()
            if sup.coordinator is not None:
                sup.coordinator.stop()
            if sup.current_job is not None:
                sup.current_job.cancel()
        return True

    def status(self, job_id: str) -> Optional[dict]:
        run = self._jobs.get(job_id)
        if run is None:
            return None
        return {"job_id": run.job_id, "name": run.name, "state": run.state,
                "error": run.error,
                "attempts": getattr(run.supervisor, "attempt", 0)}

    def overview(self) -> list[dict]:
        with self._lock:
            return [{"job_id": r.job_id, "name": r.name, "state": r.state}
                    for r in self._jobs.values()]

    def _savepoint(self, job_id: str) -> tuple[int, dict]:
        run = self._jobs.get(job_id)
        if run is None:
            return 404, {"error": "no such job"}
        coord = getattr(run.supervisor, "coordinator", None)
        if coord is None or run.state != "RUNNING":
            return 409, {"error": f"job is {run.state}"}
        sp = coord.trigger_savepoint(timeout=60.0)
        return 200, {"id": sp.checkpoint_id,
                     "external_path": sp.external_path}

    # -- http --------------------------------------------------------------
    def start(self) -> int:
        import http.server

        dispatcher = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                parts = [p for p in self.path.split("/") if p]
                if parts == ["jobs"]:
                    self._reply(200, dispatcher.overview())
                elif len(parts) == 2 and parts[0] == "jobs":
                    st = dispatcher.status(parts[1])
                    self._reply(200 if st else 404,
                                st or {"error": "no such job"})
                else:
                    self._reply(404, {"error": "unknown path"})

            def do_POST(self):  # noqa: N802
                parts = [p for p in self.path.split("/") if p]
                try:
                    if parts == ["jobs"]:
                        from ..utils import auth as _auth
                        # token check precedes the unpickle: job
                        # submission bodies are cloudpickle (code)
                        if not _auth.token_ok(
                                self.headers.get(_auth.HTTP_HEADER),
                                dispatcher._secret):
                            self._reply(403, {"error": "bad cluster token"})
                            return
                        n = int(self.headers.get("Content-Length", 0))
                        payload = _pickle.loads(self.rfile.read(n))
                        jg, config = payload[0], payload[1]
                        restore = payload[2] if len(payload) > 2 else None
                        job_id = dispatcher.submit(jg, config, restore)
                        self._reply(200, {"job_id": job_id})
                    elif (len(parts) == 3 and parts[0] == "jobs"
                          and parts[2] == "cancel"):
                        ok = dispatcher.cancel(parts[1])
                        if ok is None:
                            self._reply(404, {"error": "no such job"})
                        elif ok is False:
                            st = dispatcher.status(parts[1])
                            self._reply(409, {"error": "job is already "
                                              f"{st['state']}"})
                        else:
                            self._reply(200, {"state": "CANCELLED"})
                    elif (len(parts) == 3 and parts[0] == "jobs"
                          and parts[2] == "savepoints"):
                        code, payload = dispatcher._savepoint(parts[1])
                        self._reply(code, payload)
                    else:
                        self._reply(404, {"error": "unknown path"})
                except Exception as e:  # noqa: BLE001 - report to client
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

            def log_message(self, *args):
                pass

        from ..utils.httpd import ThreadedHTTPServer
        self._server = ThreadedHTTPServer(Handler, self._requested_port,
                                          self._host, "dispatcher")
        self.port = self._server.start()
        return self.port

    def stop(self) -> None:
        with self._lock:
            ids = list(self._jobs)
        for job_id in ids:
            self.cancel(job_id)
        if self._server is not None:
            self._server.stop()
            self._server = None

    @property
    def address(self) -> str:
        return f"{self._host}:{self.port}"


class _NeverRestart:
    def can_restart(self) -> bool:
        return False

    def backoff_seconds(self) -> float:
        return 0.0

    def notify_failure(self) -> None:
        pass


class ClusterClient:
    """Submit locally-built pipelines to a running Dispatcher
    (reference RestClusterClient)."""

    def __init__(self, address: str, config=None):
        self.address = address
        self._config = config

    def _url(self, path: str) -> str:
        return f"http://{self.address}{path}"

    @staticmethod
    def _raise_with_server_error(e) -> None:
        """Surface the dispatcher's JSON error detail instead of the bare
        'HTTP Error 500' urllib message."""
        try:
            detail = json.loads(e.read().decode()).get("error", "")
        except (ValueError, OSError):
            detail = ""
        raise RuntimeError(
            f"dispatcher returned {e.code}: {detail or e.reason}") from e

    def _get(self, path: str) -> dict:
        import urllib.error
        try:
            with urllib.request.urlopen(self._url(path), timeout=30) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            self._raise_with_server_error(e)

    def _post(self, path: str, body: bytes = b"") -> dict:
        import urllib.error

        from ..utils import auth
        req = urllib.request.Request(self._url(path), data=body,
                                     method="POST")
        secret = auth.resolve_secret(self._config)
        if secret:
            req.add_header(auth.HTTP_HEADER, secret)
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            self._raise_with_server_error(e)

    def submit(self, env_or_graph, config=None, name: str = "job",
               restore=None) -> str:
        """Ship the pipeline to the cluster; returns the job id. Accepts a
        StreamExecutionEnvironment (graph built from its transformations)
        or a prebuilt JobGraph + config. ``restore`` ships a savepoint/
        checkpoint the remote supervisor starts from."""
        if hasattr(env_or_graph, "get_job_graph"):
            config = env_or_graph.config
            jg = env_or_graph.get_job_graph(name)
        else:
            jg = env_or_graph
            if config is None:
                raise ValueError("config required with a raw JobGraph")
        body = _pickle.dumps((jg, config, restore),
                             protocol=pickle.HIGHEST_PROTOCOL)
        return self._post("/jobs", body)["job_id"]

    def status(self, job_id: str) -> dict:
        return self._get(f"/jobs/{job_id}")

    def list_jobs(self) -> list[dict]:
        return self._get("/jobs")

    def cancel(self, job_id: str) -> None:
        self._post(f"/jobs/{job_id}/cancel")

    def trigger_savepoint(self, job_id: str) -> dict:
        return self._post(f"/jobs/{job_id}/savepoints")

    def wait(self, job_id: str, timeout: Optional[float] = 300.0,
             poll_s: float = 0.1) -> dict:
        """Block until the job reaches a terminal state; raises on FAILED.
        ``timeout=None`` waits without bound (matching local execute)."""
        deadline = None if timeout is None else time.time() + timeout
        while deadline is None or time.time() < deadline:
            st = self.status(job_id)
            if st["state"] in ("FINISHED", "FAILED", "CANCELLED"):
                if st["state"] == "FAILED":
                    raise RuntimeError(
                        f"job {job_id} failed: {st.get('error')}")
                return st
            time.sleep(poll_s)
        raise TimeoutError(f"job {job_id} not terminal within {timeout}s")
