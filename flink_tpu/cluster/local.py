"""Local deployment: run a JobGraph as threads in one process.

MiniCluster analog (flink-runtime minicluster/MiniCluster.java:153): real
channels, real barrier alignment, real state backends — multi-subtask
semantics without a cluster. Also the execution engine behind
``env.execute()`` locally (reference LocalExecutor), and the substrate the
failover/cluster layer drives (cluster/scheduler.py restarts these tasks).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..core.config import CheckpointingOptions, Configuration, PipelineOptions
from ..graph.stream_graph import JobGraph, JobVertex
from ..runtime.channels import InputGate, LocalChannel
from ..runtime.operators.base import OperatorChain, OperatorContext, Output
from ..runtime.stream_task import (
    OneInputStreamTask, SourceStreamTask, StreamTask, TaskReporter,
    TwoInputStreamTask,
)
from ..runtime.writer import RecordWriter

__all__ = ["LocalJob", "deploy_local", "run_job"]


@dataclass
class _Deployment:
    """Wiring for one execution attempt."""

    tasks: dict[str, StreamTask] = field(default_factory=dict)
    source_tasks: dict[str, SourceStreamTask] = field(default_factory=dict)


class LocalJob(TaskReporter):
    """One running local job: tasks + reporter + (optional) checkpoint hook."""

    def __init__(self, job_graph: JobGraph, config: Configuration):
        self.job_graph = job_graph
        self.config = config
        self.tasks: dict[str, StreamTask] = {}
        self.source_tasks: dict[str, SourceStreamTask] = {}
        self._finished: set[str] = set()
        self._failed: list[tuple[str, BaseException]] = []
        # a cancelled job's tasks unwind cleanly through task_finished;
        # this flag is how callers tell cancellation from real completion
        self.cancelled = False
        self._lock = threading.Lock()
        self._done = threading.Event()
        self.checkpoint_listener: Optional[Callable] = None  # coordinator hook
        self.metrics_registry = None
        from ..state.queryable import KvStateRegistry
        self.kv_registry = KvStateRegistry()
        from ..runtime.alignment import WatermarkAlignmentCoordinator
        self.watermark_alignment = WatermarkAlignmentCoordinator()
        # bounded per-job failure history (the FailureHandlingResult
        # analog, reference ExceptionHistoryEntry): every task failure,
        # degradation, and restart decision lands here; REST exposes it
        # at /jobs/<name>/exceptions. The supervisor shares ONE deque
        # across restart attempts so history survives redeploys.
        from collections import deque
        self.failure_history: deque = deque(maxlen=64)
        # per-attempt Execution records (reference ExecutionGraph's
        # Execution/ExecutionAttemptID): every deployment of a task id
        # appends one attempt with its state transitions
        self.executions: dict[str, list[dict]] = {}

    # -- execution-attempt tracking ----------------------------------------
    def _exec_new(self, task_id: str) -> None:
        with self._lock:
            attempts = self.executions.setdefault(task_id, [])
            attempts.append({"attempt": len(attempts) + 1,
                             "state": "DEPLOYING", "start": time.time(),
                             "end": None, "failure": None})

    def _exec_set(self, task_id: str, state: str,
                  failure: Optional[str] = None) -> None:
        attempts = self.executions.get(task_id)
        if not attempts:
            return
        rec = attempts[-1]
        if rec["state"] in ("FINISHED", "FAILED", "CANCELED"):
            return                      # terminal states never regress
        rec["state"] = state
        if state in ("FINISHED", "FAILED", "CANCELED"):
            rec["end"] = time.time()
        if failure is not None:
            rec["failure"] = failure

    # -- TaskReporter ------------------------------------------------------
    def acknowledge_checkpoint(self, task_id: str, checkpoint_id: int,
                               snapshot: dict) -> None:
        if self.checkpoint_listener is not None:
            self.checkpoint_listener("ack", task_id, checkpoint_id, snapshot)

    def declined_checkpoint(self, task_id: str, checkpoint_id: int,
                            reason: str) -> None:
        if self.checkpoint_listener is not None:
            self.checkpoint_listener("decline", task_id, checkpoint_id, reason)

    def task_finished(self, task_id: str) -> None:
        with self._lock:
            self._exec_set(task_id,
                           "CANCELED" if self.cancelled else "FINISHED")
            self._finished.add(task_id)
            if len(self._finished) == len(self.tasks):
                self._done.set()

    def task_failed(self, task_id: str, error: BaseException) -> None:
        with self._lock:
            self._exec_set(task_id, "FAILED", failure=repr(error))
            self._failed.append((task_id, error))
            self.failure_history.append({
                "timestamp": time.time(), "task": task_id,
                "job": self.job_graph.name, "kind": "task-failure",
                "error": f"{type(error).__name__}: {error}"})
            self._done.set()
        # feed the owning job's circuit breaker — a task failure is one
        # consecutive-failure step toward its bulkhead shedding instead
        # of restarting forever (cluster/isolation.py)
        from .isolation import ISOLATION
        ISOLATION.note_failure(self.job_graph.name)

    # -- control -----------------------------------------------------------
    def start(self) -> None:
        if not self.tasks:
            # a host can legitimately hold zero subtasks (slot-weighted
            # placement, parallelism < host count): it is trivially done
            self._done.set()
            return
        for tid, t in self.tasks.items():
            t.start()
            with self._lock:
                self._exec_set(tid, "RUNNING")

    def cancel(self) -> None:
        self.cancelled = True
        for t in self.tasks.values():
            t.cancel()
        self._done.set()

    def wait_event(self, timeout: Optional[float] = None) -> bool:
        """Wait for completion OR failure WITHOUT cancelling — the
        supervisor uses this to attempt a region-scoped restart before
        giving up on the whole job."""
        return self._done.wait(timeout)

    def current_failures(self) -> list:
        with self._lock:
            return list(self._failed)

    def wait(self, timeout: Optional[float] = None) -> None:
        if not self._done.wait(timeout):
            self.cancel()
            raise TimeoutError(f"Job did not finish within {timeout}s")
        if self._failed:
            task_id, err = self._failed[0]
            self.cancel()
            raise RuntimeError(f"Task {task_id} failed: {err!r}") from err

    @property
    def failed(self) -> bool:
        return bool(self._failed)


def deploy_local(job_graph: JobGraph, config: Configuration,
                 restored_state: Optional[dict] = None,
                 metrics_registry=None) -> LocalJob:
    """Instantiate channels, gates, writers, chains, and tasks for every
    (vertex, subtask) — the Execution.deploy analog
    (flink-runtime executiongraph/Execution.java:511)."""
    job = LocalJob(job_graph, config)
    job.metrics_registry = metrics_registry
    # arm (or disarm) the process-global fault injector from THIS job's
    # config — idempotent on an unchanged spec, so failover redeploys
    # keep their visit counters (a once@N fault must not re-arm) — and
    # the stall watchdog's per-site deadlines
    from ..runtime.faults import FAULTS
    from ..runtime.watchdog import WATCHDOG
    FAULTS.configure(config)
    WATCHDOG.configure(config)
    # job-wide causal tracing is on by default: the global tracer picks up
    # traces.* limits from this job's config and the compile cache reports
    # device spans into the same trace trees
    from ..metrics.device import set_compile_tracer
    from ..metrics.tracing import TRACER
    TRACER.configure(config)
    set_compile_tracer(TRACER if TRACER.enabled else None)
    # the mesh runtime (axis rules + live-rescale policy) is process-global
    # for the same reason the fault injector is: sharded programs compiled
    # by ANY task must agree on the partition rules
    from ..parallel.plan import MESH_RUNTIME
    MESH_RUNTIME.configure(config)
    # device-time ledger: per-program dispatch profiling + recompile
    # attribution (off by default — profiler.enabled)
    from ..metrics.profiler import DEVICE_LEDGER
    DEVICE_LEDGER.configure(config)
    # multi-tenant isolation: per-job admission quotas + bulkheads are
    # process-global for the same reason — every job sharing the device
    # pool must meter against the same scheduler (off by default)
    from .isolation import ISOLATION
    ISOLATION.configure(config)
    ISOLATION.register_job(job_graph.name)
    # persistent AOT executable cache: warm-start this process's program
    # caches (watchdog-bounded aot.warmup) before the first batch, so a
    # restart/replacement pays zero compile storm (off by default)
    from ..runtime.aot import AOT
    AOT.configure(config)
    AOT.warmup()
    if metrics_registry is not None:
        # process-global compile/transfer accounting surfaces through the
        # same registry the reporters/REST endpoint scrape
        from ..metrics.device import bind_device_metrics
        from ..metrics.profiler import bind_ledger_metrics
        bind_device_metrics(metrics_registry)
        bind_ledger_metrics(metrics_registry)

    # channels[edge_key][src_sub][dst_sub]; feedback channels are UNBOUNDED:
    # a bounded back edge would wedge the body forever once the head exits
    # on quiescence (nothing drains a dead loop), and a live loop blocking
    # on its own output is the classic iteration deadlock the reference
    # documents — growth under a slow head is the accepted tradeoff
    channels: dict[int, list[list[LocalChannel]]] = {}
    for ei, e in enumerate(job_graph.edges):
        src = job_graph.vertices[e.source_vertex]
        dst = job_graph.vertices[e.target_vertex]
        channels[ei] = [
            [LocalChannel(0) if e.feedback else LocalChannel()  # 0=unbounded
             for _ in range(dst.parallelism)]
            for _ in range(src.parallelism)]

    aligned = config.get(CheckpointingOptions.MODE) == "exactly-once"
    unaligned = config.get(CheckpointingOptions.UNALIGNED)
    alignment_timeout = config.get(CheckpointingOptions.ALIGNMENT_TIMEOUT)

    has_feedback = any(e.feedback for e in job_graph.edges)
    if has_feedback and config.get(CheckpointingOptions.INTERVAL) > 0:
        # a barrier circulating a feedback loop would re-align the head
        # forever; the reference's iterations likewise exclude loop state
        # from exactly-once guarantees — reject loudly instead of hanging
        raise ValueError(
            "iterations (feedback edges) cannot run with periodic "
            "checkpointing enabled; disable execution.checkpointing."
            "interval for this job")

    _deploy_vertices(job, job_graph, config, channels, restored_state,
                     metrics_registry, set(job_graph.vertices))
    return job


def restart_region(job: "LocalJob", job_graph: JobGraph,
                   config: Configuration, vids: set,
                   restored_state: Optional[dict] = None) -> list[str]:
    """Pipelined-region failover (reference
    RestartPipelinedRegionFailoverStrategy.java:110): tear down and
    rebuild ONLY the tasks of the given region's vertices inside a live
    job — regions share no channels, so the rest of the job keeps
    running untouched. Returns the restarted task ids."""
    affected = [tid for tid in list(job.tasks)
                if tid.rsplit("#", 1)[0] in vids]
    from ..metrics.tracing import TRACER, dump_flight_recorder
    restart_sb = (TRACER.span("restart", "RegionRestart")
                  .set_attribute("job", job_graph.name)
                  .set_attribute("vertices", sorted(vids))
                  .set_attribute("tasks", len(affected)))
    dump_flight_recorder("region-restart", job=job_graph.name,
                         vertices=sorted(vids), tasks=affected)
    old = []
    for tid in affected:
        t = job.tasks.pop(tid)
        job.source_tasks.pop(tid, None)
        t.cancel()
        with job._lock:
            # region teardown cancels the healthy region-mates of the
            # failed task; their attempt ends CANCELED, not FINISHED
            job._exec_set(tid, "CANCELED")
        old.append(t)
    for t in old:
        # the old attempt must fully unwind BEFORE the new one deploys:
        # its unwind path reports task_finished, which would otherwise
        # mark the restarted task id as already finished
        t.join(10)
    # fresh channels for the region's (internal) edges
    channels: dict[int, list[list[LocalChannel]]] = {}
    for ei, e in enumerate(job_graph.edges):
        if e.source_vertex not in vids:
            continue
        src = job_graph.vertices[e.source_vertex]
        dst = job_graph.vertices[e.target_vertex]
        channels[ei] = [
            [LocalChannel(0) if e.feedback else LocalChannel()
             for _ in range(dst.parallelism)]
            for _ in range(src.parallelism)]
    _deploy_vertices(job, job_graph, config, channels, restored_state,
                     job.metrics_registry, vids)
    with job._lock:
        job._failed = [(tid, err) for tid, err in job._failed
                       if tid.rsplit("#", 1)[0] not in vids]
        # the cancelled attempt's tasks unwound through task_finished;
        # their ids must count again for the NEW attempt
        job._finished -= set(affected)
        job._done.clear()
        if job._failed:
            # a DIFFERENT region failed during this restart window: its
            # wake-up signal must survive the clear
            job._done.set()
    for tid in affected:
        job.tasks[tid].start()
        with job._lock:
            job._exec_set(tid, "RUNNING")
    restart_sb.finish()
    return affected


def live_rescale(job: "LocalJob", n_devices: int,
                 timeout: Optional[float] = None) -> dict:
    """Coordinator-driven live rescale: change every mesh operator's
    worker set (device count) inside a RUNNING job, barrier-aligned and
    exactly-once, without a restart.

    Protocol (the elastic counterpart of restart_region): stage the new
    device count on every mesh operator (request_rescale), then trigger
    ONE aligned checkpoint — each operator applies the staged change on
    its mailbox thread at its snapshot point, where every buffered row is
    folded and every in-flight fire drained, so the barrier that makes
    the checkpoint consistent is the same event that makes the worker-set
    switch consistent. State moves via the checkpoint page format
    (digest-verified; see parallel/rescale.py); derived window planes are
    rebuilt on the new mesh, not shipped. Returns the merged migration
    stats ({keygroups_migrated, bytes_moved, epoch, ...} summed/maxed
    over operators).
    """
    from ..metrics.tracing import TRACER
    from ..parallel.plan import MESH_RUNTIME
    if not MESH_RUNTIME.rescale_enabled:
        raise RuntimeError(
            "live rescale is disabled (mesh.rescale.enabled=false)")
    if timeout is None:
        timeout = MESH_RUNTIME.rescale_timeout_ms / 1000.0
    targets = []
    for tid in list(job.tasks):
        chain = getattr(job.tasks[tid], "chain", None)
        for op in (chain.operators if chain is not None else ()):
            if hasattr(op, "request_rescale"):
                targets.append((tid, op))
    if not targets:
        raise ValueError("live_rescale: job has no mesh operators")
    sb = (TRACER.span("rescale", "Rescale")
          .set_attribute("job", job.job_graph.name)
          .set_attribute("operators", len(targets))
          .set_attribute("new_devices", int(n_devices)))
    try:
        # rescale-up warm start: programs for the NEW mesh shape compile
        # on the first post-switch batch unless their executables are
        # already warm — re-scan the persistent AOT cache (artifacts a
        # prior run at the target scale stored) before the barrier
        from ..runtime.aot import AOT
        if AOT.enabled:
            AOT.warmup()
        old_epochs = {tid: op._rescale_epoch for tid, op in targets}
        for _, op in targets:
            op.request_rescale(n_devices)
        coordinator = getattr(job, "coordinator", None)
        ephemeral = None
        if coordinator is None:
            # no periodic checkpointing on this job: stand up a one-shot
            # coordinator purely to circulate the alignment barrier
            from ..checkpoint.coordinator import CheckpointCoordinator
            ephemeral = coordinator = CheckpointCoordinator(
                job, job.config, tracer=TRACER if TRACER.enabled else None)
        try:
            pending = coordinator.trigger_checkpoint()
            if not pending.done.wait(timeout):
                raise TimeoutError(
                    f"live rescale to {n_devices} devices timed out after "
                    f"{timeout:.1f}s (mesh.rescale.timeout) waiting for the "
                    f"alignment barrier")
            if pending.completed is None:
                raise RuntimeError(
                    f"live rescale checkpoint {pending.checkpoint_id} was "
                    f"declined; worker set unchanged")
        finally:
            if ephemeral is not None:
                job.checkpoint_listener = None
        stale = [tid for tid, op in targets
                 if op._rescale_epoch <= old_epochs[tid]]
        if stale:
            raise RuntimeError(
                f"live rescale barrier completed but operators {stale} did "
                f"not bump their mesh epoch")
        merged = {"new_devices": int(n_devices), "operators": len(targets),
                  "keygroups_migrated": 0, "bytes_moved": 0,
                  "duration_ms": 0.0, "epoch": 0}
        for _, op in targets:
            st = op._last_rescale_stats or {}
            merged["keygroups_migrated"] += st.get("keygroups_migrated", 0)
            merged["bytes_moved"] += st.get("bytes_moved", 0)
            merged["duration_ms"] = max(merged["duration_ms"],
                                        st.get("duration_ms", 0.0))
            merged["epoch"] = max(merged["epoch"], st.get("epoch", 0))
        sb.set_attribute("keygroups_migrated", merged["keygroups_migrated"])
        sb.set_attribute("bytes_moved", merged["bytes_moved"])
        sb.set_attribute("epoch", merged["epoch"])
        return merged
    except BaseException as e:
        sb.set_attribute("error", repr(e))
        raise
    finally:
        sb.finish()


def _deploy_vertices(job: "LocalJob", job_graph: JobGraph,
                     config: Configuration, channels: dict,
                     restored_state: Optional[dict],
                     metrics_registry, vids: set) -> None:
    from ..metrics.core import TaskMetrics

    aligned = config.get(CheckpointingOptions.MODE) == "exactly-once"
    unaligned = config.get(CheckpointingOptions.UNALIGNED)
    alignment_timeout = config.get(CheckpointingOptions.ALIGNMENT_TIMEOUT)

    for vid, vertex in job_graph.vertices.items():
        if vid not in vids:
            continue
        out_edges = [(ei, e) for ei, e in enumerate(job_graph.edges)
                     if e.source_vertex == vid]
        in_edges = [(ei, e) for ei, e in enumerate(job_graph.edges)
                    if e.target_vertex == vid]
        for sub in range(vertex.parallelism):
            task_id = f"{vid}#{sub}"
            metrics = (TaskMetrics(metrics_registry, job_graph.name, vid, sub)
                       if metrics_registry is not None else None)
            ctx = OperatorContext(
                task_name=vertex.name, subtask_index=sub,
                parallelism=vertex.parallelism,
                max_parallelism=vertex.max_parallelism,
                config=config, metrics=metrics, operator_id=vertex.id,
                kv_registry=job.kv_registry)

            # writers: one per (non-side) out edge; side writers by tag;
            # feedback edges get the filtering writer (records only).
            # Backpressure waits are capped (task.backpressure.stall-
            # timeout) so a stuck-but-alive downstream peer raises
            # StallError into the supervisor instead of wedging the task
            from ..core.config import WatchdogOptions
            from ..runtime.writer import FeedbackRecordWriter
            bp_stall = float(config.get(
                WatchdogOptions.BACKPRESSURE_STALL_TIMEOUT))
            writers, side_writers = [], {}
            for ei, e in out_edges:
                cls = FeedbackRecordWriter if e.feedback else RecordWriter
                w = cls([channels[ei][sub][d]
                         for d in range(len(channels[ei][sub]))],
                        e.partitioner_factory(), sub,
                        stall_timeout=bp_stall)
                if e.side_tag is None:
                    writers.append(w)
                else:
                    side_writers.setdefault(e.side_tag, []).append(w)

            snapshot = (restored_state or {}).get(task_id)

            if vertex.kind == "source":
                src_node = vertex.chained_nodes[0]
                chain_ops = [n.operator_factory()
                             for n in vertex.chained_nodes[1:]]
                reader = _make_reader(src_node, sub, vertex.parallelism)
                # certified fused-chain lowering: the fusion certificate
                # (graph/fusion.py) proved this vertex's source→window
                # prefix collapses to one dispatch — arm both ends. Runtime
                # gates (deferred overflow on the operator, a timestamp
                # column on the reader) can still decline, in which case
                # the chain runs exactly as before.
                cert = getattr(job_graph, "certificate", None)
                rep = (cert.chain_for_vertex(vid)
                       if cert is not None else None)
                if (rep is not None and rep.lowered_prefix and chain_ops
                        and hasattr(reader, "enable_fused")
                        and hasattr(chain_ops[0], "enable_fused_chain")
                        and chain_ops[0].enable_fused_chain(
                            src_node.source, sub, vertex.parallelism)):
                    if not reader.enable_fused():
                        chain_ops[0]._fused_spec = None
                task = SourceStreamTask(
                    task_id, ctx, src_node.source, reader,
                    src_node.watermark_strategy,
                    None, writers, job, config)
                task.side_writers = side_writers
                if chain_ops:
                    task.chain = OperatorChain(
                        chain_ops, ctx, task.make_tail_output(),
                        side_outputs=_side_outputs_map(side_writers, metrics))
                if snapshot:
                    task.restore_state(snapshot)
                job.source_tasks[task_id] = task
            elif vertex.kind == "two_input":
                # one gate per logical input (reference TwoInputStreamTask)
                per_input: list[list] = [[], []]
                for ei, e in in_edges:
                    for s in range(len(channels[ei])):
                        per_input[e.target_input].append(channels[ei][s][sub])
                ops = [n.operator_factory() for n in vertex.chained_nodes]
                task = TwoInputStreamTask.__new__(TwoInputStreamTask)
                StreamTask.__init__(task, task_id, ctx, writers, job, config,
                                    side_writers=side_writers)
                task.gates = [
                    InputGate(per_input[0], aligned=aligned,
                              unaligned=unaligned and aligned,
                              alignment_timeout_s=alignment_timeout),
                    InputGate(per_input[1], aligned=aligned,
                              unaligned=unaligned and aligned,
                              alignment_timeout_s=alignment_timeout)]
                task._gate_barrier = [None, None]
                task._unaligned_pending = None
                task._restored_inflight = [[], []]
                task.chain = OperatorChain(
                    ops, ctx, task.make_tail_output(),
                    side_outputs=_side_outputs_map(side_writers, metrics))
                if snapshot:
                    task.restore_state(snapshot)
            else:
                # input gate over all in-edges' channels for this subtask
                in_channels, feedback_idx = [], set()
                for ei, e in in_edges:
                    for s in range(len(channels[ei])):
                        if e.feedback:
                            feedback_idx.add(len(in_channels))
                        in_channels.append(channels[ei][s][sub])
                head_node = vertex.chained_nodes[0]
                if getattr(head_node, "iteration_head", False):
                    from ..runtime.channels import IterationGate
                    gate = IterationGate(
                        in_channels, feedback_idx,
                        head_node.iteration_wait_s, aligned=aligned)
                else:
                    gate = InputGate(in_channels, aligned=aligned,
                                     unaligned=unaligned and aligned,
                                     alignment_timeout_s=alignment_timeout)
                ops = [n.operator_factory() for n in vertex.chained_nodes]
                task = OneInputStreamTask.__new__(OneInputStreamTask)
                StreamTask.__init__(task, task_id, ctx, writers, job, config,
                                    side_writers=side_writers)
                task.gate = gate
                task._restored_inflight = []
                task._unaligned_pending = None
                task.chain = OperatorChain(
                    ops, ctx, task.make_tail_output(),
                    side_outputs=_side_outputs_map(side_writers, metrics))
                if snapshot:
                    task.restore_state(snapshot)
            job.tasks[task_id] = task
            job._exec_new(task_id)


def _side_outputs_map(side_writers, metrics) -> Optional[dict[str, Output]]:
    if not side_writers:
        return None
    from ..runtime.stream_task import _WriterFanout
    return {tag: _WriterFanout(ws, metrics) for tag, ws in side_writers.items()}


def _make_reader(src_node, subtask: int, parallelism: int):
    source = src_node.source
    splits = source.create_splits(parallelism)
    reader = source.create_reader(splits[subtask])
    reader._parallelism = parallelism
    return reader


def run_job(job_graph: JobGraph, config: Configuration,
            timeout: Optional[float] = 120.0,
            metrics_registry=None,
            restored_state: Optional[dict] = None) -> LocalJob:
    """Deploy, optionally attach periodic checkpointing, run to completion."""
    job = deploy_local(job_graph, config, restored_state=restored_state,
                       metrics_registry=metrics_registry)
    coordinator = None
    interval = config.get(CheckpointingOptions.INTERVAL)
    if interval and interval > 0:
        from ..checkpoint.coordinator import CheckpointCoordinator
        from ..metrics.tracing import TRACER
        coordinator = CheckpointCoordinator(
            job, config, tracer=TRACER if TRACER.enabled else None)
        coordinator.start_periodic()
    job.coordinator = coordinator
    # task-progress supervision: without a supervisor there is no restart
    # path, but a stalled subtask still FAILS the job with a typed
    # StallError instead of blocking job.wait until its timeout with
    # zero signal
    from ..core.config import WatchdogOptions
    from ..runtime.watchdog import TaskStallDetector
    detector = TaskStallDetector(
        job, float(config.get(WatchdogOptions.TASK_STALL_TIMEOUT))).start()
    job.start()
    try:
        job.wait(timeout)
    finally:
        detector.stop()
        if coordinator is not None:
            coordinator.stop()
    return job
