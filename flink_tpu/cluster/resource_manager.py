"""Resource manager: slot registry, declarative requirements, blocklist.

Reference semantics (SURVEY §2.3): ResourceManager.java:119 brokers slot
requests against registered TaskExecutors through the SlotManager
(slotmanager/DeclarativeSlotManager.java:67 — jobs *declare* requirements,
the manager matches them as workers come and go), and the blocklist
(runtime/blocklist/BlocklistHandler.java) excludes misbehaving nodes from
scheduling until a timeout passes.

TPU-native shape: there is no per-subtask slot *object* to ship around — the
SPMD deployment (cluster/distributed.py) needs one thing from resource
management: a **deterministic schedule**, the host sequence that subtask
``i`` maps onto. The SlotManager therefore resolves (live workers × slot
counts × blocklist) into ``schedule()`` — host ``h`` appears ``slots[h]``
times, round-robin interleaved — and placement is
``schedule[sub % len(schedule)]`` everywhere. That keeps the reference's capacity semantics (a 2-slot worker
takes twice the subtasks of a 1-slot worker; a blocked worker takes none)
while staying a pure function every SPMD host can evaluate identically.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["SlotManager", "Blocklist", "BlockedNode",
           "InsufficientResourcesError", "build_schedule"]


class InsufficientResourcesError(RuntimeError):
    """Declared requirements exceed registered capacity (reference
    NoResourceAvailableException)."""


@dataclass
class BlockedNode:
    host_id: int
    reason: str
    until: float  # absolute deadline; float('inf') = permanent


class Blocklist:
    """Nodes excluded from scheduling (reference BlocklistHandler: block on
    repeated failures, auto-expire after the timeout)."""

    def __init__(self):
        self._nodes: dict[int, BlockedNode] = {}
        self._lock = threading.Lock()

    def block(self, host_id: int, reason: str,
              ttl: Optional[float] = None) -> None:
        until = float("inf") if ttl is None else time.time() + ttl
        with self._lock:
            cur = self._nodes.get(host_id)
            # extending an existing block keeps the later deadline
            if cur is None or until > cur.until:
                self._nodes[host_id] = BlockedNode(host_id, reason, until)

    def unblock(self, host_id: int) -> None:
        with self._lock:
            self._nodes.pop(host_id, None)

    def is_blocked(self, host_id: int) -> bool:
        with self._lock:
            node = self._nodes.get(host_id)
            if node is None:
                return False
            if time.time() >= node.until:
                del self._nodes[host_id]
                return False
            return True

    def active(self) -> list[BlockedNode]:
        now = time.time()
        with self._lock:
            expired = [h for h, n in self._nodes.items() if now >= n.until]
            for h in expired:
                del self._nodes[h]
            return sorted(self._nodes.values(), key=lambda n: n.host_id)


@dataclass
class _Worker:
    host_id: int
    slots: int
    registered_at: float = field(default_factory=time.time)


def build_schedule(slots: dict[int, int]) -> list[int]:
    """Deterministic host sequence: host ``h`` appears ``slots[h]`` times,
    round-robin interleaved (one entry per host per pass, ascending id, while
    capacity remains). Placement = schedule[sub % len(schedule)].

    Interleaving keeps low subtask indices spread across hosts — with
    uniform slot counts this reduces exactly to the unweighted
    ``live[sub % len(live)]`` placement, and with skewed counts every host
    still receives work before any host receives its second share."""
    remaining = {h: s for h, s in slots.items() if s > 0}
    if not remaining:
        raise InsufficientResourcesError(
            f"no host contributes a positive slot count: {slots}")
    out: list[int] = []
    while remaining:
        for h in sorted(remaining):
            out.append(h)
            remaining[h] -= 1
            if remaining[h] == 0:
                del remaining[h]
    return out


class SlotManager:
    """Registry of workers and their slot capacity + declared requirements
    (reference DeclarativeSlotManager: requirements are a standing
    declaration, fulfillment is re-evaluated as workers register/die)."""

    def __init__(self, blocklist: Optional[Blocklist] = None):
        self._workers: dict[int, _Worker] = {}
        self._required = 0
        self._lock = threading.Lock()
        self.blocklist = blocklist or Blocklist()

    # -- registry ----------------------------------------------------------
    def register_worker(self, host_id: int, slots: int = 1) -> None:
        with self._lock:
            self._workers[host_id] = _Worker(host_id, slots)

    def unregister_worker(self, host_id: int) -> None:
        with self._lock:
            self._workers.pop(host_id, None)

    def workers(self) -> list[int]:
        with self._lock:
            return sorted(self._workers)

    # -- requirements ------------------------------------------------------
    def declare_requirements(self, slots: int) -> None:
        with self._lock:
            self._required = slots

    def free_slots(self) -> int:
        return max(self.total_slots() - self._required, 0)

    def total_slots(self) -> int:
        with self._lock:
            return sum(w.slots for w in self._workers.values()
                       if not self.blocklist.is_blocked(w.host_id))

    def fulfilled(self) -> bool:
        return self.total_slots() >= self._required

    # -- scheduling --------------------------------------------------------
    def slots_map(self, live: Optional[list[int]] = None) -> dict[int, int]:
        """Usable slot counts: registered, alive (in ``live`` when given),
        not blocklisted."""
        with self._lock:
            out = {}
            for h, w in self._workers.items():
                if live is not None and h not in live:
                    continue
                if self.blocklist.is_blocked(h):
                    continue
                out[h] = w.slots
            return out

    def schedule(self, live: Optional[list[int]] = None,
                 required: Optional[int] = None) -> list[int]:
        """The deterministic placement sequence; raises when capacity can't
        cover ``required`` (default: the standing declaration)."""
        slots = self.slots_map(live)
        need = self._required if required is None else required
        total = sum(slots.values())
        if total < need:
            raise InsufficientResourcesError(
                f"need {need} slots, have {total} "
                f"(workers={sorted(slots)}, "
                f"blocked={[n.host_id for n in self.blocklist.active()]})")
        if total == 0:
            raise InsufficientResourcesError("no usable workers")
        return build_schedule(slots)
