"""Inter-host data plane: TCP channels with credit-based flow control.

Analog of the reference's Netty network stack (flink-runtime
io/network/netty/: NettyServer/NettyClient, PartitionRequestQueue,
CreditBasedPartitionRequestClientHandler; consumer/RemoteInputChannel.java:68
with exclusive credits announced upstream — backpressure is absence of
credit). This is the DCN leg of the §5.8 split: intra-slice exchange rides
XLA collectives over ICI (parallel/), while cross-host dataflow edges carry
serialized columnar batches over TCP behind the same Channel interface the
local runtime uses — tasks cannot tell local and remote edges apart.

Wire protocol (little-endian, length-prefixed):
    frame   := u32 length, u8 type, payload
    HELLO   := channel key (utf-8)         -- sender registers its edge
    BATCH   := serialize_batch bytes       -- one RecordBatch
    CONTROL := pickled stream element      -- watermark/barrier/end-of-input
    CREDIT  := u32 n                       -- receiver grants n more sends

Each logical edge (edge id, src subtask, dst subtask) is one TCP connection;
the receiver grants ``INITIAL_CREDITS`` up front and re-grants as the task
drains its queue, so a slow consumer stalls exactly its upstream producer —
the same per-channel backpressure story as the reference's credit loop.
"""

from __future__ import annotations

import pickle
import queue
import socket
import struct
import threading
import time
from typing import Any, Callable, Optional

from ..core.records import RecordBatch
from ..core.serializers import deserialize_batch, serialize_batch
from ..runtime.channels import Channel

__all__ = ["RemoteChannelSender", "TransportServer", "INITIAL_CREDITS"]

INITIAL_CREDITS = 32

_LEN = struct.Struct("<I")
_TYPE_HELLO = 0
_TYPE_BATCH = 1
_TYPE_CONTROL = 2
_TYPE_CREDIT = 3


def _send_frame(sock: socket.socket, ftype: int, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload) + 1) + bytes([ftype]) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Optional[tuple[int, bytes]]:
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (length,) = _LEN.unpack(head)
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return body[0], body[1:]


class RemoteChannelSender(Channel):
    """Producer end of a cross-host edge (the RemoteInputChannel's upstream
    counterpart): serializes elements, spends credits, blocks without."""

    def __init__(self, host: str, port: int, channel_key: str,
                 connect_timeout: float = 30.0):
        deadline = time.time() + connect_timeout
        last_err: Optional[Exception] = None
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=5.0)
                break
            except OSError as e:  # receiver may not be up yet
                last_err = e
                if time.time() >= deadline:
                    raise ConnectionError(
                        f"cannot reach {host}:{port} for {channel_key}"
                    ) from last_err
                time.sleep(0.1)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._key = channel_key
        self._credits = threading.Semaphore(0)
        self._closed = threading.Event()
        _send_frame(self._sock, _TYPE_HELLO, channel_key.encode())
        self._reader = threading.Thread(target=self._credit_loop,
                                        name=f"credits-{channel_key}",
                                        daemon=True)
        self._reader.start()

    def _credit_loop(self) -> None:
        try:
            while not self._closed.is_set():
                frame = _recv_frame(self._sock)
                if frame is None:
                    break
                ftype, payload = frame
                if ftype == _TYPE_CREDIT:
                    (n,) = _LEN.unpack(payload)
                    for _ in range(n):
                        self._credits.release()
        except OSError:
            pass
        finally:
            self._closed.set()
            # unblock any waiting put() so the task sees the broken pipe
            self._credits.release()

    def put(self, element: Any, timeout: Optional[float] = None) -> bool:
        if not self._credits.acquire(timeout=timeout):
            return False  # no credit: backpressure
        if self._closed.is_set():
            raise ConnectionError(f"remote channel {self._key} closed")
        if isinstance(element, RecordBatch):
            _send_frame(self._sock, _TYPE_BATCH, serialize_batch(element))
        else:
            _send_frame(self._sock, _TYPE_CONTROL,
                        pickle.dumps(element,
                                     protocol=pickle.HIGHEST_PROTOCOL))
        return True

    def poll(self) -> Optional[Any]:
        raise RuntimeError("sender side of a remote channel cannot poll")

    def size(self) -> int:
        return 0

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass


class _ReceiverChannel(Channel):
    """Consumer end: a local queue fed by the transport server; polling
    grants credits back upstream."""

    def __init__(self, grant: Callable[[int], None]):
        self._q: queue.Queue = queue.Queue()
        self._grant = grant

    def _enqueue(self, element: Any) -> None:
        self._q.put(element)

    def put(self, element: Any, timeout: Optional[float] = None) -> bool:
        raise RuntimeError("receiver side of a remote channel cannot put")

    def poll(self) -> Optional[Any]:
        try:
            e = self._q.get_nowait()
        except queue.Empty:
            return None
        self._grant(1)  # consumed one element: re-grant its credit
        return e

    def size(self) -> int:
        return self._q.qsize()


class TransportServer:
    """Per-host data-plane server (reference NettyServer +
    PartitionRequestServerHandler): accepts one connection per incoming
    edge, demuxes by channel key into receiver channels."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 initial_credits: int = INITIAL_CREDITS):
        self._initial_credits = initial_credits
        self._channels: dict[str, _ReceiverChannel] = {}
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(128)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               name="transport-accept",
                                               daemon=True)
        self._accept_thread.start()

    def channel(self, channel_key: str) -> Channel:
        """The local Channel for an incoming edge; register before (or
        after) the remote sender connects — both orders work."""
        with self._lock:
            ch = self._channels.get(channel_key)
            if ch is None:
                ch = _ReceiverChannel(lambda n: None)  # grant wired on HELLO
                self._channels[channel_key] = ch
            return ch

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="transport-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_lock = threading.Lock()

        def grant(n: int) -> None:
            try:
                with send_lock:
                    _send_frame(conn, _TYPE_CREDIT, _LEN.pack(n))
            except OSError:
                pass

        channel: Optional[_ReceiverChannel] = None
        try:
            frame = _recv_frame(conn)
            if frame is None or frame[0] != _TYPE_HELLO:
                return
            key = frame[1].decode()
            with self._lock:
                channel = self._channels.get(key)
                if channel is None:
                    channel = _ReceiverChannel(grant)
                    self._channels[key] = channel
                else:
                    channel._grant = grant
            grant(self._initial_credits)
            while not self._stop.is_set():
                frame = _recv_frame(conn)
                if frame is None:
                    return
                ftype, payload = frame
                if ftype == _TYPE_BATCH:
                    channel._enqueue(deserialize_batch(payload))
                elif ftype == _TYPE_CONTROL:
                    channel._enqueue(pickle.loads(payload))
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
