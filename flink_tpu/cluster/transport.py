"""Inter-host data plane: TCP channels with credit-based flow control
and partition-tolerant, sequence-numbered delivery.

Analog of the reference's Netty network stack (flink-runtime
io/network/netty/: NettyServer/NettyClient, PartitionRequestQueue,
CreditBasedPartitionRequestClientHandler; consumer/RemoteInputChannel.java:68
with exclusive credits announced upstream — backpressure is absence of
credit). This is the DCN leg of the §5.8 split: intra-slice exchange rides
XLA collectives over ICI (parallel/), while cross-host dataflow edges carry
serialized columnar batches over TCP behind the same Channel interface the
local runtime uses — tasks cannot tell local and remote edges apart.

A TCP connection's life is decoupled from the logical edge's: every data
frame carries a monotone sequence number, the receiver acknowledges
delivery, and the sender keeps unacked frames in a bounded replay buffer.
On socket death the sender reconnects with backoff under the
``net.reconnect-timeout`` deadline, re-HELLOs with (channel key, attempt
epoch, last-acked seq), and replays the buffer; the receiver dedups
already-delivered frames by sequence number — a severed-and-restored
connection is exactly-once with ZERO region restarts. Only deadline
expiry escalates into the StallError -> region-restart ladder. A HELLO
whose attempt epoch is older than the server's is answered with FENCED:
the zombie attempt's sends fail with :class:`FencedError` instead of
feeding a deposed job's data into the new attempt.

Wire protocol (little-endian, length-prefixed):
    frame   := u32 length, u8 type, payload
    HELLO   := u64 epoch, u64 last-acked seq, channel key (utf-8)
    BATCH   := u64 seq, serialize_batch bytes   -- one RecordBatch
    CONTROL := u64 seq, pickled stream element  -- watermark/barrier/eoi
    CREDIT  := u32 n          -- receiver grants n more sends
    ACK     := u64 seq        -- receiver: delivered through seq
    FENCED  := u64 epoch      -- receiver: sender's attempt is deposed

Each logical edge (edge id, src subtask, dst subtask) is one connection;
the receiver grants ``INITIAL_CREDITS`` up front and re-grants as the task
drains its queue, so a slow consumer stalls exactly its upstream producer —
the same per-channel backpressure story as the reference's credit loop.
"""

from __future__ import annotations

import pickle
import queue
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from ..core.records import RecordBatch
from ..core.serializers import deserialize_batch, serialize_batch
from ..runtime.channels import Channel

__all__ = ["RemoteChannelSender", "TransportServer", "INITIAL_CREDITS",
           "FencedError", "NET_EVENTS"]

INITIAL_CREDITS = 32

_LEN = struct.Struct("<I")
_SEQ = struct.Struct("<Q")
_HELLO = struct.Struct("<QQ")
_TYPE_HELLO = 0
_TYPE_BATCH = 1
_TYPE_CONTROL = 2
_TYPE_CREDIT = 3
_TYPE_ACK = 4
_TYPE_FENCED = 5

#: Bounded transport event log (reconnects, fenced peers, socket errors
#: that used to be silently swallowed), merged into REST
#: ``/jobs/<name>/exceptions`` alongside the watchdog's stall events.
NET_EVENTS: deque = deque(maxlen=256)


def _note_net_event(kind: str, **fields) -> None:
    e = {"timestamp": time.time(), "kind": kind}
    e.update(fields)
    NET_EVENTS.append(e)


def _note_net_error(direction: str, err: BaseException, **fields) -> None:
    from ..metrics.device import DEVICE_STATS
    DEVICE_STATS.note_net_error(direction)
    _note_net_event("network-error", direction=direction,
                    error=f"{type(err).__name__}: {err}", **fields)


class FencedError(ConnectionError):
    """The peer rejected this sender's attempt epoch: a newer execution
    attempt owns the job, so the deposed (zombie) attempt must cancel —
    retrying or reconnecting cannot help."""


def _send_frame(sock: socket.socket, ftype: int, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload) + 1) + bytes([ftype]) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Optional[tuple[int, bytes]]:
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (length,) = _LEN.unpack(head)
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return body[0], body[1:]


class RemoteChannelSender(Channel):
    """Producer end of a cross-host edge (the RemoteInputChannel's upstream
    counterpart): serializes elements, spends credits, blocks without.

    Self-healing: sequence-numbers every frame into a bounded replay
    buffer and survives socket death by reconnecting under the
    ``net.reconnect-timeout`` deadline — see the module docstring for the
    resume protocol. ``connect_timeout`` is accepted as a legacy alias
    for ``reconnect_timeout``."""

    def __init__(self, host: str, port: int, channel_key: str,
                 connect_timeout: Optional[float] = None,
                 epoch: int = 0,
                 reconnect_timeout: Optional[float] = None,
                 reconnect_backoff: float = 0.05,
                 replay_capacity: int = 1024):
        from ..runtime.watchdog import WATCHDOG

        self._addr = (host, port)
        self._key = channel_key
        self._epoch = int(epoch)
        if reconnect_timeout is None:
            reconnect_timeout = connect_timeout
        if reconnect_timeout is None:
            reconnect_timeout = WATCHDOG.deadline_for("net.reconnect")
        self._reconnect_timeout = float(reconnect_timeout)
        self._backoff = float(reconnect_backoff)
        self._replay_capacity = int(replay_capacity)
        self._credits = threading.Semaphore(0)
        self._closed = threading.Event()     # explicit close() only
        self._fenced = threading.Event()
        self._peer_epoch: Optional[int] = None
        # _io_lock guards the socket writes, the replay buffer and the
        # connection generation; _conn_lock serializes whole (re)connect
        # procedures so racing threads don't each dial the peer
        self._io_lock = threading.RLock()
        self._conn_lock = threading.Lock()
        self._gen = 0            # bumped per established connection
        self._conn_dead = True
        self._sock: Optional[socket.socket] = None
        self._seq = 0            # last assigned sequence number
        self._acked = 0          # highest seq the receiver confirmed
        self._buffer: deque = deque()  # unacked (seq, ftype, payload)
        self.reconnects = 0      # observability (tests/bench)
        self.replayed_frames = 0
        # the INITIAL connect is bounded by the same net.reconnect
        # deadline as every later reconnect (it used to spin on a
        # hard-coded window) and raises the same typed StallError
        self._reconnect(observed_gen=0, initial=True)

    # -- connection lifecycle ---------------------------------------------
    def _raise_if_dead(self) -> None:
        if self._closed.is_set():
            raise ConnectionError(f"remote channel {self._key} closed")
        if self._fenced.is_set():
            raise FencedError(
                f"remote channel {self._key} fenced: attempt epoch "
                f"{self._epoch} deposed by peer epoch {self._peer_epoch}")

    def _reconnect(self, observed_gen: int, initial: bool = False) -> None:
        """(Re)establish the connection, re-HELLO with (key, epoch,
        last-acked seq) and replay every unacked frame. The loser of a
        connect race returns once the winner's generation is live.
        Bounded by ``net.reconnect-timeout``; expiry raises the typed
        StallError that feeds the existing region-restart ladder. A zero
        deadline disables reconnection of an ESTABLISHED connection
        (fail fast into the ladder) but still allows the initial
        connect its one attempt."""
        from ..metrics.tracing import TRACER, now_ms
        from ..runtime.faults import FAULTS, InjectedFault
        from ..runtime.watchdog import WATCHDOG

        reconnect_start = now_ms()
        with self._conn_lock:
            with self._io_lock:
                if self._gen > observed_gen and not self._conn_dead:
                    return  # another thread already healed it
            self._raise_if_dead()
            if not initial and self._reconnect_timeout <= 0:
                raise WATCHDOG.note_stall(
                    "net.reconnect", self._reconnect_timeout,
                    scope=self._key)
            deadline = time.monotonic() + self._reconnect_timeout
            attempts = 0
            while True:
                self._raise_if_dead()
                attempts += 1
                try:
                    if FAULTS.enabled:
                        FAULTS.fire("net.connect")
                    sock = socket.create_connection(self._addr, timeout=5.0)
                    break
                except (OSError, InjectedFault):
                    if time.monotonic() >= deadline:
                        raise WATCHDOG.note_stall(
                            "net.reconnect", self._reconnect_timeout,
                            scope=self._key)
                    time.sleep(self._backoff)
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._io_lock:
                self._sock = sock
                self._gen += 1
                gen = self._gen
                self._conn_dead = False
                # stale credits belong to the dead connection's window;
                # the new connection re-grants from scratch
                while self._credits.acquire(blocking=False):
                    pass
                _send_frame(sock, _TYPE_HELLO,
                            _HELLO.pack(self._epoch, self._acked)
                            + self._key.encode())
                replay = list(self._buffer)
                for seq, ftype, payload in replay:
                    _send_frame(sock, ftype, _SEQ.pack(seq) + payload)
                self.replayed_frames += len(replay)
            if not initial:
                from ..metrics.device import DEVICE_STATS
                self.reconnects += 1
                DEVICE_STATS.note_net_reconnect("data")
                _note_net_event("network-reconnect", channel=self._key,
                                attempts=attempts, replayed=len(replay))
                (TRACER.span("net", "Reconnect")
                 .set_attribute("channel", self._key)
                 .set_attribute("attempts", attempts)
                 .set_attribute("replayed", len(replay))
                 .set_start_ts(reconnect_start)
                 .finish())
            threading.Thread(target=self._receive_loop, args=(sock, gen),
                             name=f"credits-{self._key}",
                             daemon=True).start()

    def _mark_dead(self, gen: int) -> None:
        with self._io_lock:
            if self._gen == gen:
                self._conn_dead = True

    def _receive_loop(self, sock: socket.socket, gen: int) -> None:
        """Per-connection reader: credits, delivery acks (prune the
        replay buffer), and the fencing verdict."""
        try:
            while not self._closed.is_set():
                frame = _recv_frame(sock)
                if frame is None:
                    break
                ftype, payload = frame
                if ftype == _TYPE_CREDIT:
                    (n,) = _LEN.unpack(payload)
                    for _ in range(n):
                        self._credits.release()
                elif ftype == _TYPE_ACK:
                    (seq,) = _SEQ.unpack(payload)
                    with self._io_lock:
                        if seq > self._acked:
                            self._acked = seq
                        while (self._buffer
                               and self._buffer[0][0] <= self._acked):
                            self._buffer.popleft()
                elif ftype == _TYPE_FENCED:
                    (peer_epoch,) = _SEQ.unpack(payload)
                    self._peer_epoch = peer_epoch
                    self._fenced.set()
                    break
        except OSError:
            pass
        finally:
            self._mark_dead(gen)
            # unblock any waiting put() so the task notices the break
            self._credits.release()
            try:
                sock.close()
            except OSError:
                pass
            self._heal_tail(gen)

    def _heal_tail(self, gen: int) -> None:
        """Unacked frames with no future put() to carry them (a sever
        right after the last frame of the stream) are re-delivered from
        here; failures stay best-effort — a later put escalates, and a
        receiver starved of its tail hits task-progress supervision."""
        from ..runtime.watchdog import StallError

        if self._closed.is_set() or self._fenced.is_set():
            return
        with self._io_lock:
            pending = bool(self._buffer)
        if not pending:
            return
        try:
            self._reconnect(gen)
        except (ConnectionError, StallError):
            pass

    # -- the Channel surface ----------------------------------------------
    def put(self, element: Any, timeout: Optional[float] = None) -> bool:
        from ..runtime.faults import FAULTS

        if not self._credits.acquire(timeout=timeout):
            return False  # no credit: backpressure
        self._raise_if_dead()
        if isinstance(element, RecordBatch):
            ftype, payload = _TYPE_BATCH, serialize_batch(element)
        else:
            ftype, payload = _TYPE_CONTROL, pickle.dumps(
                element, protocol=pickle.HIGHEST_PROTOCOL)
        with self._io_lock:
            if len(self._buffer) >= self._replay_capacity:
                # credits bound in-flight frames far below this: an
                # overflowing buffer means the receiver stopped acking
                raise ConnectionError(
                    f"remote channel {self._key}: replay buffer overflow "
                    f"({len(self._buffer)} unacked frames)")
            self._seq += 1
            seq = self._seq
            self._buffer.append((seq, ftype, payload))
        wire = _SEQ.pack(seq) + payload
        while True:
            with self._io_lock:
                gen = self._gen
                dead = self._conn_dead
                sock = self._sock
            if not dead:
                if FAULTS.enabled:
                    FAULTS.check("net.delay")  # !hang@MS: wire latency
                    if FAULTS.check("net.sever"):
                        # deterministic partition drill: kill the
                        # established socket under the send below
                        try:
                            sock.close()
                        except OSError:
                            pass
                try:
                    with self._io_lock:
                        if self._gen == gen and not self._conn_dead:
                            _send_frame(self._sock, ftype, wire)
                            return True
                    # the connection turned over underneath us: the
                    # winner's replay already carried this frame
                    return True
                except OSError as e:
                    _note_net_error("send", e, channel=self._key)
                    self._mark_dead(gen)
            self._raise_if_dead()
            # reconnect replays the buffer — including the frame staged
            # above — so a successful heal IS a successful put
            self._reconnect(gen)
            return True

    def poll(self) -> Optional[Any]:
        raise RuntimeError("sender side of a remote channel cannot poll")

    def size(self) -> int:
        return 0

    @property
    def unacked(self) -> int:
        with self._io_lock:
            return len(self._buffer)

    def close(self) -> None:
        self._closed.set()
        with self._io_lock:
            sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class _ReceiverChannel(Channel):
    """Consumer end: a local queue fed by the transport server; polling
    grants credits back upstream. Survives connections: ``last_seq``
    persists across reconnects so replayed frames dedup here."""

    def __init__(self, grant: Callable[[int], None]):
        self._q: queue.Queue = queue.Queue()
        self._grant = grant
        self._seq_lock = threading.Lock()
        self.last_seq = 0   # highest delivered sequence number
        self.deduped = 0    # replayed frames dropped as already-delivered

    def _deliver(self, seq: int, element: Any) -> bool:
        """Enqueue iff this sequence number was not already delivered
        (exactly-once across reconnects); returns whether it was."""
        with self._seq_lock:
            if seq <= self.last_seq:
                self.deduped += 1
                return False
            self.last_seq = seq
            self._q.put(element)
            return True

    def put(self, element: Any, timeout: Optional[float] = None) -> bool:
        raise RuntimeError("receiver side of a remote channel cannot put")

    def poll(self) -> Optional[Any]:
        try:
            e = self._q.get_nowait()
        except queue.Empty:
            return None
        self._grant(1)  # consumed one element: re-grant its credit
        return e

    def size(self) -> int:
        return self._q.qsize()


class TransportServer:
    """Per-host data-plane server (reference NettyServer +
    PartitionRequestServerHandler): accepts one connection per incoming
    edge, demuxes by channel key into receiver channels. Tracks the
    current attempt ``epoch`` (set by each deploy): a HELLO from an
    older epoch is a zombie attempt's data plane and is answered with an
    explicit FENCED frame instead of being served."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 initial_credits: int = INITIAL_CREDITS, epoch: int = 0):
        self._initial_credits = initial_credits
        self._channels: dict[str, _ReceiverChannel] = {}
        self._lock = threading.Lock()
        self._epoch = int(epoch)
        self.fenced_peers = 0
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(128)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               name="transport-accept",
                                               daemon=True)
        self._accept_thread.start()

    def set_epoch(self, epoch: int) -> None:
        """Adopt a new attempt epoch (each deploy): HELLOs from older
        epochs are fenced from here on."""
        with self._lock:
            self._epoch = max(self._epoch, int(epoch))

    def channel(self, channel_key: str) -> Channel:
        """The local Channel for an incoming edge; register before (or
        after) the remote sender connects — both orders work."""
        with self._lock:
            ch = self._channels.get(channel_key)
            if ch is None:
                ch = _ReceiverChannel(lambda n: None)  # grant wired on HELLO
                self._channels[channel_key] = ch
            return ch

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except OSError as e:
                if self._stop.is_set():
                    return
                # not the shutdown path: count it, surface it on the
                # exceptions endpoint, and keep accepting
                _note_net_error("accept", e)
                time.sleep(0.05)
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="transport-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_lock = threading.Lock()

        def reply(ftype: int, payload: bytes) -> None:
            with send_lock:
                _send_frame(conn, ftype, payload)

        def grant(n: int) -> None:
            try:
                reply(_TYPE_CREDIT, _LEN.pack(n))
            except OSError as e:
                # the task keeps draining its queue after the sender's
                # socket died; grants toward a dead connection are
                # expected during a reconnect window — count, don't spam
                _note_net_error("credit", e, channel=key)

        channel: Optional[_ReceiverChannel] = None
        key: Optional[str] = None
        try:
            frame = _recv_frame(conn)
            if frame is None or frame[0] != _TYPE_HELLO:
                return
            payload = frame[1]
            peer_epoch, _peer_acked = _HELLO.unpack(payload[:_HELLO.size])
            key = payload[_HELLO.size:].decode()
            with self._lock:
                epoch = self._epoch
            if peer_epoch < epoch:
                # a deposed attempt's data plane: explicit fence so the
                # zombie cancels instead of retrying into the void
                from ..metrics.device import DEVICE_STATS
                with self._lock:
                    self.fenced_peers += 1
                DEVICE_STATS.note_zombie_fenced("transport")
                _note_net_event("zombie-fenced", channel=key,
                                peer_epoch=peer_epoch, epoch=epoch)
                from ..metrics.tracing import TRACER
                (TRACER.span("net", "Fence")
                 .set_attribute("channel", key)
                 .set_attribute("peer_epoch", peer_epoch)
                 .set_attribute("epoch", epoch)
                 .finish())
                try:
                    reply(_TYPE_FENCED, _SEQ.pack(epoch))
                except OSError:
                    pass
                return
            with self._lock:
                channel = self._channels.get(key)
                if channel is None:
                    channel = _ReceiverChannel(grant)
                    self._channels[key] = channel
                else:
                    channel._grant = grant
            # resume point: a reconnecting sender prunes its replay
            # buffer up to what was already delivered
            reply(_TYPE_ACK, _SEQ.pack(channel.last_seq))
            grant(self._initial_credits)
            while not self._stop.is_set():
                frame = _recv_frame(conn)
                if frame is None:
                    return
                ftype, payload = frame
                if ftype not in (_TYPE_BATCH, _TYPE_CONTROL):
                    continue
                (seq,) = _SEQ.unpack(payload[:_SEQ.size])
                body = payload[_SEQ.size:]
                element = (deserialize_batch(body) if ftype == _TYPE_BATCH
                           else pickle.loads(body))
                if channel._deliver(seq, element):
                    reply(_TYPE_ACK, _SEQ.pack(seq))
                else:
                    from ..metrics.device import DEVICE_STATS
                    DEVICE_STATS.note_frame_deduped(key)
                    # ack the high-water mark anyway so the sender prunes
                    reply(_TYPE_ACK, _SEQ.pack(channel.last_seq))
        except OSError as e:
            if not self._stop.is_set():
                _note_net_error("receive", e, channel=key)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        """Idempotent: a host torn down by both a failover path and its
        own shutdown must release the data port exactly once (a process
        promoted on the same host re-binds immediately)."""
        if self._stop.is_set():
            return
        self._stop.set()
        # shutdown() first: it wakes the thread blocked in accept(), whose
        # in-flight syscall otherwise pins the socket in the kernel and
        # keeps the port bound after close() (EADDRINUSE for a process
        # promoted on the same host)
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        if threading.current_thread() is not self._accept_thread:
            self._accept_thread.join(timeout=1.0)
