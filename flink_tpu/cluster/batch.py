"""Bounded (batch) execution: blocking exchanges, stage-by-stage
scheduling, speculative retries.

The batch half of the reference's runtime-mode split
(flink-runtime scheduler/adaptivebatch/AdaptiveBatchScheduler.java:95,
SpeculativeScheduler.java:89; blocking exchange:
io/network/partition/SortMergeResultPartition.java:66), scoped to the
local/SPMD runner:

* every exchange is a BLOCKING partition (runtime/channels.py
  ReplayableChannel): a producer vertex runs to completion and
  materializes its entire output before any consumer task starts — the
  scheduling granularity of batch mode, and what makes retries cheap
  (inputs are re-readable, nothing upstream re-runs);
* vertices are scheduled in topological stages: a vertex starts once all
  of its input vertices finished;
* speculative execution (behind execution.batch.speculative.enabled):
  when a stage's median subtask has finished but a straggler keeps
  running past ``median * multiplier``, a SECOND attempt of that subtask
  deploys with fresh cursors over the same blocking inputs and shadow
  output partitions; whichever attempt finishes first wins — the
  winner's partitions become the stage output, the loser is cancelled.
  Attempts never share operator state, so the race is safe by
  construction.

Checkpointing is meaningless for bounded stage execution (the reference
disables it in batch mode); run_job_batch ignores any configured
interval. Streaming jobs keep the pipelined runner (cluster/local.py).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..core.config import CheckpointingOptions, Configuration, \
    ExecutionOptions
from ..graph.stream_graph import JobGraph
from ..runtime.channels import ReplayableChannel
from .local import LocalJob, _deploy_vertices

__all__ = ["run_job_batch"]


def _topo_stages(job_graph: JobGraph) -> list[str]:
    """Vertex ids in topological order (each is one scheduling stage)."""
    order, seen = [], set()

    def visit(vid: str) -> None:
        if vid in seen:
            return
        seen.add(vid)
        for e in job_graph.in_edges(vid):
            visit(e.source_vertex)
        order.append(vid)

    for vid in job_graph.vertices:
        visit(vid)
    return order


class _StageAttempt:
    """One speculative (shadow) attempt of a single subtask."""

    def __init__(self, task, shadow_channels: dict):
        self.task = task
        self.shadow_channels = shadow_channels  # ei -> [dst channels]


def run_job_batch(job_graph: JobGraph, config: Configuration,
                  timeout: Optional[float] = 120.0,
                  metrics_registry=None) -> LocalJob:
    """Run a bounded job stage by stage over blocking exchanges."""
    for e in job_graph.edges:
        if e.feedback:
            raise ValueError("iterations cannot run in batch mode "
                             "(a feedback edge is unbounded by nature)")
    job = LocalJob(job_graph, config)
    job.metrics_registry = metrics_registry
    # speculation audit trail: [{"task", "winner"}] per settled race
    job.speculative_attempts = []

    channels: dict[int, list[list[ReplayableChannel]]] = {}
    for ei, e in enumerate(job_graph.edges):
        src = job_graph.vertices[e.source_vertex]
        dst = job_graph.vertices[e.target_vertex]
        channels[ei] = [[ReplayableChannel() for _ in range(dst.parallelism)]
                        for _ in range(src.parallelism)]

    # checkpointing is a no-op for staged bounded execution: hide any
    # configured interval from the deployed tasks (barriers would wedge
    # against not-yet-started stages; reference batch mode likewise
    # disables checkpoints)
    cfg = config.clone()
    cfg.set(CheckpointingOptions.INTERVAL, 0.0)
    _deploy_vertices(job, job_graph, cfg, channels, None,
                     metrics_registry, set(job_graph.vertices))

    speculative = config.get(ExecutionOptions.SPECULATIVE)
    factor = config.get(ExecutionOptions.SPECULATIVE_FACTOR)
    deadline = None if timeout is None else time.time() + timeout

    for vid in _topo_stages(job_graph):
        vertex = job_graph.vertices[vid]
        task_ids = [f"{vid}#{s}" for s in range(vertex.parallelism)]
        started_at: dict[str, float] = {}
        for tid in task_ids:
            now = time.time()
            job.tasks[tid].start()
            job._exec_set(tid, "RUNNING")
            # the attempt's clock starts at STAGE start, not deploy time
            # (all vertices deploy up front; scheduling is staged)
            attempts = job.executions.get(tid)
            if attempts:
                attempts[-1]["start"] = now
            started_at[tid] = now
        shadows: dict[str, _StageAttempt] = {}
        try:
            _await_stage(job, job_graph, cfg, vid, vertex, task_ids,
                         channels, started_at, shadows,
                         speculative, factor, deadline, metrics_registry)
        finally:
            for att in shadows.values():
                att.task.cancel()
        if job._failed:
            task_id, err = job._failed[0]
            job.cancel()
            raise RuntimeError(f"Task {task_id} failed: {err!r}") from err
    job._done.set()
    return job


def _await_stage(job, job_graph, config, vid, vertex, task_ids, channels,
                 started_at, shadows, speculative, factor, deadline,
                 metrics_registry) -> None:
    durations: dict[str, float] = {}
    pending = set(task_ids)
    while pending:
        if deadline is not None and time.time() > deadline:
            job.cancel()
            raise TimeoutError(f"batch stage {vertex.name} timed out")
        done_now = set()
        for tid in pending:
            main_done = tid in job._finished
            shadow = shadows.get(tid)
            shadow_done = (shadow is not None
                           and shadow.task.task_id in
                           shadow.task.reporter._finished)
            if main_done or shadow_done:
                if shadow is not None:
                    _settle_speculation(job, job_graph, tid, shadow,
                                        channels, winner_is_shadow=
                                        shadow_done and not main_done)
                    shadows.pop(tid, None)
                if shadow_done and not main_done:
                    # shadow completed the subtask: a failure of the
                    # (now-cancelled) original no longer fails the job —
                    # whichever attempt finishes first wins, either way
                    with job._lock:
                        job._failed = [(t, e) for t, e in job._failed
                                       if t != tid]
                durations[tid] = time.time() - started_at[tid]
                done_now.add(tid)
            elif shadow is not None and shadow.task.reporter._failed:
                # a failed shadow never wins; drop it and let the
                # original attempt decide the subtask's fate
                shadow.task.cancel()
                shadows.pop(tid, None)
        pending -= done_now
        # a failed ORIGINAL whose shadow is still racing does not fail
        # the job yet — the shadow may complete the subtask
        blocking_failures = [t for t, _e in job._failed
                             if t not in shadows]
        if blocking_failures:
            return
        if (speculative and pending and durations
                and len(durations) * 2 >= len(task_ids)
                and not _has_sink(vertex)):
            med = sorted(durations.values())[len(durations) // 2]
            for tid in list(pending):
                if tid in shadows:
                    continue
                if time.time() - started_at[tid] > max(med * factor, 0.05):
                    shadows[tid] = _spawn_shadow(job_graph, config, vid,
                                                 tid, channels,
                                                 metrics_registry)
        time.sleep(0.005)


def _has_sink(vertex) -> bool:
    """Vertices containing a sink are never speculated: shadow channels
    isolate inter-vertex partitions, but a sink's side effects (files,
    collect buffers, external systems) would run in BOTH attempts — the
    loser's writes cannot be unwound. The reference restricts speculation
    to sinks implementing SupportsConcurrentExecutionAttempts; ours
    declare no such contract, so all sinks are excluded."""
    return vertex.kind == "sink" or any(
        n.kind == "sink" for n in vertex.chained_nodes)


def _spawn_shadow(job_graph, config, vid, task_id, channels,
                  metrics_registry) -> _StageAttempt:
    """Deploy attempt #2 of one subtask: same blocking inputs re-read
    from the start (fresh cursors), outputs into shadow partitions."""
    sub = int(task_id.rsplit("#", 1)[1])
    shadow_job = LocalJob(job_graph, config)
    shadow_channels: dict[int, list] = {}
    chan_view: dict[int, list[list]] = {}
    for ei, e in enumerate(job_graph.edges):
        if e.source_vertex == vid:
            # shadow outputs: fresh partitions, adopted only on a win
            rows = []
            for s in range(len(channels[ei])):
                if s == sub:
                    fresh = [ReplayableChannel()
                             for _ in channels[ei][s]]
                    shadow_channels[ei] = fresh
                    rows.append(fresh)
                else:
                    rows.append(channels[ei][s])
            chan_view[ei] = rows
        elif e.target_vertex == vid:
            # shadow inputs: new cursors over the SAME materialized data
            chan_view[ei] = [
                [ch.clone_reader() if d == sub else ch
                 for d, ch in enumerate(row)]
                for row in channels[ei]]
        else:
            chan_view[ei] = channels[ei]
    # metrics_registry=None: the shadow must not share the original
    # attempt's TaskMetrics counters — both attempts incrementing the
    # same numRecords* would double-count the speculated subtask
    _deploy_vertices(shadow_job, job_graph, config, chan_view, None,
                     None, {vid})
    task = shadow_job.tasks[task_id]
    task.start()
    shadow_job._exec_set(task_id, "RUNNING")
    return _StageAttempt(task, shadow_channels)


def _settle_speculation(job, job_graph, task_id, attempt, channels,
                        winner_is_shadow: bool) -> None:
    """First finished attempt wins; the loser is cancelled. On a shadow
    win the shadow's partitions become the stage output (consumers have
    not started yet — blocking exchanges make the swap trivial)."""
    vid, sub = task_id.rsplit("#", 1)
    sub = int(sub)
    job.speculative_attempts.append(
        {"task": task_id,
         "winner": "speculative" if winner_is_shadow else "original"})
    if winner_is_shadow:
        job.tasks[task_id].cancel()
        for ei, fresh in attempt.shadow_channels.items():
            for d, ch in enumerate(channels[ei][sub]):
                ch.adopt_items(fresh[d])
        with job._lock:
            job._exec_set(task_id, "FINISHED")
            job._finished.add(task_id)
    else:
        attempt.task.cancel()
