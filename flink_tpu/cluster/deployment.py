"""Deployment drivers: provision and supervise SPMD worker processes.

Analog of the reference's active resource managers + dist launchers
(flink-kubernetes KubernetesResourceManagerDriver.java:72, flink-yarn
YarnResourceManagerDriver, flink-dist start-cluster.sh), re-thought for
the SPMD model: a "deployment" does not ship code to workers — it starts
the SAME program on N hosts with a host id and a rendezvous, and each
worker builds the identical JobGraph locally (cluster/distributed.py).
The driver's whole job is worker lifecycle:

* ``DeploymentDriver`` is the SPI (requestWorker / stopWorker /
  onWorkerTerminated of the reference driver, collapsed to the three
  calls the SPMD model needs);
* ``ProcessDeploymentDriver`` launches workers as local OS processes —
  the standalone/dev-cluster driver. Its ``command_template`` seam is
  where a remote launcher (ssh, a pod create) slots in: a Kubernetes
  driver is this class with the template swapped for pod creation and
  DNS-based rendezvous.
* ``SpmdDeployment`` orchestrates a full job: allocate ports, start N
  workers running one user script, supervise (a dead worker restarts up
  to ``max_worker_restarts`` times — the coordinator's heartbeat failover
  handles the JOB-side recovery; the driver only replaces the process),
  collect exit status, tear down.

Workers receive their identity through the environment
(FLINK_TPU_HOST_ID / N_HOSTS / COORDINATOR / DATA_PORTS), which
``run_deployed()`` reads — a user script is identical on every host:

    env = StreamExecutionEnvironment()
    ... build pipeline ...
    run_deployed(env.get_job_graph("job"), env.config)
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..core.config import Configuration

__all__ = ["DeploymentDriver", "ProcessDeploymentDriver", "SpmdDeployment",
           "run_deployed", "free_ports"]


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@dataclass
class WorkerSpec:
    """What a worker needs to join the job (reference
    TaskExecutorProcessSpec collapsed to rendezvous identity)."""

    host_id: int
    n_hosts: int
    script: str
    data_ports: dict[int, int]
    coordinator_port: int
    env_extra: dict = field(default_factory=dict)


class DeploymentDriver:
    """Worker lifecycle SPI (reference ResourceManagerDriver)."""

    def request_worker(self, spec: WorkerSpec) -> Any:
        """Start a worker; returns an opaque handle."""
        raise NotImplementedError

    def stop_worker(self, handle: Any) -> None:
        raise NotImplementedError

    def poll_terminated(self) -> list[tuple[Any, int]]:
        """(handle, exit_code) for workers that stopped since last poll."""
        raise NotImplementedError


class ProcessDeploymentDriver(DeploymentDriver):
    """Workers as local OS processes (standalone cluster driver). The
    ``command_template`` receives the python executable and script and
    may wrap them (e.g. ["ssh", "{host}", ...] for a remote standalone
    setup); element placeholders: {python} {script}."""

    def __init__(self, command_template: Optional[list[str]] = None,
                 stdout_dir: Optional[str] = None):
        self._template = command_template or ["{python}", "{script}"]
        self._stdout_dir = stdout_dir
        self._procs: list[tuple[subprocess.Popen, Any]] = []

    def request_worker(self, spec: WorkerSpec) -> subprocess.Popen:
        env = dict(os.environ)
        env.update({
            "FLINK_TPU_HOST_ID": str(spec.host_id),
            "FLINK_TPU_N_HOSTS": str(spec.n_hosts),
            "FLINK_TPU_DATA_PORTS": json.dumps(spec.data_ports),
            "FLINK_TPU_COORDINATOR": f"127.0.0.1:{spec.coordinator_port}",
        })
        env.update({k: str(v) for k, v in spec.env_extra.items()})
        cmd = [part.format(python=sys.executable, script=spec.script)
               for part in self._template]
        if self._stdout_dir:
            os.makedirs(self._stdout_dir, exist_ok=True)
            with open(os.path.join(self._stdout_dir,
                                   f"worker-{spec.host_id}.log"),
                      "ab") as out:
                # the child inherits the fd; close our copy immediately
                proc = subprocess.Popen(cmd, env=env, stdout=out,
                                        stderr=subprocess.STDOUT)
        else:
            proc = subprocess.Popen(cmd, env=env,
                                    stdout=subprocess.DEVNULL,
                                    stderr=subprocess.STDOUT)
        self._procs.append((proc, spec))
        return proc

    def stop_worker(self, handle: subprocess.Popen) -> None:
        if handle.poll() is None:
            handle.terminate()
            try:
                handle.wait(10)
            except subprocess.TimeoutExpired:
                handle.kill()

    def poll_terminated(self) -> list[tuple[subprocess.Popen, int]]:
        done = []
        for proc, _spec in self._procs:
            rc = proc.poll()
            if rc is not None:
                done.append((proc, rc))
        self._procs = [(p, s) for p, s in self._procs if p.poll() is None]
        return done

    def spec_for(self, handle: subprocess.Popen) -> Optional[WorkerSpec]:
        for p, s in self._procs:
            if p is handle:
                return s
        return None


class SpmdDeployment:
    """Deploy one SPMD script across N workers and supervise it."""

    def __init__(self, script: str, n_hosts: int,
                 driver: Optional[DeploymentDriver] = None,
                 max_worker_restarts: int = 2,
                 env_extra: Optional[dict] = None):
        self.script = script
        self.n_hosts = int(n_hosts)
        self.driver = driver or ProcessDeploymentDriver()
        self.max_restarts = int(max_worker_restarts)
        self._env_extra = env_extra or {}
        self._handles: dict[int, Any] = {}
        self._specs: dict[int, WorkerSpec] = {}
        self._restarts: dict[int, int] = {}
        self.exit_codes: dict[int, int] = {}

    def start(self) -> None:
        ports = free_ports(self.n_hosts + 1)
        data_ports = {i: ports[i] for i in range(self.n_hosts)}
        coord_port = ports[-1]
        for i in range(self.n_hosts):
            spec = WorkerSpec(i, self.n_hosts, self.script, data_ports,
                              coord_port, dict(self._env_extra))
            self._specs[i] = spec
            self._handles[i] = self.driver.request_worker(spec)

    def wait(self, timeout: float = 600.0) -> dict[int, int]:
        """Supervise until every worker exits (dead workers restart up to
        the limit; a worker that exits 0 is finished). Returns final exit
        codes by host id. Exit detection goes through the driver's
        poll_terminated SPI, so non-process drivers (pods) supervise the
        same way."""
        deadline = time.time() + timeout
        live: dict[int, Any] = dict(self._handles)
        by_handle = {id(h): hid for hid, h in live.items()}
        while live and time.time() < deadline:
            for handle, rc in self.driver.poll_terminated():
                hid = by_handle.pop(id(handle), None)
                if hid is None or hid not in live:
                    continue
                del live[hid]
                if rc == 0:
                    self.exit_codes[hid] = 0
                    continue
                n = self._restarts.get(hid, 0)
                if n < self.max_restarts:
                    # replace the worker; the surviving coordinator's
                    # heartbeat failover re-deploys the job state side
                    self._restarts[hid] = n + 1
                    h = self.driver.request_worker(self._specs[hid])
                    live[hid] = self._handles[hid] = h
                    by_handle[id(h)] = hid
                else:
                    self.exit_codes[hid] = rc
            time.sleep(0.1)
        for hid, handle in live.items():
            self.driver.stop_worker(handle)
            self.exit_codes.setdefault(hid, -1)
        return dict(self.exit_codes)

    def stop(self) -> None:
        for handle in self._handles.values():
            self.driver.stop_worker(handle)


def run_deployed(jg, config: Optional[Configuration] = None,
                 timeout: float = 300.0):
    """Worker-side entry: run ``jg`` as this deployment's slice, taking
    identity + rendezvous from the environment injected by the driver.
    The same script runs unchanged on every host (SPMD)."""
    from .distributed import run_distributed

    host_id = int(os.environ["FLINK_TPU_HOST_ID"])
    n_hosts = int(os.environ["FLINK_TPU_N_HOSTS"])
    data_ports = {int(k): int(v) for k, v in
                  json.loads(os.environ["FLINK_TPU_DATA_PORTS"]).items()}
    coord = os.environ["FLINK_TPU_COORDINATOR"]
    coord_port = int(coord.rsplit(":", 1)[1])
    peers = {i: ("127.0.0.1", p) for i, p in data_ports.items()}
    from .distributed import DistributedHost

    host = DistributedHost(jg, config or Configuration(), host_id, n_hosts,
                           coordinator_addr=None if host_id == 0 else coord,
                           data_port=data_ports[host_id],
                           coordinator_port=(coord_port if host_id == 0
                                             else 0))
    try:
        return host.run(peers, timeout=timeout)
    finally:
        host.close()
