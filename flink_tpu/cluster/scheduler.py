"""Job supervisor: deploy, monitor, checkpoint, restart-on-failure.

The scheduler/JobMaster analog for local execution
(flink-runtime scheduler/DefaultScheduler.java:83 onTaskFailed:263 +
jobmaster/JobMaster + §3.5 failure->region-restart flow): a failed execution
cancels the attempt, consults the restart strategy, rebuilds the deployment,
and restores every task from the latest completed checkpoint (reference
restoreLatestCheckpointedStateToAll:1704). A fully pipelined local job is one
failover region, so region restart == attempt restart, exactly as the
reference behaves for all-pipelined graphs.

Also the seam for elastic rescaling: ``rescale(new_parallelism)`` takes a
savepoint, rewrites vertex parallelism, and redeploys with key-group
re-sharding (AdaptiveScheduler's Restarting->Executing transition).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..core.config import CheckpointingOptions, Configuration
from ..checkpoint.coordinator import CheckpointCoordinator, build_restore_map
from ..checkpoint.storage import CompletedCheckpoint
from ..graph.stream_graph import JobGraph
from .failover import restart_strategy_from_config
from .local import LocalJob, deploy_local

__all__ = ["JobSupervisor"]


class JobSupervisor:
    """Runs a JobGraph to completion across failures."""

    def __init__(self, job_graph: JobGraph, config: Configuration,
                 metrics_registry=None):
        self.job_graph = job_graph
        self.config = config
        self.metrics_registry = metrics_registry
        self.restart_strategy = restart_strategy_from_config(config)
        self.attempt = 0
        # external cancel intent (dispatcher/HA): checked right after each
        # deploy, so a cancel landing in the deploy window — before
        # current_job exists to cancel — still stops the job
        self.cancel_requested = False
        self.current_job: Optional[LocalJob] = None
        self.coordinator: Optional[CheckpointCoordinator] = None
        self._detector = None  # per-attempt TaskStallDetector
        self._latest: Optional[CompletedCheckpoint] = None
        self._rescaling = False  # guards the cancel->redeploy swap window
        self.failures: list[tuple[int, str]] = []  # (attempt, error message)
        # one bounded history shared across every attempt's LocalJob (the
        # FailureHandlingResult analog): task failures append from the
        # reporter, restart decisions append here
        from collections import deque
        self.failure_history: deque = deque(maxlen=64)

    # -- lifecycle ---------------------------------------------------------
    def _deploy(self, restore: Optional[CompletedCheckpoint]) -> LocalJob:
        restored_state = (build_restore_map(restore, self.job_graph)
                          if restore else None)
        job = deploy_local(self.job_graph, self.config,
                           restored_state=restored_state,
                           metrics_registry=self.metrics_registry)
        job.failure_history = self.failure_history  # survives redeploys
        from ..metrics.tracing import TRACER
        coordinator = CheckpointCoordinator(
            job, self.config, tracer=TRACER if TRACER.enabled else None)
        if self._latest is not None:
            # keep checkpoint ids monotonically increasing across restarts
            coordinator._next_id = self._latest.checkpoint_id + 1
        coordinator.start_periodic()
        # task-progress supervision (runtime/watchdog.py): a subtask whose
        # epoch stalls with queued input fails with StallError, which
        # lands in current_failures() and rides the SAME region-restart /
        # restart-from-checkpoint flow below as any other task failure
        from ..core.config import WatchdogOptions
        from ..runtime.watchdog import TaskStallDetector
        if self._detector is not None:
            self._detector.stop()
        self._detector = TaskStallDetector(
            job, float(self.config.get(
                WatchdogOptions.TASK_STALL_TIMEOUT))).start()
        self.current_job = job
        self.coordinator = coordinator
        return job

    def _stop_supervision(self) -> None:
        if self._detector is not None:
            self._detector.stop()
        self.coordinator.stop()

    def run(self, timeout: Optional[float] = 300.0,
            initial_restore: Optional[CompletedCheckpoint] = None
            ) -> LocalJob:
        """Blocking execute-with-recovery; raises when the restart strategy
        gives up or the deadline passes. ``initial_restore`` starts the
        first attempt from a savepoint/checkpoint (reference 'run -s')."""
        deadline = None if timeout is None else time.time() + timeout
        restore = initial_restore
        if initial_restore is not None:
            self._latest = initial_restore
        while True:
            if self.cancel_requested:
                return self.current_job
            self.attempt += 1
            job = self._deploy(restore)
            if self.cancel_requested:
                self._stop_supervision()
                job.cancel()
                return job
            job.start()
            try:
                while True:
                    if deadline is not None and time.time() >= deadline:
                        raise TimeoutError(
                            f"job did not finish within {timeout}s")
                    remaining = (None if deadline is None
                                 else max(deadline - time.time(), 0.1))
                    if not job.wait_event(remaining):
                        job.cancel()
                        raise TimeoutError(
                            f"Job did not finish within {timeout}s")
                    if job.current_failures() and \
                            self._try_region_restart(job):
                        continue
                    job.wait(0.1)  # raises for non-region-recoverable
                    if self.current_job is job and not self._rescaling:
                        break
                    if self.current_job is not job:
                        # rescale() swapped the deployment underneath us:
                        # the old job's cancel completed normally — keep
                        # supervising the new one (its coordinator runs on)
                        job = self.current_job
                    else:
                        # rescale() cancelled this job but hasn't installed
                        # the replacement yet — wait for the swap
                        time.sleep(0.05)
                self._stop_supervision()
                return job
            except TimeoutError:
                self._stop_supervision()
                raise
            except RuntimeError as e:
                # task failure: snapshot the latest VERIFIED checkpoint,
                # consult the restart strategy, redeploy (reference
                # maybeRestartTasks). Corrupt artifacts are quarantined and
                # skipped; CorruptArtifactError propagates (job failure)
                # only when NO retained checkpoint verifies.
                self._stop_supervision()
                latest = self.coordinator.latest_verified_checkpoint()
                if latest is not None:
                    self._latest = latest
                self.failures.append((self.attempt, str(e)))
                self.restart_strategy.notify_failure()
                if not self.restart_strategy.can_restart():
                    self.failure_history.append({
                        "timestamp": time.time(), "attempt": self.attempt,
                        "kind": "terminal-failure", "error": str(e)})
                    raise RuntimeError(
                        f"Job failed terminally after {self.attempt} "
                        f"attempts: {e}") from e
                self.failure_history.append({
                    "timestamp": time.time(), "attempt": self.attempt,
                    "kind": "restart", "error": str(e),
                    "restored_checkpoint": (self._latest.checkpoint_id
                                            if self._latest else None)})
                from ..metrics.tracing import TRACER, dump_flight_recorder
                dump_flight_recorder(
                    "job-restart", job=self.job_graph.name,
                    attempt=self.attempt, error=str(e))
                restart_sb = (TRACER.span("restart", "JobRestart")
                              .set_attribute("job", self.job_graph.name)
                              .set_attribute("attempt", self.attempt)
                              .set_attribute("restored",
                                             self._latest.checkpoint_id
                                             if self._latest else None))
                job.cancel()
                time.sleep(self.restart_strategy.backoff_seconds())
                restore = self._latest
                restart_sb.finish()

    def _try_region_restart(self, job: LocalJob) -> bool:
        """Pipelined-region failover (reference
        RestartPipelinedRegionFailoverStrategy.java:110): when the failed
        tasks' regions do not span the whole graph, restart ONLY those
        regions from the latest checkpoint — the other regions keep
        running, their state untouched. Returns True when handled."""
        from .local import restart_region
        from .regions import affected_vertices, compute_regions

        failed = job.current_failures()
        if not failed:
            return False
        regions = compute_regions(self.job_graph)
        if len(regions) <= 1:
            return False
        vids = affected_vertices(regions, [tid for tid, _e in failed])
        if vids >= set(self.job_graph.vertices):
            return False
        self.restart_strategy.notify_failure()
        if not self.restart_strategy.can_restart():
            return False
        self.failures.append((self.attempt, str(failed[0][1])))
        self.failure_history.append({
            "timestamp": time.time(), "attempt": self.attempt,
            "kind": "region-restart", "error": str(failed[0][1]),
            "vertices": sorted(vids)})
        latest = self.coordinator.latest_verified_checkpoint()
        restored = {}
        if latest is not None:
            self._latest = latest
            restored = {tid: snap for tid, snap in build_restore_map(
                latest, self.job_graph).items()
                if tid.rsplit("#", 1)[0] in vids}
        self.coordinator.pause()
        try:
            time.sleep(self.restart_strategy.backoff_seconds())
            restart_region(job, self.job_graph, self.config, vids,
                           restored)
        finally:
            self.coordinator.resume()
        return True

    # -- elastic rescaling -------------------------------------------------
    def rescale(self, vertex_parallelism: dict[str, int],
                timeout: float = 60.0) -> None:
        """Stop-with-savepoint, rewrite parallelism, redeploy restoring from
        the savepoint (AdaptiveScheduler Executing->Restarting->Executing).
        Call from a thread other than the job's tasks."""
        sp = self.coordinator.trigger_savepoint(timeout)
        self._rescaling = True
        try:
            self.coordinator.stop()
            self.current_job.cancel()
            for vid, par in vertex_parallelism.items():
                self.job_graph.vertices[vid].parallelism = par
            self._latest = sp
            if self.cancel_requested:
                # a cancel landed mid-rescale: redeploying would resurrect
                # the job the caller just stopped
                return
            job = self._deploy(sp)
            if self.cancel_requested:
                self.coordinator.stop()
                job.cancel()
                return
            job.start()
        finally:
            self._rescaling = False
