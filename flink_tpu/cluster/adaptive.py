"""Adaptive scheduler: explicit state machine + reactive rescaling.

Reference: scheduler/adaptive/AdaptiveScheduler.java:167 with one class per
state (Created, WaitingForResources, Executing, Restarting, Finished,
Failing) and REACTIVE mode — the job's parallelism tracks the resources
that are actually available: workers joining scale the job up, workers
leaving scale it down, always through stop-with-savepoint -> redeploy so
keyed state re-shards by key-group range.

TPU-native shape: "resources" are the SlotManager's usable slot count
(cluster/resource_manager.py — registrations minus blocklist). Desired
parallelism for every scalable vertex = min(total_slots, vertex
max_parallelism), floored at min_parallelism. The state machine drives the
same JobSupervisor rescale primitive the operator would call by hand, and
every transition lands in ``history`` for observability/tests (reference
exposes the same through the REST jobs/:id/status).

States and transitions:

    CREATED -> WAITING_FOR_RESOURCES      start()
    WAITING_FOR_RESOURCES -> EXECUTING    enough slots (>= min_parallelism)
    EXECUTING -> RESTARTING               resource change => new parallelism
    RESTARTING -> EXECUTING               redeploy from savepoint done
    EXECUTING -> FINISHED | FAILED        job terminal
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..core.config import Configuration
from .resource_manager import SlotManager
from .scheduler import JobSupervisor

__all__ = ["AdaptiveScheduler"]

_SCALABLE_KINDS = ("one_input",)   # sources/sinks keep their parallelism


class AdaptiveScheduler:
    """Runs a JobGraph with parallelism tracking available slots."""

    STATES = ("CREATED", "WAITING_FOR_RESOURCES", "EXECUTING", "RESTARTING",
              "FINISHED", "FAILED")

    def __init__(self, job_graph, config: Configuration,
                 slots: Optional[SlotManager] = None,
                 min_parallelism: int = 1,
                 resource_stabilization_s: float = 0.05,
                 scale_check_interval_s: float = 0.05):
        self.job_graph = job_graph
        self.config = config
        self.slots = slots or SlotManager()
        self.min_parallelism = min_parallelism
        self.stabilization_s = resource_stabilization_s
        self.check_interval_s = scale_check_interval_s
        self.state = "CREATED"
        self.history: list[tuple[str, str]] = []   # (state, reason)
        self.supervisor: Optional[JobSupervisor] = None
        self.current_parallelism = 0
        self.rescales = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._run_error: Optional[BaseException] = None
        self._terminal = threading.Event()

    # -- state machine -----------------------------------------------------
    def _transition(self, to: str, reason: str) -> None:
        assert to in self.STATES, to
        self.state = to
        self.history.append((to, reason))
        if to in ("FINISHED", "FAILED"):
            self._terminal.set()

    def _desired_parallelism(self) -> int:
        total = self.slots.total_slots()
        maxp = min((v.max_parallelism
                    for v in self.job_graph.vertices.values()),
                   default=128)
        return max(0, min(total, maxp))

    def _scalable_vertices(self) -> list[str]:
        return [vid for vid, v in self.job_graph.vertices.items()
                if v.kind in _SCALABLE_KINDS]

    def _apply_parallelism(self, par: int) -> None:
        for vid in self._scalable_vertices():
            self.job_graph.vertices[vid].parallelism = par
        self.current_parallelism = par

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Begin scheduling; returns immediately (drive() runs on its own
        thread, reference's main-thread executor collapsed onto it)."""
        self._transition("WAITING_FOR_RESOURCES", "started")
        self._thread = threading.Thread(target=self._drive, daemon=True,
                                        name="adaptive-scheduler")
        self._thread.start()

    def wait_terminal(self, timeout: float = 120.0) -> str:
        if not self._terminal.wait(timeout):
            raise TimeoutError(f"not terminal within {timeout}s "
                               f"(state={self.state})")
        if self.state == "FAILED" and self._run_error is not None:
            raise RuntimeError("adaptive job failed") from self._run_error
        return self.state

    def _cancel_supervised(self) -> None:
        """Stop the supervised job for good: the cancel_requested flag
        also covers the rescale/redeploy window where current_job is not
        yet the attempt that would otherwise survive."""
        sup = self.supervisor
        if sup is None:
            return
        sup.cancel_requested = True
        if sup.coordinator is not None:
            sup.coordinator.stop()
        if sup.current_job is not None:
            sup.current_job.cancel()

    def stop(self) -> None:
        self._stop.set()
        self._cancel_supervised()
        if self._thread is not None:
            self._thread.join(5.0)

    # -- driver ------------------------------------------------------------
    def _wait_for_resources(self) -> Optional[int]:
        """Block until >= min_parallelism slots exist AND the slot count
        has been stable for the stabilization window (reference
        WaitingForResources stabilization timeout)."""
        stable_since, last = None, -1
        while not self._stop.is_set():
            par = self._desired_parallelism()
            if par >= self.min_parallelism:
                if par != last:
                    stable_since, last = time.time(), par
                elif time.time() - stable_since >= self.stabilization_s:
                    return par
            else:
                stable_since, last = None, -1
            time.sleep(self.check_interval_s / 2)
        return None

    def _drive(self) -> None:
        par = self._wait_for_resources()
        if par is None:
            return
        self._apply_parallelism(par)
        self.supervisor = JobSupervisor(self.job_graph, self.config)
        self._transition("EXECUTING", f"deployed at parallelism {par}")

        result: dict = {}

        def run_job():
            try:
                result["job"] = self.supervisor.run(timeout=None)
            except BaseException as e:  # noqa: BLE001 - drives FAILED state
                result["error"] = e

        runner = threading.Thread(target=run_job, daemon=True,
                                  name="adaptive-job")
        runner.start()

        while not self._stop.is_set():
            runner.join(self.check_interval_s)
            if not runner.is_alive():
                break
            desired = self._desired_parallelism()
            if (desired != self.current_parallelism
                    and desired >= self.min_parallelism
                    and self.state == "EXECUTING"):
                # stabilization: don't thrash on a worker mid-restart
                time.sleep(self.stabilization_s)
                settled = self._desired_parallelism()
                if settled == self.current_parallelism \
                        or settled < self.min_parallelism:
                    continue
                self._transition(
                    "RESTARTING",
                    f"resources changed: {self.current_parallelism} "
                    f"-> {settled}")
                try:
                    self.supervisor.rescale(
                        {vid: settled for vid in self._scalable_vertices()})
                    self.current_parallelism = settled
                    self.rescales += 1
                    self._transition(
                        "EXECUTING", f"rescaled to parallelism {settled}")
                except Exception as e:  # noqa: BLE001 - drives FAILED state
                    # the rescale may have raced a NATURAL completion (the
                    # savepoint found finished tasks): only that counts as
                    # fine — a job cancelled mid-rescale must not read as
                    # FINISHED, and a still-running job must not keep
                    # producing after we report FAILED
                    runner.join(2.0)
                    if self._stop.is_set():
                        return  # user stop mid-rescale is not a failure
                    job = self.supervisor.current_job
                    completed = (not runner.is_alive() and job is not None
                                 and not job.failed and not job.cancelled
                                 and len(job._finished) == len(job.tasks))
                    if completed:
                        break
                    self._run_error = e
                    self._cancel_supervised()
                    self._transition("FAILED", f"rescale failed: {e}")
                    return
        runner.join(5.0)
        if self._stop.is_set():
            # stopped externally: the cancelled attempt's clean unwind must
            # not read as a successful FINISHED — state stays as-is
            return
        if "error" in result:
            self._run_error = result["error"]
            self._transition("FAILED", str(result["error"]))
        else:
            self._transition("FINISHED", "job completed")
