"""Multi-tenant isolation: per-job quotas, bulkheads, overload defense.

The process-global device-time scheduler that turns the device-time
ledger's attribution signal (metrics/profiler.py: every dispatch is
charged to a ``(job, operator, site, shape)`` key) into enforcement
(docs/ROBUSTNESS.md, 'Multi-tenant isolation'):

* **Quotas** — micro-batch dispatch admission runs deficit-round-robin
  over ``isolation.job-weights``: each source polls ``try_admit`` before
  reading its next batch; under contention a job spends one credit per
  batch and credits replenish in proportion to weight only when every
  active demanding job has exhausted its deficit. All decisions are
  count-based (a global admission-attempt counter, never wall-clock and
  never random), so the admission sequence is a pure function of the
  arrival order — deterministic per TPU501.

* **Bulkheads** — each job gets its own admission bound
  (``isolation.queue-bound``), its own failure domain (failure history,
  flight dumps, watchdog/faults events, and REST exception surfaces are
  job-scoped via the thread-local dispatch context), and its own
  circuit breaker: ``isolation.breaker-failures`` consecutive failures
  open it, a count-based cooldown (``isolation.breaker-cooldown``
  admission attempts) later it half-opens and admits one probe.

* **Shedding** — sustained overload (gate wait past
  ``isolation.shed-after`` or an open breaker) sheds the batch to the
  existing dead-letter side output with a typed ``OverloadShedError``:
  never a silent drop (the records land in the quarantine the operator
  already exposes), never a blocked healthy tenant (the shed is the
  backpressure relief valve — see the shed-vs-backpressure table in
  docs/ROBUSTNESS.md).

Disabled (the default) every gate check is one attribute read.
``deploy_local`` / ``DistributedHost.deploy`` configure the singleton
from the job Configuration, like FAULTS / WATCHDOG / DEVICE_LEDGER.
"""

from __future__ import annotations

import threading
from typing import Optional

__all__ = ["OverloadShedError", "JobBulkhead", "IsolationScheduler",
           "ISOLATION"]

#: A job counts as "active" (competing for credit) while its last
#: admission attempt is within this many global attempts of now; an
#: idle, finished, or wedged-and-not-polling job ages out and stops
#: holding back replenishment for everyone else. Count-based, not
#: wall-clock, so schedules replay deterministically.
ACTIVE_WINDOW = 512


class OverloadShedError(RuntimeError):
    """A micro-batch was shed by its job's bulkhead instead of
    dispatched. ``reason`` is one of ``breaker-open`` (circuit breaker
    tripped by consecutive failures), ``gate-timeout`` (admission wait
    exceeded ``isolation.shed-after``), ``bulkhead-full`` (more waiters
    than ``isolation.queue-bound``), or ``injected`` (a ``sched.shed``
    chaos rule tripped). The records are NOT lost: the caller emits the
    batch on the dead-letter side output before surfacing this."""

    def __init__(self, job: str, reason: str, waited_s: float = 0.0):
        super().__init__(
            f"job {job!r} shed a micro-batch ({reason}, waited "
            f"{waited_s * 1e3:.0f}ms)")
        self.job = job
        self.reason = reason
        self.waited_s = waited_s


class JobBulkhead:
    """Per-job scheduler record. Mutated only under the owning
    scheduler's lock — it carries no lock of its own."""

    __slots__ = ("name", "weight", "deficit", "last_attempt", "waiting",
                 "admitted_total", "rejected_total", "shed_batches_total",
                 "shed_records_total", "bulkhead_trips_total",
                 "consecutive_failures", "failures_total",
                 "breaker_open", "breaker_opened_at",
                 "breaker_opens_total", "probe_inflight")

    def __init__(self, name: str, weight: float):
        self.name = name
        self.weight = weight
        self.deficit = weight          # one replenish-free burst at start
        self.last_attempt = 0
        self.waiting = 0               # batches at the gate right now
        self.admitted_total = 0
        self.rejected_total = 0
        self.shed_batches_total = 0
        self.shed_records_total = 0
        self.bulkhead_trips_total = 0
        self.consecutive_failures = 0
        self.failures_total = 0
        self.breaker_open = False
        self.breaker_opened_at = 0     # global attempt count at open
        self.breaker_opens_total = 0
        self.probe_inflight = False    # half-open probe outstanding

    def breaker_state(self) -> str:
        if not self.breaker_open:
            return "closed"
        return "half-open" if self.probe_inflight else "open"


class IsolationScheduler:
    """Process-wide per-job admission scheduler + bulkhead registry.

    Admission is caller-driven: each source task polls ``try_admit``
    before reading a micro-batch and backs off ~1ms (counted as
    backpressure) on ``"retry"``, so there is no scheduler thread and
    no queue to drain — the bounded "queue" is the set of polling
    callers, and ``waiting`` tracks its depth per job.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self._jobs: dict[str, JobBulkhead] = {}
        self._weights: dict[str, float] = {}
        self._quantum = 8.0
        self._shed_after = 5.0
        self._breaker_failures = 8
        self._breaker_cooldown = 64
        self._queue_bound = 128
        self._attempts = 0             # global admission-attempt counter
        self._fingerprint: Optional[tuple] = None

    # -- configuration ---------------------------------------------------
    def configure(self, config) -> None:
        """Adopt ``isolation.*`` keys from a job Configuration.
        Idempotent on an unchanged fingerprint so failover redeploys of
        the SAME job keep their counters and breaker state — a tripped
        breaker must not silently close on every restart attempt."""
        from ..core.config import IsolationOptions

        enabled = bool(config.get(IsolationOptions.ENABLED))
        weights = str(config.get(IsolationOptions.JOB_WEIGHTS) or "")
        quantum = float(config.get(IsolationOptions.QUANTUM))
        shed_after = float(config.get(IsolationOptions.SHED_AFTER))
        breaker_failures = int(config.get(
            IsolationOptions.BREAKER_FAILURES))
        breaker_cooldown = int(config.get(
            IsolationOptions.BREAKER_COOLDOWN))
        queue_bound = int(config.get(IsolationOptions.QUEUE_BOUND))
        fingerprint = (enabled, weights, quantum, shed_after,
                       breaker_failures, breaker_cooldown, queue_bound)
        with self._lock:
            if fingerprint == self._fingerprint:
                return
            self.enabled = enabled
            self._weights = self._parse_weights(weights)
            self._quantum = max(1.0, quantum)
            self._shed_after = max(0.0, shed_after)
            self._breaker_failures = max(1, breaker_failures)
            self._breaker_cooldown = max(1, breaker_cooldown)
            self._queue_bound = max(1, queue_bound)
            self._jobs.clear()
            self._attempts = 0
            self._fingerprint = fingerprint

    @staticmethod
    def _parse_weights(spec: str) -> dict[str, float]:
        out: dict[str, float] = {}
        for entry in (spec or "").split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if "=" not in entry:
                raise ValueError(
                    f"isolation.job-weights entry {entry!r}: expected "
                    f"'job=weight'")
            name, _, w = entry.partition("=")
            if not name.strip():
                raise ValueError(
                    f"isolation.job-weights entry {entry!r}: empty "
                    f"job name")
            try:
                weight = float(w)
            except ValueError:
                raise ValueError(
                    f"isolation.job-weights entry {entry!r}: weight "
                    f"{w!r} is not a number") from None
            if weight <= 0.0:
                raise ValueError(
                    f"isolation.job-weights entry {entry!r}: weight "
                    f"must be > 0")
            out[name.strip()] = weight
        return out

    def reset(self) -> None:
        """Disarm and clear all per-job state (test isolation)."""
        with self._lock:
            self.enabled = False
            self._jobs.clear()
            self._weights = {}
            self._attempts = 0
            self._fingerprint = None

    def register_job(self, name: str) -> None:
        """Create the job's bulkhead (idempotent — a failover redeploy
        keeps the existing record and its breaker state)."""
        if not name:
            return
        with self._lock:
            if name not in self._jobs:
                self._jobs[name] = JobBulkhead(
                    name, self._weights.get(name, 1.0))

    def _job_locked(self, name: str) -> JobBulkhead:
        b = self._jobs.get(name)
        if b is None:
            b = self._jobs[name] = JobBulkhead(
                name, self._weights.get(name, 1.0))
        return b

    # -- admission (the tentpole chokepoint) -----------------------------
    def note_waiting(self, job: str, delta: int) -> None:
        """Track gate depth: +1 when a caller starts polling for one
        micro-batch, -1 when it admits or sheds."""
        if not self.enabled:
            return
        with self._lock:
            b = self._job_locked(job)
            b.waiting = max(0, b.waiting + delta)

    def try_admit(self, job: str, waited_s: float = 0.0) -> str:
        """One admission attempt for the next micro-batch of ``job``.

        Returns ``"admit"`` (dispatch it), ``"retry"`` (no credit under
        contention — back off ~1ms, keep the mailbox live, poll again
        with the accumulated wait), or a shed verdict:
        ``"shed:breaker-open"`` / ``"shed:gate-timeout"`` /
        ``"shed:bulkhead-full"`` — emit the batch to the dead-letter
        side output and surface ``OverloadShedError``."""
        if not self.enabled:
            return "admit"
        with self._lock:
            b = self._job_locked(job)
            self._attempts += 1
            b.last_attempt = self._attempts
            # breaker first: an open breaker sheds regardless of credit
            if b.breaker_open:
                cooled = (self._attempts - b.breaker_opened_at
                          >= self._breaker_cooldown)
                if cooled and not b.probe_inflight:
                    # half-open: admit exactly one probe batch; its
                    # note_success/note_failure decides the transition
                    b.probe_inflight = True
                    b.admitted_total += 1
                    return "admit"
                b.rejected_total += 1
                return "shed:breaker-open"
            # bulkhead bound: too many batches already at this gate
            if b.waiting > self._queue_bound:
                b.rejected_total += 1
                b.bulkhead_trips_total += 1
                return "shed:bulkhead-full"
            # age-based shed: sustained overload, relieve the queue
            if self._shed_after > 0.0 and waited_s >= self._shed_after:
                b.rejected_total += 1
                return "shed:gate-timeout"
            # deficit-round-robin over the active set
            active = [j for j in self._jobs.values()
                      if self._attempts - j.last_attempt < ACTIVE_WINDOW]
            if len(active) <= 1:
                # solo tenant: admission is free — quotas only shape
                # CONTENTION, a lone job must run at full speed
                b.admitted_total += 1
                return "admit"
            if b.deficit >= 1.0:
                b.deficit -= 1.0
                b.admitted_total += 1
                return "admit"
            if any(j.deficit >= 1.0 for j in active if j is not b):
                # a competitor holds credit — yield the slot to it
                b.rejected_total += 1
                return "retry"
            # every active job is exhausted: replenish the whole round
            # in weight proportion (sorted for a stable, seed-free order)
            for j in sorted(active, key=lambda x: x.name):
                j.deficit = min(j.deficit + j.weight * self._quantum,
                                2.0 * j.weight * self._quantum)
            b.deficit -= 1.0
            b.admitted_total += 1
            return "admit"

    def note_shed(self, job: str, records: int,
                  reason: str = "gate-timeout") -> None:
        """Account one shed batch (``records`` rows quarantined to the
        dead-letter output) against the job's bulkhead."""
        if not self.enabled:
            return
        with self._lock:
            b = self._job_locked(job)
            b.shed_batches_total += 1
            b.shed_records_total += max(0, int(records))
            if reason == "breaker-open":
                b.bulkhead_trips_total += 1

    # -- circuit breaker -------------------------------------------------
    def note_failure(self, job: str) -> None:
        """One task/segment failure in ``job``'s domain (region restart,
        poison quarantine, retries-exhausted DeviceSegmentError). Trips
        the breaker open after ``isolation.breaker-failures``
        consecutive failures; a half-open probe's failure re-opens."""
        if not self.enabled or not job:
            return
        with self._lock:
            b = self._job_locked(job)
            b.failures_total += 1
            b.consecutive_failures += 1
            if b.breaker_open:
                if b.probe_inflight:          # probe failed: re-open
                    b.probe_inflight = False
                    b.breaker_opened_at = self._attempts
                return
            if b.consecutive_failures >= self._breaker_failures:
                b.breaker_open = True
                b.probe_inflight = False
                b.breaker_opened_at = self._attempts
                b.breaker_opens_total += 1

    def note_success(self, job: str) -> None:
        """One healthy dispatch in ``job``: resets the consecutive-
        failure ladder and closes a half-open breaker."""
        if not self.enabled or not job:
            return
        with self._lock:
            b = self._jobs.get(job)
            if b is None:
                return
            b.consecutive_failures = 0
            if b.breaker_open and b.probe_inflight:
                b.breaker_open = False
                b.probe_inflight = False

    # -- views -----------------------------------------------------------
    def _device_shares(self) -> dict[str, float]:
        """Each job's share of total attributed device time, from the
        device-time ledger (empty when the ledger is off)."""
        try:
            from ..metrics.profiler import DEVICE_LEDGER
            jobs = DEVICE_LEDGER.snapshot().get("jobs", {})
        except Exception:  # pragma: no cover - ledger must never break us
            return {}
        total = sum(row.get("device_ms", 0.0) for row in jobs.values())
        if total <= 0.0:
            return {}
        return {name: round(row.get("device_ms", 0.0) / total, 4)
                for name, row in jobs.items()}

    def quota_view(self, name: str) -> Optional[dict]:
        """One job's quota/bulkhead state for REST and the CLI."""
        shares = self._device_shares()
        with self._lock:
            b = self._jobs.get(name)
            if b is None:
                return None
            return self._row(b, shares)

    @staticmethod
    def _row(b: JobBulkhead, shares: dict[str, float]) -> dict:
        return {"job": b.name,
                "weight": b.weight,
                "deficit": round(b.deficit, 3),
                "waiting": b.waiting,
                "device_time_share": shares.get(b.name, 0.0),
                "admitted_total": b.admitted_total,
                "admissions_rejected_total": b.rejected_total,
                "shed_batches_total": b.shed_batches_total,
                "shed_records_total": b.shed_records_total,
                "bulkhead_trips_total": b.bulkhead_trips_total,
                "failures_total": b.failures_total,
                "consecutive_failures": b.consecutive_failures,
                "breaker": b.breaker_state(),
                "breaker_opens_total": b.breaker_opens_total}

    def snapshot(self) -> dict:
        shares = self._device_shares()
        with self._lock:
            return {"enabled": self.enabled,
                    "attempts": self._attempts,
                    "jobs": {name: self._row(b, shares)
                             for name, b in sorted(self._jobs.items())}}


#: The process-global scheduler every admission gate consults.
#: ``deploy_local`` / ``DistributedHost.deploy`` configure it from the
#: job Configuration.
ISOLATION = IsolationScheduler()
