"""Streaming joins: stream-stream equi-join, interval join, lookup join.

Analogs of the reference table-runtime join operators
(flink-table-runtime operators/join/stream/StreamingJoinOperator.java —
two-sided state with association counting for outer joins;
operators/join/interval/IntervalJoinOperator — time-bounded buffered join;
operators/join/lookup/ — per-row probe of an external table) and of the
planner nodes StreamExecJoin / StreamExecIntervalJoin / StreamExecLookupJoin.

TPU-first shape: batches are grouped by join key once per micro-batch, state
is probed per distinct key (not per record), and output rows for one batch
are emitted as a single columnar batch. State lives per key group so
snapshots re-shard on rescale exactly like the keyed backends.

Outer-join semantics follow the reference's OuterJoinRecordStateView: each
stored row on an outer side tracks its number of associations; the
null-padded row is emitted while that count is zero and retracted (DELETE)
when the first association appears, re-emitted when the last disappears.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..core.keygroups import assign_to_key_group
from ..core.records import RecordBatch, Schema, scalar as _scalar
from ..runtime.operators.base import (
    OneInputOperator, Output, TwoInputOperator,
)
from . import rowkind as rk

__all__ = ["StreamingJoinOperator", "IntervalJoinOperator",
           "LookupJoinOperator"]



def _key_of(row: tuple, kidx) -> Any:
    """Join key of a row: single index or composite tuple of indices."""
    if isinstance(kidx, tuple):
        return tuple(row[i] for i in kidx)
    return row[kidx]


class _SideState:
    """One side's keyed state: kg -> key -> {row_tuple: [count, assoc]}.

    ``count`` is the row's multiplicity (duplicates accumulate), ``assoc``
    the number of matching rows currently on the other side (only meaningful
    when this side is outer — reference OuterJoinRecordStateView)."""

    def __init__(self):
        self.state: dict[int, dict[Any, dict[tuple, list]]] = {}

    def rows_for(self, kg: int, key) -> dict[tuple, list]:
        return self.state.get(kg, {}).get(key, {})

    def add(self, kg: int, key, row: tuple, assoc: int) -> list:
        entry = (self.state.setdefault(kg, {}).setdefault(key, {})
                 .setdefault(row, [0, assoc]))
        entry[0] += 1
        return entry

    def retract(self, kg: int, key, row: tuple) -> Optional[list]:
        kmap = self.state.get(kg, {}).get(key)
        if not kmap or row not in kmap:
            return None  # retraction of unknown row: ignore (reference logs)
        entry = kmap[row]
        entry[0] -= 1
        if entry[0] <= 0:
            del kmap[row]
            if not kmap:
                del self.state[kg][key]
        return entry

    def snapshot(self) -> dict:
        return {kg: {k: {r: list(e) for r, e in rows.items()}
                     for k, rows in keys.items()}
                for kg, keys in self.state.items()}

    def restore(self, snap: dict, key_group_range) -> None:
        for kg, keys in snap.items():
            if kg in key_group_range:
                tgt = self.state.setdefault(kg, {})
                for k, rows in keys.items():
                    tgt.setdefault(k, {}).update(
                        {tuple(r): list(e) for r, e in rows.items()})


class StreamingJoinOperator(TwoInputOperator):
    """Unbounded two-stream equi-join with changelog in/out.

    ``join_type`` in inner|left|right|full. Inputs may carry a rowkind
    column (changelog); outputs always carry one. ``key_index{1,2}`` are the
    positions of the join key inside each side's (rowkind-stripped) row;
    ``out_schema`` is left-fields + right-fields + rowkind, with other-side
    numeric fields pre-promoted to float64 by the planner when nullable."""

    def __init__(self, join_type: str, key_index1: int, key_index2: int,
                 out_schema: Schema, n_left: int, n_right: int,
                 post_filter: Optional[Callable] = None,
                 name: str = "Join"):
        super().__init__(name)
        if join_type not in ("inner", "left", "right", "full"):
            raise ValueError(f"unknown join type {join_type}")
        self.join_type = join_type
        self.key_idx = (key_index1, key_index2)
        self.out_schema = out_schema
        self.n_fields = (n_left, n_right)
        self.post_filter = post_filter
        if post_filter is not None and join_type != "inner":
            raise ValueError("non-equi conditions only supported for INNER")
        self.sides = (_SideState(), _SideState())
        self._null_rows = (tuple([None] * n_left), tuple([None] * n_right))

    def _outer(self, side: int) -> bool:
        return (self.join_type == "full"
                or (self.join_type == "left" and side == 0)
                or (self.join_type == "right" and side == 1))

    # -- data path ---------------------------------------------------------
    def process_batch1(self, batch: RecordBatch) -> None:
        self._process(0, batch)

    def process_batch2(self, batch: RecordBatch) -> None:
        self._process(1, batch)

    def _process(self, side: int, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        has_kind = rk.ROWKIND_COLUMN in batch.schema
        names = [f.name for f in batch.schema.fields
                 if f.name != rk.ROWKIND_COLUMN]
        kinds = (batch.column(rk.ROWKIND_COLUMN).astype(np.int8)
                 if has_kind else np.zeros(batch.n, np.int8))
        cols = [batch.column(n) for n in names]
        ts = batch.timestamps
        out_rows: list[tuple] = []
        out_ts: list[int] = []
        kidx = self.key_idx[side]
        for i in range(batch.n):
            row = tuple(_scalar(c[i]) for c in cols)
            accumulate = kinds[i] in (rk.INSERT, rk.UPDATE_AFTER)
            self._process_row(side, row, _key_of(row, kidx), accumulate,
                              int(ts[i]), out_rows, out_ts)
        if out_rows:
            self.output.emit(RecordBatch.from_rows(
                self.out_schema, out_rows, out_ts))

    def _joined(self, side: int, this_row: tuple, other_row: tuple,
                kind) -> tuple:
        l, r = (this_row, other_row) if side == 0 else (other_row, this_row)
        return l + r + (int(kind),)

    def _process_row(self, side: int, row: tuple, key, accumulate: bool,
                     ts: int, out_rows: list, out_ts: list) -> None:
        kg = assign_to_key_group(key, self.ctx.max_parallelism)
        mine, other = self.sides[side], self.sides[1 - side]
        other_rows = other.rows_for(kg, key)
        other_outer = self._outer(1 - side)
        this_outer = self._outer(side)

        def emit(r: tuple, t: int) -> None:
            if self.post_filter is not None and not self.post_filter(r):
                return
            out_rows.append(r)
            out_ts.append(t)

        if accumulate:
            total_matches = 0
            for orow, oentry in other_rows.items():
                if other_outer and oentry[1] == 0:
                    # other side's rows lose their null padding (one per
                    # stored duplicate)
                    for _ in range(oentry[0]):
                        emit(self._joined(side, self._null_rows[side], orow,
                                          rk.DELETE), ts)
                oentry[1] += 1
                total_matches += oentry[0]
                for _ in range(oentry[0]):
                    emit(self._joined(side, row, orow, rk.INSERT), ts)
            mine.add(kg, key, row, total_matches)
            if this_outer and total_matches == 0:
                emit(self._joined(side, row, self._null_rows[1 - side],
                                  rk.INSERT), ts)
        else:
            entry = mine.retract(kg, key, row)
            if entry is None:
                return  # retraction of a row we never saw
            for orow, oentry in other_rows.items():
                for _ in range(oentry[0]):
                    emit(self._joined(side, row, orow, rk.DELETE), ts)
                oentry[1] -= 1
                if other_outer and oentry[1] == 0:
                    for _ in range(oentry[0]):
                        emit(self._joined(side, self._null_rows[side], orow,
                                          rk.INSERT), ts)
            if this_outer and not other_rows:
                emit(self._joined(side, row, self._null_rows[1 - side],
                                  rk.DELETE), ts)

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"keyed": {"backend": {
            "join-left": self.sides[0].snapshot(),
            "join-right": self.sides[1].snapshot()}}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        for snap in keyed_snapshots:
            table = snap.get("backend", {})
            self.sides[0].restore(table.get("join-left", {}),
                                  self.ctx.key_group_range)
            self.sides[1].restore(table.get("join-right", {}),
                                  self.ctx.key_group_range)


class IntervalJoinOperator(TwoInputOperator):
    """Event-time interval join (reference IntervalJoinOperator):
    emit (l, r) when r.ts in [l.ts + lower, l.ts + upper]. Append-only in
    and out; state pruned by the combined watermark. Output timestamp is
    max(l.ts, r.ts) like the reference."""

    def __init__(self, key_index1: int, key_index2: int, lower_ms: int,
                 upper_ms: int, out_schema: Schema,
                 join_type: str = "inner", rows_per_key: int = 256,
                 name: str = "IntervalJoin"):
        super().__init__(name)
        if join_type != "inner":
            raise NotImplementedError(
                "outer interval joins need per-row emitted flags; v1 is "
                "inner-only (matches the DataStream API surface)")
        self.key_idx = (key_index1, key_index2)
        self.lower = lower_ms
        self.upper = upper_ms
        self.out_schema = out_schema
        self.rows_per_key = int(rows_per_key)
        # host plane: kg -> key -> list[(ts, row)] per side
        self.buffers: tuple[dict, dict] = ({}, {})
        # device plane (tpu backend + numeric schemas): per-side
        # DeviceListStore — each side's buffered rows live in HBM and a
        # probe batch is ONE lookup+gather; see state/device_lists.py
        self._stores: list = [None, None]
        self._side_ok = [False, False]   # per-side schema validated
        self._device: Optional[bool] = None
        self._restored_device: dict = {}

    def process_batch1(self, batch: RecordBatch) -> None:
        self._process(0, batch)

    def process_batch2(self, batch: RecordBatch) -> None:
        self._process(1, batch)

    def _bounds(self, side: int, ts: int) -> tuple[int, int]:
        """Other-side timestamp window matching a row with timestamp ts."""
        if side == 0:
            return ts + self.lower, ts + self.upper
        return ts - self.upper, ts - self.lower

    # -- device routing ----------------------------------------------------
    def _device_eligible(self, schema: Schema, side: int) -> bool:
        if self._device is False:
            return False
        if self._device and self._side_ok[side]:
            return True   # established AND validated; skip the scan
        from ..core.config import StateOptions
        if self.ctx.config.get(StateOptions.BACKEND) != "tpu":
            self._device = False
            return False
        if self.buffers[0] or self.buffers[1]:
            # host-plane buffers restored from a hashmap-backend
            # checkpoint: heterogeneous rows can't migrate to the packed
            # device lists without their schemas — keep plane continuity
            self._device = False
            return False
        ok = all(f.dtype is not object and
                 np.dtype(f.dtype).kind in "iufb" for f in schema.fields)
        kf = schema.fields[self.key_idx[side]]
        ok = ok and np.issubdtype(np.dtype(kf.dtype), np.integer)
        if not ok:
            if (self._stores[0] is not None or self._stores[1] is not None
                    or self._restored_device):
                raise TypeError(
                    "interval join: device-plane state exists but this "
                    "input is not device-eligible (non-numeric columns or "
                    "non-integer key); use the hashmap backend")
            self._device = False
            return False
        self._device = True
        self._side_ok[side] = True
        return True

    def _store(self, side: int, schema: Schema):
        # restored stores were materialized eagerly in initialize_state
        if self._stores[side] is None:
            from ..state.device_lists import DeviceListStore
            self._stores[side] = DeviceListStore(
                self.ctx.key_group_range, self.ctx.max_parallelism,
                [np.dtype(f.dtype) for f in schema.fields],
                rows_per_key=self.rows_per_key)
        return self._stores[side]

    def _process(self, side: int, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        if self._device_eligible(batch.schema, side):
            self._process_device(side, batch)
            return
        names = [f.name for f in batch.schema.fields]
        cols = [batch.column(n) for n in names]
        ts_arr = batch.timestamps
        kidx = self.key_idx[side]
        out_rows, out_ts = [], []
        for i in range(batch.n):
            row = tuple(_scalar(c[i]) for c in cols)
            ts = int(ts_arr[i])
            key = _key_of(row, kidx)
            kg = assign_to_key_group(key, self.ctx.max_parallelism)
            lo, hi = self._bounds(side, ts)
            for ots, orow in self.buffers[1 - side].get(kg, {}).get(key, ()):
                if lo <= ots <= hi:
                    l, r = (row, orow) if side == 0 else (orow, row)
                    out_rows.append(l + r)
                    out_ts.append(max(ts, ots))
            (self.buffers[side].setdefault(kg, {}).setdefault(key, [])
             .append((ts, row)))
        if out_rows:
            self.output.emit(RecordBatch.from_rows(
                self.out_schema, out_rows, out_ts))

    def _process_device(self, side: int, batch: RecordBatch) -> None:
        """Batched probe of the other side's HBM lists + append of this
        batch — two device programs and one transfer per batch, replacing
        the per-record Python buffer walk."""
        names = [f.name for f in batch.schema.fields]
        keys = batch.column(names[self.key_idx[side]]).astype(np.int64)
        ts = batch.timestamps
        other = self._stores[1 - side]
        if other is not None:
            packed, counts = other.probe_batch(keys)       # [B, L, C], [B]
            L = packed.shape[1]
            ots = packed[:, :, 0]                          # [B, L]
            live = np.arange(L)[None, :] < counts[:, None]
            if side == 0:
                lo, hi = ts + self.lower, ts + self.upper
            else:
                lo, hi = ts - self.upper, ts - self.lower
            m = live & (ots >= lo[:, None]) & (ots <= hi[:, None])
            bi, li = np.nonzero(m)
            if len(bi):
                mine = [batch.column(n)[bi] for n in names]
                theirs = [other._unpack_col(packed[bi, li], i)
                          for i in range(len(other.col_dtypes))]
                ordered = mine + theirs if side == 0 else theirs + mine
                out_cols = {f.name: c for f, c in
                            zip(self.out_schema.fields, ordered)}
                out_ts = np.maximum(ts[bi], ots[bi, li])
                self.output.emit(RecordBatch(self.out_schema, out_cols,
                                             out_ts))
        self._store(side, batch.schema).append_batch(
            keys, ts, [batch.column(n) for n in names])

    def process_watermark_n(self, input_index: int, watermark) -> None:
        super().process_watermark_n(input_index, watermark)
        wm = self.current_watermark
        # a row on side s can still match other-side rows arriving later iff
        # its matching window upper bound >= wm; prune the rest
        keep_after = (wm - self.upper, wm + self.lower)
        for side in (0, 1):
            horizon = keep_after[side]
            if self._stores[side] is not None:
                self._stores[side].prune(horizon)   # device compaction
                continue
            for kmap in self.buffers[side].values():
                for key in list(kmap):
                    kept = [(t, r) for t, r in kmap[key] if t >= horizon]
                    if kept:
                        kmap[key] = kept
                    else:
                        del kmap[key]

    def snapshot_state(self, checkpoint_id: int) -> dict:
        if self._device:
            return {"keyed": {"backend": {
                "list-left": (self._stores[0].snapshot()
                              if self._stores[0] is not None else None),
                "list-right": (self._stores[1].snapshot()
                               if self._stores[1] is not None else None)}}}
        return {"keyed": {"backend": {
            "buf-left": {kg: {k: list(v) for k, v in m.items()}
                         for kg, m in self.buffers[0].items()},
            "buf-right": {kg: {k: list(v) for k, v in m.items()}
                          for kg, m in self.buffers[1].items()}}}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        for snap in keyed_snapshots:
            table = snap.get("backend", {})
            for name, side in (("list-left", 0), ("list-right", 1)):
                dsnap = table.get(name)
                if dsnap is not None:
                    self._restored_device.setdefault(side, []).append(dsnap)
            for name, side in (("buf-left", 0), ("buf-right", 1)):
                for kg, kmap in table.get(name, {}).items():
                    if kg in self.ctx.key_group_range:
                        tgt = self.buffers[side].setdefault(kg, {})
                        for k, rows in kmap.items():
                            tgt.setdefault(k, []).extend(
                                (int(t), tuple(r)) for t, r in rows)
        if self._restored_device:
            # build stores EAGERLY: a checkpoint taken before the first
            # batch must carry this state, not an empty host plane
            from ..state.device_lists import DeviceListStore
            for side in list(self._restored_device):
                self._stores[side] = DeviceListStore.from_snapshots(
                    self.ctx.key_group_range, self.ctx.max_parallelism,
                    self._restored_device.pop(side),
                    rows_per_key=self.rows_per_key)
            self._device = True


class LookupJoinOperator(OneInputOperator):
    """Stream enriched against an external table (reference lookup join,
    StreamExecLookupJoin): per distinct probe key, ``lookup(key)`` returns
    matching rows from the dimension table; results are cached per operator
    instance. inner drops misses, left pads with nulls."""

    def __init__(self, key_index: int, lookup: Callable[[Any], Sequence[tuple]],
                 out_schema: Schema, n_right: int, join_type: str = "inner",
                 cache_size: int = 10000, name: str = "LookupJoin"):
        super().__init__(name)
        if join_type not in ("inner", "left"):
            raise ValueError("lookup join supports inner|left")
        self.key_index = key_index
        self.lookup = lookup
        self.out_schema = out_schema
        self.join_type = join_type
        self._null_right = tuple([None] * n_right)
        self._cache: dict[Any, tuple] = {}
        self._cache_size = cache_size

    def _probe(self, key) -> tuple:
        hit = self._cache.get(key)
        if hit is None:
            hit = tuple(tuple(r) for r in self.lookup(key))
            if len(self._cache) >= self._cache_size:
                self._cache.clear()
            self._cache[key] = hit
        return hit

    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        names = [f.name for f in batch.schema.fields]
        cols = [batch.column(n) for n in names]
        ts_arr = batch.timestamps
        out_rows, out_ts = [], []
        for i in range(batch.n):
            row = tuple(_scalar(c[i]) for c in cols)
            matches = self._probe(row[self.key_index])
            ts = int(ts_arr[i])
            if matches:
                for m in matches:
                    out_rows.append(row + m)
                    out_ts.append(ts)
            elif self.join_type == "left":
                out_rows.append(row + self._null_right)
                out_ts.append(ts)
        if out_rows:
            self.output.emit(RecordBatch.from_rows(
                self.out_schema, out_rows, out_ts))
