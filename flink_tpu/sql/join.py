"""Streaming joins: stream-stream equi-join, interval join, lookup join.

Analogs of the reference table-runtime join operators
(flink-table-runtime operators/join/stream/StreamingJoinOperator.java —
two-sided state with association counting for outer joins;
operators/join/interval/IntervalJoinOperator — time-bounded buffered join;
operators/join/lookup/ — per-row probe of an external table) and of the
planner nodes StreamExecJoin / StreamExecIntervalJoin / StreamExecLookupJoin.

TPU-first shape: batches are grouped by join key once per micro-batch, state
is probed per distinct key (not per record), and output rows for one batch
are emitted as a single columnar batch. State lives per key group so
snapshots re-shard on rescale exactly like the keyed backends.

Outer-join semantics follow the reference's OuterJoinRecordStateView: each
stored row on an outer side tracks its number of associations; the
null-padded row is emitted while that count is zero and retracted (DELETE)
when the first association appears, re-emitted when the last disappears.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..core.keygroups import assign_to_key_group
from ..core.records import RecordBatch, Schema, scalar as _scalar
from ..runtime.operators.base import (
    OneInputOperator, Output, TwoInputOperator,
)
from . import rowkind as rk

__all__ = ["StreamingJoinOperator", "IntervalJoinOperator",
           "LookupJoinOperator", "TemporalJoinOperator"]



def _key_of(row: tuple, kidx) -> Any:
    """Join key of a row: single index or composite tuple of indices."""
    if isinstance(kidx, tuple):
        return tuple(row[i] for i in kidx)
    return row[kidx]


class _SideState:
    """One side's keyed state: kg -> key -> {row_tuple: [count, assoc]}.

    ``count`` is the row's multiplicity (duplicates accumulate), ``assoc``
    the number of matching rows currently on the other side (only meaningful
    when this side is outer — reference OuterJoinRecordStateView)."""

    def __init__(self):
        self.state: dict[int, dict[Any, dict[tuple, list]]] = {}

    def rows_for(self, kg: int, key) -> dict[tuple, list]:
        return self.state.get(kg, {}).get(key, {})

    def add(self, kg: int, key, row: tuple, assoc: int) -> list:
        entry = (self.state.setdefault(kg, {}).setdefault(key, {})
                 .setdefault(row, [0, assoc]))
        entry[0] += 1
        return entry

    def retract(self, kg: int, key, row: tuple) -> Optional[list]:
        kmap = self.state.get(kg, {}).get(key)
        if not kmap or row not in kmap:
            return None  # retraction of unknown row: ignore (reference logs)
        entry = kmap[row]
        entry[0] -= 1
        if entry[0] <= 0:
            del kmap[row]
            if not kmap:
                del self.state[kg][key]
        return entry

    def snapshot(self) -> dict:
        return {kg: {k: {r: list(e) for r, e in rows.items()}
                     for k, rows in keys.items()}
                for kg, keys in self.state.items()}

    def restore(self, snap: dict, key_group_range) -> None:
        for kg, keys in snap.items():
            if kg in key_group_range:
                tgt = self.state.setdefault(kg, {})
                for k, rows in keys.items():
                    tgt.setdefault(k, {}).update(
                        {tuple(r): list(e) for r, e in rows.items()})


class StreamingJoinOperator(TwoInputOperator):
    """Unbounded two-stream equi-join with changelog in/out.

    ``join_type`` in inner|left|right|full. Inputs may carry a rowkind
    column (changelog); outputs always carry one. ``key_index{1,2}`` are the
    positions of the join key inside each side's (rowkind-stripped) row;
    ``out_schema`` is left-fields + right-fields + rowkind, with other-side
    numeric fields pre-promoted to float64 by the planner when nullable."""

    def __init__(self, join_type: str, key_index1: int, key_index2: int,
                 out_schema: Schema, n_left: int, n_right: int,
                 post_filter: Optional[Callable] = None,
                 name: str = "Join"):
        super().__init__(name)
        if join_type not in ("inner", "left", "right", "full"):
            raise ValueError(f"unknown join type {join_type}")
        self.join_type = join_type
        self.key_idx = (key_index1, key_index2)
        self.out_schema = out_schema
        self.n_fields = (n_left, n_right)
        self.post_filter = post_filter
        if post_filter is not None and join_type != "inner":
            raise ValueError("non-equi conditions only supported for INNER")
        self.sides = (_SideState(), _SideState())
        self._null_rows = (tuple([None] * n_left), tuple([None] * n_right))

    def _outer(self, side: int) -> bool:
        return (self.join_type == "full"
                or (self.join_type == "left" and side == 0)
                or (self.join_type == "right" and side == 1))

    # -- data path ---------------------------------------------------------
    def process_batch1(self, batch: RecordBatch) -> None:
        self._process(0, batch)

    def process_batch2(self, batch: RecordBatch) -> None:
        self._process(1, batch)

    def _process(self, side: int, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        has_kind = rk.ROWKIND_COLUMN in batch.schema
        names = [f.name for f in batch.schema.fields
                 if f.name != rk.ROWKIND_COLUMN]
        kinds = (batch.column(rk.ROWKIND_COLUMN).astype(np.int8)
                 if has_kind else np.zeros(batch.n, np.int8))
        cols = [batch.column(n) for n in names]
        ts = batch.timestamps
        out_rows: list[tuple] = []
        out_ts: list[int] = []
        kidx = self.key_idx[side]
        for i in range(batch.n):
            row = tuple(_scalar(c[i]) for c in cols)
            accumulate = kinds[i] in (rk.INSERT, rk.UPDATE_AFTER)
            self._process_row(side, row, _key_of(row, kidx), accumulate,
                              int(ts[i]), out_rows, out_ts)
        if out_rows:
            self.output.emit(RecordBatch.from_rows(
                self.out_schema, out_rows, out_ts))

    def _joined(self, side: int, this_row: tuple, other_row: tuple,
                kind) -> tuple:
        l, r = (this_row, other_row) if side == 0 else (other_row, this_row)
        return l + r + (int(kind),)

    def _process_row(self, side: int, row: tuple, key, accumulate: bool,
                     ts: int, out_rows: list, out_ts: list) -> None:
        kg = assign_to_key_group(key, self.ctx.max_parallelism)
        mine, other = self.sides[side], self.sides[1 - side]
        other_rows = other.rows_for(kg, key)
        other_outer = self._outer(1 - side)
        this_outer = self._outer(side)

        def emit(r: tuple, t: int) -> None:
            if self.post_filter is not None and not self.post_filter(r):
                return
            out_rows.append(r)
            out_ts.append(t)

        if accumulate:
            total_matches = 0
            for orow, oentry in other_rows.items():
                if other_outer and oentry[1] == 0:
                    # other side's rows lose their null padding (one per
                    # stored duplicate)
                    for _ in range(oentry[0]):
                        emit(self._joined(side, self._null_rows[side], orow,
                                          rk.DELETE), ts)
                oentry[1] += 1
                total_matches += oentry[0]
                for _ in range(oentry[0]):
                    emit(self._joined(side, row, orow, rk.INSERT), ts)
            mine.add(kg, key, row, total_matches)
            if this_outer and total_matches == 0:
                emit(self._joined(side, row, self._null_rows[1 - side],
                                  rk.INSERT), ts)
        else:
            entry = mine.retract(kg, key, row)
            if entry is None:
                return  # retraction of a row we never saw
            for orow, oentry in other_rows.items():
                for _ in range(oentry[0]):
                    emit(self._joined(side, row, orow, rk.DELETE), ts)
                oentry[1] -= 1
                if other_outer and oentry[1] == 0:
                    for _ in range(oentry[0]):
                        emit(self._joined(side, self._null_rows[side], orow,
                                          rk.INSERT), ts)
            if this_outer and not other_rows:
                emit(self._joined(side, row, self._null_rows[1 - side],
                                  rk.DELETE), ts)

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"keyed": {"backend": {
            "join-left": self.sides[0].snapshot(),
            "join-right": self.sides[1].snapshot()}}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        for snap in keyed_snapshots:
            table = snap.get("backend", {})
            self.sides[0].restore(table.get("join-left", {}),
                                  self.ctx.key_group_range)
            self.sides[1].restore(table.get("join-right", {}),
                                  self.ctx.key_group_range)


class IntervalJoinOperator(TwoInputOperator):
    """Event-time interval join (reference IntervalJoinOperator):
    emit (l, r) when r.ts in [l.ts + lower, l.ts + upper]. Append-only in
    and out; state pruned by the combined watermark. Output timestamp is
    max(l.ts, r.ts) like the reference."""

    def __init__(self, key_index1: int, key_index2: int, lower_ms: int,
                 upper_ms: int, out_schema: Schema,
                 join_type: str = "inner", rows_per_key: int = 256,
                 store_capacity: int = 1 << 12,
                 name: str = "IntervalJoin"):
        """``store_capacity``: initial key slots per side's device list
        store; pre-sizing to the expected key count avoids rehash
        round-trips AND keeps program shapes constant (every capacity
        change recompiles the append/probe/prune executables)."""
        super().__init__(name)
        if join_type != "inner":
            raise NotImplementedError(
                "outer interval joins need per-row emitted flags; v1 is "
                "inner-only (matches the DataStream API surface)")
        self.key_idx = (key_index1, key_index2)
        self.lower = lower_ms
        self.upper = upper_ms
        self.out_schema = out_schema
        self.rows_per_key = int(rows_per_key)
        self.store_capacity = int(store_capacity)
        # host plane: kg -> key -> list[(ts, row)] per side
        self.buffers: tuple[dict, dict] = ({}, {})
        # device plane (tpu backend + numeric schemas): per-side
        # DeviceListStore — each side's buffered rows live in HBM and a
        # probe batch is ONE lookup+gather; see state/device_lists.py
        self._stores: list = [None, None]
        self._side_ok = [False, False]   # per-side schema validated
        self._device: Optional[bool] = None
        self._restored_device: dict = {}

    def process_batch1(self, batch: RecordBatch) -> None:
        self._process(0, batch)

    def process_batch2(self, batch: RecordBatch) -> None:
        self._process(1, batch)

    def _bounds(self, side: int, ts: int) -> tuple[int, int]:
        """Other-side timestamp window matching a row with timestamp ts."""
        if side == 0:
            return ts + self.lower, ts + self.upper
        return ts - self.upper, ts - self.lower

    # -- device routing ----------------------------------------------------
    def _device_eligible(self, schema: Schema, side: int) -> bool:
        if self._device is False:
            return False
        if self._device and self._side_ok[side]:
            return True   # established AND validated; skip the scan
        from ..core.config import StateOptions
        if self.ctx.config.get(StateOptions.BACKEND) != "tpu":
            self._device = False
            return False
        if self.buffers[0] or self.buffers[1]:
            # host-plane buffers restored from a hashmap-backend
            # checkpoint: heterogeneous rows can't migrate to the packed
            # device lists without their schemas — keep plane continuity
            self._device = False
            return False
        ok = all(f.dtype is not object and
                 np.dtype(f.dtype).kind in "iufb" for f in schema.fields)
        kf = schema.fields[self.key_idx[side]]
        ok = ok and np.issubdtype(np.dtype(kf.dtype), np.integer)
        if not ok:
            if (self._stores[0] is not None or self._stores[1] is not None
                    or self._restored_device):
                raise TypeError(
                    "interval join: device-plane state exists but this "
                    "input is not device-eligible (non-numeric columns or "
                    "non-integer key); use the hashmap backend")
            self._device = False
            return False
        self._device = True
        self._side_ok[side] = True
        return True

    def _store(self, side: int, schema: Schema):
        # restored stores were materialized eagerly in initialize_state
        if self._stores[side] is None:
            from ..state.device_lists import DeviceListStore
            self._stores[side] = DeviceListStore(
                self.ctx.key_group_range, self.ctx.max_parallelism,
                [np.dtype(f.dtype) for f in schema.fields],
                capacity=self.store_capacity,
                rows_per_key=self.rows_per_key)
        return self._stores[side]

    def _process(self, side: int, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        if self._device_eligible(batch.schema, side):
            self._process_device(side, batch)
            return
        names = [f.name for f in batch.schema.fields]
        cols = [batch.column(n) for n in names]
        ts_arr = batch.timestamps
        kidx = self.key_idx[side]
        out_rows, out_ts = [], []
        for i in range(batch.n):
            row = tuple(_scalar(c[i]) for c in cols)
            ts = int(ts_arr[i])
            key = _key_of(row, kidx)
            kg = assign_to_key_group(key, self.ctx.max_parallelism)
            lo, hi = self._bounds(side, ts)
            for ots, orow in self.buffers[1 - side].get(kg, {}).get(key, ()):
                if lo <= ots <= hi:
                    l, r = (row, orow) if side == 0 else (orow, row)
                    out_rows.append(l + r)
                    out_ts.append(max(ts, ots))
            (self.buffers[side].setdefault(kg, {}).setdefault(key, [])
             .append((ts, row)))
        if out_rows:
            self.output.emit(RecordBatch.from_rows(
                self.out_schema, out_rows, out_ts))

    def _process_device(self, side: int, batch: RecordBatch) -> None:
        """Batched probe of the other side's HBM lists + append of this
        batch — two device programs and one transfer per batch, replacing
        the per-record Python buffer walk."""
        names = [f.name for f in batch.schema.fields]
        keys = batch.column(names[self.key_idx[side]]).astype(np.int64)
        ts = batch.timestamps
        other = self._stores[1 - side]
        if other is not None:
            packed, counts = other.probe_batch(keys)       # [B, L, C], [B]
            L = packed.shape[1]
            ots = packed[:, :, 0]                          # [B, L]
            live = np.arange(L)[None, :] < counts[:, None]
            if side == 0:
                lo, hi = ts + self.lower, ts + self.upper
            else:
                lo, hi = ts - self.upper, ts - self.lower
            m = live & (ots >= lo[:, None]) & (ots <= hi[:, None])
            bi, li = np.nonzero(m)
            if len(bi):
                mine = [batch.column(n)[bi] for n in names]
                theirs = [other._unpack_col(packed[bi, li], i)
                          for i in range(len(other.col_dtypes))]
                ordered = mine + theirs if side == 0 else theirs + mine
                out_cols = {f.name: c for f, c in
                            zip(self.out_schema.fields, ordered)}
                out_ts = np.maximum(ts[bi], ots[bi, li])
                self.output.emit(RecordBatch(self.out_schema, out_cols,
                                             out_ts))
        self._store(side, batch.schema).append_batch(
            keys, ts, [batch.column(n) for n in names])

    def process_watermark_n(self, input_index: int, watermark) -> None:
        super().process_watermark_n(input_index, watermark)
        wm = self.current_watermark
        # a row on side s can still match other-side rows arriving later iff
        # its matching window upper bound >= wm; prune the rest
        keep_after = (wm - self.upper, wm + self.lower)
        for side in (0, 1):
            horizon = keep_after[side]
            if self._stores[side] is not None:
                self._stores[side].prune(horizon)   # device compaction
                continue
            for kmap in self.buffers[side].values():
                for key in list(kmap):
                    kept = [(t, r) for t, r in kmap[key] if t >= horizon]
                    if kept:
                        kmap[key] = kept
                    else:
                        del kmap[key]

    def snapshot_state(self, checkpoint_id: int) -> dict:
        if self._device:
            return {"keyed": {"backend": {
                "list-left": (self._stores[0].snapshot()
                              if self._stores[0] is not None else None),
                "list-right": (self._stores[1].snapshot()
                               if self._stores[1] is not None else None)}}}
        return {"keyed": {"backend": {
            "buf-left": {kg: {k: list(v) for k, v in m.items()}
                         for kg, m in self.buffers[0].items()},
            "buf-right": {kg: {k: list(v) for k, v in m.items()}
                          for kg, m in self.buffers[1].items()}}}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        for snap in keyed_snapshots:
            table = snap.get("backend", {})
            for name, side in (("list-left", 0), ("list-right", 1)):
                dsnap = table.get(name)
                if dsnap is not None:
                    self._restored_device.setdefault(side, []).append(dsnap)
            for name, side in (("buf-left", 0), ("buf-right", 1)):
                for kg, kmap in table.get(name, {}).items():
                    if kg in self.ctx.key_group_range:
                        tgt = self.buffers[side].setdefault(kg, {})
                        for k, rows in kmap.items():
                            tgt.setdefault(k, []).extend(
                                (int(t), tuple(r)) for t, r in rows)
        if self._restored_device:
            # build stores EAGERLY: a checkpoint taken before the first
            # batch must carry this state, not an empty host plane
            from ..state.device_lists import DeviceListStore
            for side in list(self._restored_device):
                self._stores[side] = DeviceListStore.from_snapshots(
                    self.ctx.key_group_range, self.ctx.max_parallelism,
                    self._restored_device.pop(side),
                    rows_per_key=self.rows_per_key,
                    capacity=self.store_capacity)
            self._device = True


class TemporalJoinOperator(TwoInputOperator):
    """Event-time temporal (versioned-table) join: each left (append)
    row joins the right-side VERSION that was valid at the left row's
    event time (reference StreamExecTemporalJoin.java:77 /
    TemporalRowTimeJoinOperator).

    Input 2 is a changelog/upsert stream building the versioned table:
    INSERT/UPDATE_AFTER rows start a new version at their timestamp,
    DELETE rows a tombstone (no valid version from then on);
    UPDATE_BEFORE rows are ignored (the matching UA carries the state).
    Left rows buffer until the combined watermark passes their timestamp —
    only then are all versions <= t known — and emit as INSERT rows
    (inner drops versionless rows, left pads nulls). Version history at
    or below the watermark compacts to the latest entry per key."""

    def __init__(self, join_type: str, key_index1: int, key_index2: int,
                 out_schema: Schema, n_left: int, n_right: int,
                 name: str = "TemporalJoin"):
        super().__init__(name)
        if join_type not in ("inner", "left"):
            raise ValueError("temporal join supports inner|left")
        self.join_type = join_type
        self.key_idx = (key_index1, key_index2)
        self.out_schema = out_schema
        self.n_fields = (n_left, n_right)
        self._null_right = tuple([None] * n_right)
        # kg -> key -> [ts_list, row_list] parallel sorted arrays
        # (row None = tombstone); parallel lists keep the bisect O(log V)
        # per probe instead of rebuilding a timestamp list per record
        self._versions: dict[int, dict[Any, list]] = {}
        # kg -> [(ts, key, row)] awaiting the watermark
        self._left_buf: dict[int, list] = {}
        # version-table keys touched since the last compaction: the
        # watermark pass prunes only these (untouched keys prune when
        # next touched)
        self._dirty_keys: set = set()

    # -- ingest ------------------------------------------------------------
    def process_batch1(self, batch: RecordBatch) -> None:
        names = [f.name for f in batch.schema.fields
                 if f.name != rk.ROWKIND_COLUMN]
        cols = [batch.column(n) for n in names]
        kinds = (np.asarray(batch.column(rk.ROWKIND_COLUMN))
                 if rk.ROWKIND_COLUMN in batch.schema else None)
        ts_arr = batch.timestamps
        for i in range(batch.n):
            if kinds is not None and kinds[i] != rk.INSERT:
                raise ValueError(
                    "temporal join: the probe side must be append-only "
                    "(reference: updating left inputs need a changelog "
                    "temporal join, not supported)")
            row = tuple(_scalar(c[i]) for c in cols)
            key = _key_of(row, self.key_idx[0])
            kg = assign_to_key_group(key, self.ctx.max_parallelism)
            self._left_buf.setdefault(kg, []).append(
                (int(ts_arr[i]), key, row))

    def process_batch2(self, batch: RecordBatch) -> None:
        names = [f.name for f in batch.schema.fields
                 if f.name != rk.ROWKIND_COLUMN]
        cols = [batch.column(n) for n in names]
        kinds = (np.asarray(batch.column(rk.ROWKIND_COLUMN))
                 if rk.ROWKIND_COLUMN in batch.schema else None)
        ts_arr = batch.timestamps
        import bisect
        for i in range(batch.n):
            kind = int(kinds[i]) if kinds is not None else rk.INSERT
            if kind == rk.UPDATE_BEFORE:
                continue
            key_row = tuple(_scalar(c[i]) for c in cols)
            row = None if kind == rk.DELETE else key_row
            key = _key_of(key_row, self.key_idx[1])
            kg = assign_to_key_group(key, self.ctx.max_parallelism)
            entry = self._versions.setdefault(kg, {}).setdefault(
                key, [[], []])
            ts_list, row_list = entry
            ts = int(ts_arr[i])
            # keep sorted by version time; equal timestamps: last wins
            pos = bisect.bisect_right(ts_list, ts)
            if pos > 0 and ts_list[pos - 1] == ts:
                row_list[pos - 1] = row
            else:
                ts_list.insert(pos, ts)
                row_list.insert(pos, row)
            self._dirty_keys.add((kg, key))

    # -- emission ----------------------------------------------------------
    def process_watermark_n(self, input_index: int, watermark) -> None:
        # release buffered rows BEFORE the base class forwards the
        # watermark: a downstream event-time operator must see the rows
        # (all with ts <= wm) ahead of the watermark that closed them, or
        # every temporal-join result would arrive late by construction
        wms = list(self._input_watermarks)
        wms[input_index] = watermark.timestamp
        wm = min(wms)
        import bisect
        out_rows, out_ts = [], []
        for kg, buf in list(self._left_buf.items()):
            keep = []
            for ts, key, row in buf:
                if ts > wm:
                    keep.append((ts, key, row))
                    continue
                entry = self._versions.get(kg, {}).get(key)
                vrow = None
                if entry is not None:
                    pos = bisect.bisect_right(entry[0], ts)
                    vrow = entry[1][pos - 1] if pos > 0 else None
                if vrow is not None:
                    out_rows.append(row + vrow + (rk.INSERT,))
                    out_ts.append(ts)
                elif self.join_type == "left":
                    out_rows.append(row + self._null_right + (rk.INSERT,))
                    out_ts.append(ts)
            if keep:
                self._left_buf[kg] = keep
            else:
                del self._left_buf[kg]
        # compact TOUCHED keys' version history: keep the newest version
        # at/below the watermark (rows between it and wm still need it)
        # plus everything above. A key stays dirty while it still holds
        # multiple versions (one lagging input can leave wm at -inf, which
        # compacts nothing — dirtiness must survive that watermark).
        still_dirty = set()
        for kg, key in self._dirty_keys:
            keys = self._versions.get(kg, {})
            entry = keys.get(key)
            if entry is None:
                continue
            ts_list, row_list = entry
            pos = bisect.bisect_right(ts_list, wm)
            if pos > 1:
                entry[0] = ts_list = ts_list[pos - 1:]
                entry[1] = row_list = row_list[pos - 1:]
            if (len(ts_list) == 1 and row_list[0] is None
                    and ts_list[0] <= wm):
                del keys[key]   # settled tombstone: key is gone
            elif len(ts_list) > 1 or row_list[-1] is None:
                # still compactable later: multiple versions, or a
                # tombstone the watermark has not settled yet (dropping
                # it here would leak the entry forever)
                still_dirty.add((kg, key))
        self._dirty_keys = still_dirty
        if out_rows:
            self.output.emit(RecordBatch.from_rows(
                self.out_schema, out_rows, out_ts))
        super().process_watermark_n(input_index, watermark)

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"keyed": {"backend": {
            "temporal-versions": {
                kg: {k: [list(e[0]), list(e[1])]
                     for k, e in keys.items()}
                for kg, keys in self._versions.items()},
            "temporal-left": {kg: list(v)
                              for kg, v in self._left_buf.items()}}}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        for snap in keyed_snapshots:
            table = snap.get("backend", {})
            for kg, keys in table.get("temporal-versions", {}).items():
                if kg in self.ctx.key_group_range:
                    tgt = self._versions.setdefault(kg, {})
                    for k, (ts_list, row_list) in keys.items():
                        entry = tgt.setdefault(k, [[], []])
                        pairs = sorted(
                            list(zip(entry[0], entry[1]))
                            + [(int(t), tuple(r) if r is not None else None)
                               for t, r in zip(ts_list, row_list)],
                            key=lambda v: v[0])
                        entry[0] = [p[0] for p in pairs]
                        entry[1] = [p[1] for p in pairs]
            for kg, buf in table.get("temporal-left", {}).items():
                if kg in self.ctx.key_group_range:
                    self._left_buf.setdefault(kg, []).extend(
                        (int(t), k, tuple(r)) for t, k, r in buf)
        # restored version histories must be compactable without waiting
        # for the key to be touched again
        for kg, keys in self._versions.items():
            for key in keys:
                self._dirty_keys.add((kg, key))


class LookupJoinOperator(OneInputOperator):
    """Stream enriched against an external table (reference lookup join,
    StreamExecLookupJoin): per distinct probe key, ``lookup(key)`` returns
    matching rows from the dimension table; results are cached per operator
    instance. inner drops misses, left pads with nulls."""

    def __init__(self, key_index: int, lookup: Callable[[Any], Sequence[tuple]],
                 out_schema: Schema, n_right: int, join_type: str = "inner",
                 cache_size: int = 10000, name: str = "LookupJoin"):
        super().__init__(name)
        if join_type not in ("inner", "left"):
            raise ValueError("lookup join supports inner|left")
        self.key_index = key_index
        self.lookup = lookup
        self.out_schema = out_schema
        self.join_type = join_type
        self._null_right = tuple([None] * n_right)
        self._cache: dict[Any, tuple] = {}
        self._cache_size = cache_size

    def _probe(self, key) -> tuple:
        hit = self._cache.get(key)
        if hit is None:
            hit = tuple(tuple(r) for r in self.lookup(key))
            if len(self._cache) >= self._cache_size:
                self._cache.clear()
            self._cache[key] = hit
        return hit

    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        names = [f.name for f in batch.schema.fields]
        cols = [batch.column(n) for n in names]
        ts_arr = batch.timestamps
        out_rows, out_ts = [], []
        for i in range(batch.n):
            row = tuple(_scalar(c[i]) for c in cols)
            matches = self._probe(row[self.key_index])
            ts = int(ts_arr[i])
            if matches:
                for m in matches:
                    out_rows.append(row + m)
                    out_ts.append(ts)
            elif self.join_type == "left":
                out_rows.append(row + self._null_right)
                out_ts.append(ts)
        if out_rows:
            self.output.emit(RecordBatch.from_rows(
                self.out_schema, out_rows, out_ts))
