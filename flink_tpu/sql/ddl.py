"""SQL DDL: CREATE TABLE/VIEW, DROP, SHOW, DESCRIBE, INSERT INTO — and the
catalog + connector-factory machinery behind them.

Reference semantics: TableEnvironmentImpl.executeSql:727 routes non-query
statements to catalog operations (flink-table-api-java), table specs live in
a catalog (GenericInMemoryCatalog), and `WITH ('connector'='...')` options
are resolved through the factory SPI (FactoryUtil.createDynamicTableSource;
flink-table-common factories/Factory). Here the catalog stores *connector
specs*, instantiated lazily into an execution environment when a query
references them — "codegen" for a spec is just building the DataStream
source, so a spec-backed table can be re-planned into any number of fresh
environments (each execute_sql gets its own), unlike a temporary view which
stays bound to the user's stream.

Grammar (LL(1), same tokenizer as the query parser):

    CREATE [TEMPORARY] TABLE [IF NOT EXISTS] name
        (col TYPE [, ...] [, WATERMARK FOR col AS col - INTERVAL 'n' UNIT])
        WITH ('connector' = '...', ...)
    CREATE [TEMPORARY] VIEW name AS <select>
    DROP TABLE|VIEW [IF EXISTS] name
    SHOW TABLES | DESCRIBE name | INSERT INTO name <select>

Connectors: datagen (rows-per-second, number-of-rows, per-field kind =
sequence|random), filesystem (path, format = csv|json|binary), log (the
Kafka-shaped partitioned log: topic, broker), socket, print, blackhole.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..core.records import Schema
from ..core.watermarks import WatermarkStrategy
from .parser import SelectStmt, SqlError, _tokenize, parse

__all__ = ["Catalog", "CatalogTable", "parse_statement", "CreateTableStmt",
           "CreateViewStmt", "DropStmt", "ShowTablesStmt", "ShowViewsStmt",
           "ShowCreateStmt", "DescribeStmt", "InsertStmt", "ExplainStmt",
           "instantiate_source", "instantiate_sink",
           "sql_type_to_dtype", "dtype_to_sql_type"]

_SQL_TYPES = {
    "TINYINT": np.int32, "SMALLINT": np.int32, "INT": np.int32,
    "INTEGER": np.int32, "BIGINT": np.int64,
    "FLOAT": np.float32, "REAL": np.float32, "DOUBLE": np.float64,
    "DECIMAL": np.float64, "NUMERIC": np.float64,
    "BOOLEAN": np.bool_,
    "STRING": object, "VARCHAR": object, "CHAR": object,
    "TIMESTAMP": np.int64, "TIMESTAMP_LTZ": np.int64, "DATE": np.int64,
    "BYTES": object, "VARBINARY": object,
}

_UNITS_MS = {
    "MILLISECOND": 1, "SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
    "DAY": 86_400_000,
}


def sql_type_to_dtype(t: str):
    dt = _SQL_TYPES.get(t.upper())
    if dt is None:
        raise SqlError(f"unsupported SQL type {t!r}")
    return dt


def dtype_to_sql_type(dt) -> str:
    if dt is object:
        return "STRING"
    name = np.dtype(dt).name
    return {"int32": "INT", "int64": "BIGINT", "float32": "FLOAT",
            "float64": "DOUBLE", "bool": "BOOLEAN"}.get(name, name.upper())


# -- statements -------------------------------------------------------------

@dataclass
class CreateTableStmt:
    name: str
    columns: list  # [(name, sql_type)]
    options: dict
    watermark_col: Optional[str] = None
    watermark_delay_ms: int = 0
    if_not_exists: bool = False
    temporary: bool = False


@dataclass
class CreateViewStmt:
    name: str
    select: SelectStmt
    select_sql: str = ""
    temporary: bool = False


@dataclass
class DropStmt:
    kind: str  # "TABLE" | "VIEW"
    name: str
    if_exists: bool = False


@dataclass
class ShowTablesStmt:
    pass


@dataclass
class ShowViewsStmt:
    pass


@dataclass
class ShowCreateStmt:
    name: str


@dataclass
class DescribeStmt:
    name: str


@dataclass
class InsertStmt:
    target: str
    select: SelectStmt


@dataclass
class ExplainStmt:
    select: SelectStmt


# -- DDL parser -------------------------------------------------------------

class _DdlParser:
    def __init__(self, sql: str):
        self.toks = _tokenize(sql)
        self.i = 0
        self.sql = sql

    def peek(self) -> tuple[str, str]:
        return self.toks[self.i] if self.i < len(self.toks) else ("eof", "")

    def next(self) -> tuple[str, str]:
        t = self.peek()
        self.i += 1
        return t

    def expect_kw(self, *kws: str) -> str:
        kind, val = self.next()
        if kind != "id" or val.upper() not in kws:
            raise SqlError(f"expected {'/'.join(kws)}, got {val!r}")
        return val.upper()

    def accept_kw(self, kw: str) -> bool:
        kind, val = self.peek()
        if kind == "id" and val.upper() == kw:
            self.i += 1
            return True
        return False

    def ident(self) -> str:
        kind, val = self.next()
        if kind != "id":
            raise SqlError(f"expected identifier, got {val!r}")
        return val

    def string(self) -> str:
        kind, val = self.next()
        if kind != "str":
            raise SqlError(f"expected string literal, got {val!r}")
        return val  # tokenizer already stripped the quotes

    def expect_sym(self, sym: str) -> None:
        kind, val = self.next()
        if val != sym:
            raise SqlError(f"expected {sym!r}, got {val!r}")

    # CREATE ... ------------------------------------------------------------
    def parse_create(self):
        self.expect_kw("CREATE")
        temporary = self.accept_kw("TEMPORARY")
        what = self.expect_kw("TABLE", "VIEW")
        if what == "VIEW":
            name = self.ident()
            self.expect_kw("AS")
            rest = self.sql[self._rest_pos():].strip()
            if not rest:
                raise SqlError(f"CREATE VIEW {name}: missing SELECT body")
            return CreateViewStmt(name, parse(rest), rest, temporary)
        if_not_exists = False
        if self.accept_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            if_not_exists = True
        name = self.ident()
        self.expect_sym("(")
        columns: list[tuple[str, str]] = []
        wm_col, wm_delay = None, 0
        while True:
            if self.accept_kw("WATERMARK"):
                self.expect_kw("FOR")
                wm_col = self.ident()
                self.expect_kw("AS")
                wm_delay = self._watermark_expr(wm_col)
            else:
                col = self.ident()
                kind, t = self.next()
                if kind != "id":
                    raise SqlError(f"expected type after column {col!r}")
                sql_type_to_dtype(t)  # validate now, fail loud at DDL time
                # swallow parametrized types: VARCHAR(255), DECIMAL(10, 2)
                if self.peek()[1] == "(":
                    while self.next()[1] != ")":
                        pass
                columns.append((col, t.upper()))
            kind, val = self.next()
            if val == ")":
                break
            if val != ",":
                raise SqlError(f"expected ',' or ')' in column list, "
                               f"got {val!r}")
        options: dict[str, str] = {}
        if self.accept_kw("WITH"):
            self.expect_sym("(")
            while True:
                k = self.string()
                self.expect_sym("=")
                options[k] = self.string()
                kind, val = self.next()
                if val == ")":
                    break
                if val != ",":
                    raise SqlError(f"expected ',' or ')' in WITH, got {val!r}")
        if not columns:
            raise SqlError(f"CREATE TABLE {name}: empty column list")
        return CreateTableStmt(name, columns, options, wm_col, wm_delay,
                               if_not_exists, temporary)

    def _watermark_expr(self, col: str) -> int:
        """``col - INTERVAL 'n' UNIT`` (or bare ``col`` = 0 delay)."""
        first = self.ident()
        if first != col:
            raise SqlError(f"WATERMARK FOR {col} AS must reference {col}")
        if self.peek()[1] != "-":
            return 0
        self.next()
        self.expect_kw("INTERVAL")
        n = self.string()
        kind, unit = self.next()
        factor = _UNITS_MS.get(unit.upper())
        if factor is None:
            raise SqlError(f"bad interval unit {unit!r}")
        return int(float(n) * factor)

    def _rest_pos(self) -> int:
        """Char offset of the current token in the original SQL (the view
        body is re-parsed by the query parser from here)."""
        # tokens do not carry offsets; find the i-th token occurrence by
        # re-tokenizing prefix lengths — small inputs, clarity over speed
        upper = 0
        target = self.toks[self.i][1]
        seen = self.toks[: self.i]
        pos = 0
        for kind, val in seen:
            pos = self.sql.find(val, pos) + len(val)
        return self.sql.find(target, pos) if target else pos

    # others -----------------------------------------------------------------
    def parse_drop(self) -> DropStmt:
        self.expect_kw("DROP")
        kind = self.expect_kw("TABLE", "VIEW")
        if_exists = False
        if self.accept_kw("IF"):
            self.expect_kw("EXISTS")
            if_exists = True
        return DropStmt(kind, self.ident(), if_exists)

    def parse_insert(self) -> InsertStmt:
        self.expect_kw("INSERT")
        self.expect_kw("INTO")
        target = self.ident()
        rest = self.sql[self._rest_pos():].strip()
        if not rest:
            raise SqlError(f"INSERT INTO {target}: missing SELECT body")
        return InsertStmt(target, parse(rest))


def parse_statement(sql: str):
    """Statement router: returns a DDL statement object or a SelectStmt."""
    stripped = sql.strip()
    head = stripped.split(None, 1)[0].upper() if stripped else ""
    p = _DdlParser(stripped)
    if head == "CREATE":
        return p.parse_create()
    if head == "DROP":
        return p.parse_drop()
    if head == "SHOW":
        p.expect_kw("SHOW")
        what = p.expect_kw("TABLES", "VIEWS", "CREATE")
        if what == "TABLES":
            return ShowTablesStmt()
        if what == "VIEWS":
            return ShowViewsStmt()
        p.expect_kw("TABLE")
        return ShowCreateStmt(p.ident())
    if head in ("DESCRIBE", "DESC"):
        p.next()
        return DescribeStmt(p.ident())
    if head == "INSERT":
        return p.parse_insert()
    if head == "EXPLAIN":
        parts = stripped.split(None, 1)
        rest = parts[1].strip() if len(parts) > 1 else ""
        if not rest:
            raise SqlError("EXPLAIN: missing statement")
        inner = parse_statement(rest)
        if not isinstance(inner, (SelectStmt, InsertStmt)):
            raise SqlError("EXPLAIN supports queries and INSERT INTO")
        return ExplainStmt(inner)
    return parse(stripped)


# -- catalog ----------------------------------------------------------------

@dataclass
class CatalogTable:
    """One catalog entry: a connector spec (lazily instantiated), a view
    (re-planned per query), or a bound stream (temporary view over a user
    DataStream)."""

    name: str
    kind: str                      # "spec" | "view"
    schema: Optional[Schema] = None
    options: dict = field(default_factory=dict)
    watermark_col: Optional[str] = None
    watermark_delay_ms: int = 0
    view_select: Optional[SelectStmt] = None


class Catalog:
    """In-memory catalog (reference GenericInMemoryCatalog)."""

    def __init__(self, name: str = "default_catalog"):
        self.name = name
        self._tables: dict[str, CatalogTable] = {}

    def create(self, table: CatalogTable, if_not_exists: bool = False) -> None:
        key = table.name.lower()
        if key in self._tables:
            if if_not_exists:
                return
            raise SqlError(f"table {table.name!r} already exists")
        self._tables[key] = table

    def drop(self, name: str, kind: str, if_exists: bool = False) -> None:
        key = name.lower()
        entry = self._tables.get(key)
        if entry is None:
            if if_exists:
                return
            raise SqlError(f"{kind.lower()} {name!r} does not exist")
        is_view = entry.kind == "view"
        if (kind == "VIEW") != is_view:
            raise SqlError(f"{name!r} is a {'view' if is_view else 'table'}; "
                           f"use DROP {'VIEW' if is_view else 'TABLE'}")
        del self._tables[key]

    def get(self, name: str) -> Optional[CatalogTable]:
        return self._tables.get(name.lower())

    def names(self) -> list[str]:
        return sorted(t.name for t in self._tables.values())


# -- connector factories ----------------------------------------------------

# process-global named brokers for the log connector, so two tables created
# in different TableEnvironments can talk through the same topic (the way
# two Kafka clients share a cluster by address)
_BROKERS: dict[str, Any] = {}
_BROKERS_LOCK = threading.Lock()

# plugin connectors (core/plugins.py registry.connector): consulted AFTER
# the built-ins; a factory provides source and/or sink construction
_PLUGIN_CONNECTORS: dict[str, dict] = {}


def register_connector(name: str, source=None, sink=None) -> None:
    """Plugin seam (reference factory SPI discovery): ``source(env,
    catalog_table) -> DataStream``; ``sink(catalog_table) -> Sink|
    SinkFunction``."""
    _PLUGIN_CONNECTORS[name] = {"source": source, "sink": sink}


def _broker(name: str, config=None):
    """Named in-process broker, or a TCP client when the option looks like
    host:port (the real-cluster path: a LogBrokerServer listens there).
    ``config`` feeds the cluster-secret resolution of the TCP client; the
    cache key includes the resolved secret so a later caller with a
    DIFFERENT secret gets its own connection instead of silently reusing
    one authenticated (or not) as someone else."""
    from ..utils import auth

    cache_key = name
    if ":" in name:
        cache_key = (name, auth.resolve_secret(config))
    with _BROKERS_LOCK:
        b = _BROKERS.get(cache_key)
        if b is None:
            if ":" in name:     # cached per address: one connection, not
                from ..connectors.log_net import RemoteLogBroker  # per stmt
                b = RemoteLogBroker(name, config=config)
            else:
                from ..connectors.log import InMemoryLogBroker
                b = InMemoryLogBroker()
            _BROKERS[cache_key] = b
        return b


def _format(options: dict, schema: Schema):
    from ..formats.core import BinaryFormat, CsvFormat, JsonFormat
    fmt = options.get("format", "csv")
    if fmt == "csv":
        return CsvFormat(schema)
    if fmt == "json":
        return JsonFormat(schema)
    if fmt == "binary":
        return BinaryFormat(schema)
    if fmt == "columnar":
        from ..formats.columnar import ColumnarFormat
        return ColumnarFormat(schema)
    if fmt == "avro":
        from ..formats.avro import AvroFormat
        return AvroFormat(schema)
    raise SqlError(f"unsupported format {fmt!r} "
                   f"(csv|json|binary|columnar|avro)")


def _watermark_strategy(entry: CatalogTable) -> Optional[WatermarkStrategy]:
    if entry.watermark_col is None:
        return None
    return (WatermarkStrategy
            .for_bounded_out_of_orderness(entry.watermark_delay_ms)
            .with_timestamp_column(entry.watermark_col))


def _datagen_fn(schema: Schema, options: dict):
    """Vectorized generator from per-field options:
    fields.<name>.kind = sequence (start + idx) | random (min..max)."""
    specs = []
    for f in schema.fields:
        kind = options.get(f"fields.{f.name}.kind", "sequence")
        lo = int(options.get(f"fields.{f.name}.min", 0))
        hi = int(options.get(f"fields.{f.name}.max", 1 << 20))
        start = int(options.get(f"fields.{f.name}.start", 0))
        specs.append((f.name, f.dtype, kind, lo, hi, start))

    def gen(idx: np.ndarray) -> dict:
        out = {}
        for name, dtype, kind, lo, hi, start in specs:
            if dtype is object:
                out[name] = np.array([f"{name}-{int(i)}" for i in idx],
                                     dtype=object)
            elif kind == "random":
                # stateless per-idx hash keeps restore deterministic
                u = (idx.astype(np.uint64)
                     * np.uint64(0x9E3779B97F4A7C15)) >> np.uint64(33)
                span = max(hi - lo + 1, 1)
                out[name] = (lo + (u % np.uint64(span)).astype(np.int64)) \
                    .astype(dtype)
            else:
                out[name] = (start + idx).astype(dtype)
        return out

    return gen


def instantiate_source(env, entry: CatalogTable):
    """Build a DataStream for a spec-backed catalog table in ``env``
    (reference FactoryUtil.createDynamicTableSource)."""
    opts = entry.options
    connector = opts.get("connector")
    if connector is None:
        raise SqlError(f"table {entry.name!r} has no 'connector' option")
    ws = _watermark_strategy(entry)
    if connector == "datagen":
        count = opts.get("number-of-rows")
        rate = opts.get("rows-per-second")
        return env.datagen(
            _datagen_fn(entry.schema, opts), entry.schema,
            count=int(count) if count else None,
            rate_per_sec=float(rate) if rate else None,
            timestamp_column=entry.watermark_col,
            watermark_strategy=ws, name=entry.name)
    if connector == "filesystem":
        from ..connectors.file import FileSource
        src = FileSource(opts["path"], _format(opts, entry.schema))
        return env.from_source(src, ws, entry.name)
    if connector == "log":
        from ..connectors.log import LogSource
        fmt = _format(opts, entry.schema)
        if getattr(fmt, "binary", False):
            raise SqlError("log topics carry text lines; use csv|json "
                           f"(table {entry.name!r})")
        src = LogSource(_broker(opts.get("broker", "default"),
                        config=env.config),
                        opts["topic"], fmt,
                        bounded=opts.get("bounded", "false") == "true",
                        starting_offsets=opts.get("scan.startup.mode",
                                                  "earliest"))
        return env.from_source(src, ws, entry.name)
    if connector == "socket":
        from ..connectors.socket import SocketSource
        if (len(entry.schema) != 1
                or entry.schema.fields[0].dtype is not object):
            raise SqlError("socket tables carry newline-delimited text: "
                           "declare exactly one STRING column")
        src = SocketSource(opts.get("hostname", "127.0.0.1"),
                           int(opts["port"]), entry.schema)
        return env.from_source(src, ws, entry.name)
    plugin = _PLUGIN_CONNECTORS.get(connector)
    if plugin is not None and plugin.get("source") is not None:
        return plugin["source"](env, entry)
    raise SqlError(f"unknown connector {connector!r} for source table "
                   f"{entry.name!r}")


def instantiate_sink(entry: CatalogTable, config=None):
    """Build a Sink (or SinkFunction) for INSERT INTO's target
    (reference FactoryUtil.createDynamicTableSink). ``config`` feeds the
    cluster-secret resolution of network-backed connectors."""
    opts = entry.options
    connector = opts.get("connector")
    if connector == "filesystem":
        from ..connectors.file import FileSink
        return FileSink(opts["path"], _format(opts, entry.schema))
    if connector == "log":
        from ..connectors.log import LogSink
        fmt = _format(opts, entry.schema)
        if getattr(fmt, "binary", False):
            raise SqlError("log topics carry text lines; use csv|json "
                           f"(table {entry.name!r})")
        broker = _broker(opts.get("broker", "default"), config=config)
        broker.create_topic(opts["topic"])
        return LogSink(broker, opts["topic"], fmt)
    if connector == "blackhole":
        from ..core.functions import SinkFunction

        class _BlackHole(SinkFunction):
            def invoke_batch(self, batch):
                return True

        return _BlackHole()
    if connector == "print":
        from ..core.functions import SinkFunction

        class _Print(SinkFunction):
            def invoke_batch(self, batch):
                for row in batch.iter_rows():
                    print(row)
                return True

        return _Print()
    plugin = _PLUGIN_CONNECTORS.get(connector)
    if plugin is not None and plugin.get("sink") is not None:
        return plugin["sink"](entry)
    raise SqlError(f"unknown connector {connector!r} for sink table "
                   f"{entry.name!r}")
