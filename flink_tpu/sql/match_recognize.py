"""MATCH_RECOGNIZE lowering: SQL row-pattern recognition onto the CEP NFA.

Reference: flink-table's match-recognize support compiles the SQL:2016
clause into the flink-cep operator (StreamExecMatch ->
CepOperator; MATCH_RECOGNIZE docs in dev/table/sql/queries/match_recognize)
— the same lowering happens here against cep/pattern.py + cep/operator.py:

* PATTERN variables become NFA stages with STRICT contiguity (row pattern
  matching is over consecutive rows per partition), quantifiers ``+ * ?``
  map to one_or_more/optional loops with ``consecutive()`` inner
  contiguity and SQL's default greediness;
* DEFINE clauses become stage conditions; references to OTHER pattern
  variables (``B.v > A.v``) need the partial match's history, so they
  lower to ``where_with_history`` (the IterativeCondition analog);
* MEASURES evaluate over the completed match: ``FIRST(X.c)``/``LAST(X.c)``
  /``X.c`` (= LAST) plus arithmetic; output schema = partition columns +
  measures;
* AFTER MATCH SKIP PAST LAST ROW is the NFA's SKIP_PAST_LAST_EVENT
  strategy; SKIP TO NEXT ROW is the NFA's default (every row may start a
  match).

Expressions evaluate per ROW here (a match is a handful of events), unlike
the planner's vectorized column programs — pattern matching is inherently
sequential, which is also why the reference runs it in flink-cep rather
than generated columnar code.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ..cep import Pattern
from ..cep.nfa import SKIP_PAST_LAST_EVENT, SKIP_TO_NEXT_ROW
from ..core.records import Schema
from .expressions import (
    BinaryOp, CaseWhen, Column, Expr, FuncCall, Literal, UnaryOp,
)
from .parser import MatchRecognize, SqlError

__all__ = ["plan_match_recognize"]


# -- scalar expression evaluation -------------------------------------------

def _binop(op: str, a, b):
    if a is None or b is None:
        return None                      # SQL three-valued: unknown
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return a / b if b else None
    if op == "%":
        return a % b if b else None
    if op == "=":
        return a == b
    if op in ("<>", "!="):
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    if op == "AND":
        return bool(a) and bool(b)
    if op == "OR":
        return bool(a) or bool(b)
    raise SqlError(f"MATCH_RECOGNIZE: unsupported operator {op!r}")


def _eval(e: Expr, resolve: Callable[[Optional[str], str, str], Any]) -> Any:
    """``resolve(var_or_None, column, mode)`` fetches a value; mode is
    "last" or "first"."""
    if isinstance(e, Literal):
        return e.value
    if isinstance(e, Column):
        return resolve(e.table, e.name, "last")
    if isinstance(e, BinaryOp):
        return _binop(e.op.upper(), _eval(e.left, resolve),
                      _eval(e.right, resolve))
    if isinstance(e, UnaryOp):
        v = _eval(e.operand, resolve)
        if v is None:
            return None
        if e.op.upper() == "NOT":
            return not v
        if e.op == "-":
            return -v
        raise SqlError(f"MATCH_RECOGNIZE: unary {e.op!r} unsupported")
    if isinstance(e, FuncCall):
        fname = e.name.upper()
        if fname in ("FIRST", "LAST"):
            if len(e.args) != 1 or not isinstance(e.args[0], Column) \
                    or e.args[0].table is None:
                raise SqlError(f"{fname}() takes one VAR.column argument")
            c = e.args[0]
            return resolve(c.table, c.name, fname.lower())
        raise SqlError(f"MATCH_RECOGNIZE: function {fname!r} unsupported "
                       "(FIRST/LAST)")
    if isinstance(e, CaseWhen):
        for cond, then in e.branches:
            if _eval(cond, resolve):
                return _eval(then, resolve)
        return _eval(e.default, resolve) if e.default is not None \
            else None
    raise SqlError(f"MATCH_RECOGNIZE: unsupported expression {type(e).__name__}")


def _define_predicate(var: str, expr: Expr):
    """DEFINE var AS expr -> condition over (event, history)."""

    def pred(event: dict, by_name: dict) -> bool:
        def resolve(qual: Optional[str], col: str, mode: str):
            if qual is None or qual == var:
                # the current row is provisionally mapped to var: LAST(var)
                # IS the current row; FIRST(var) is the first already-
                # captured row, falling back to the current one (SQL:2016
                # running semantics, matching the reference)
                if mode == "first":
                    events = by_name.get(var)
                    if events:
                        return events[0].get(col)
                return event.get(col)
            events = by_name.get(qual)
            if not events:
                return None              # nothing captured yet -> unknown
            row = events[0] if mode == "first" else events[-1]
            return row.get(col)

        return bool(_eval(expr, resolve))

    return pred


def _uses_history(var: str, e: Expr) -> bool:
    if isinstance(e, Column):
        return e.table is not None and e.table != var
    if isinstance(e, BinaryOp):
        return _uses_history(var, e.left) or _uses_history(var, e.right)
    if isinstance(e, UnaryOp):
        return _uses_history(var, e.operand)
    if isinstance(e, FuncCall):
        if e.name.upper() == "FIRST":
            return True   # FIRST of the OWN variable reads captured rows
        return any(_uses_history(var, a) for a in e.args)
    if isinstance(e, CaseWhen):
        return (any(_uses_history(var, c) or _uses_history(var, t)
                    for c, t in e.branches)
                or (e.default is not None
                    and _uses_history(var, e.default)))
    return False


def _measure_fn(measures: list, partition_by: list):
    """Match -> output row of partition values + measure values."""

    def compute(match) -> tuple:
        events = match.events if hasattr(match, "events") else match

        def resolve(qual: Optional[str], col: str, mode: str):
            if qual is None:
                raise SqlError(
                    f"MEASURES column {col!r} must be qualified with a "
                    "pattern variable (e.g. A.{col})")
            rows = events.get(qual)
            if not rows:
                return None
            row = rows[0] if mode == "first" else rows[-1]
            return row.get(col)

        first_var_rows = next((v for v in events.values() if v), None)
        out = []
        for col in partition_by:
            out.append(first_var_rows[0].get(col)
                       if first_var_rows else None)
        for expr, _alias in measures:
            out.append(_eval(expr, resolve))
        return tuple(out)

    return compute


def _build_pattern(mr: MatchRecognize) -> Pattern:
    pat: Optional[Pattern] = None
    for i, (var, quant) in enumerate(mr.pattern):
        if pat is None:
            pat = Pattern.begin(var)     # first stage: match may start at
        else:                            # any row (relaxed vs stream head)
            pat = pat.next(var)          # row patterns are consecutive
        if quant == "+":
            # NOT .greedy(): the NFA has no backtracking, so a greedy loop
            # that swallows a row the NEXT variable needed would kill the
            # match SQL semantics produce. Branching TAKE/PROCEED explores
            # both; the NFA's greedy_per_start deferral then releases the
            # LONGEST completed match per start row — SQL greediness via
            # deferral instead of backtracking.
            pat.one_or_more().consecutive()
        elif quant == "*":
            pat.times_or_more(0).optional().consecutive()
        elif quant == "?":
            pat.optional()
        define = mr.defines.get(var)
        if define is not None:
            if _uses_history(var, define):
                pat.where_with_history(_define_predicate(var, define))
            else:
                pred = _define_predicate(var, define)
                pat.where(lambda e, _p=pred: _p(e, {}))
        # no DEFINE: variable matches any row (SQL default)
    if mr.within_ms is not None:
        pat.within(mr.within_ms)
    return pat


def plan_match_recognize(mr: MatchRecognize, stream, in_schema: Schema,
                         env):
    """Lower the clause onto the input DataStream; returns the derived
    stream with ``_sql_schema`` = partition columns + measures."""
    from ..cep import PatternStream

    for col in mr.partition_by + [mr.order_by]:
        if col not in in_schema:
            raise SqlError(f"MATCH_RECOGNIZE: column {col!r} not in input "
                           f"schema {list(in_schema.names)}")
    if not mr.partition_by:
        raise SqlError("MATCH_RECOGNIZE needs PARTITION BY (the keyed "
                       "contract of the CEP operator)")
    if len(mr.partition_by) > 1:
        raise SqlError("MATCH_RECOGNIZE supports one PARTITION BY column")
    out_fields = [(c, in_schema.field(c).dtype) for c in mr.partition_by]
    for expr, alias in mr.measures:
        # measure dtype: the referenced column's dtype when directly
        # resolvable, else float64 (arithmetic)
        dtype: Any = np.float64
        base = expr
        if isinstance(base, FuncCall) and base.args:
            base = base.args[0]
        if isinstance(base, Column) and base.name in in_schema:
            dtype = in_schema.field(base.name).dtype
        out_fields.append((alias, dtype))
    out_schema = Schema(out_fields)

    pattern = _build_pattern(mr)
    skip = (SKIP_PAST_LAST_EVENT if mr.after_match == "SKIP PAST LAST ROW"
            else SKIP_TO_NEXT_ROW)
    ps = PatternStream(stream, pattern, mr.partition_by[0],
                       skip_strategy=skip, greedy_per_start=True,
                       order_column=mr.order_by)
    out = ps.select(_measure_fn(mr.measures, mr.partition_by), out_schema)
    out._sql_schema = out_schema
    return out
