"""OVER aggregation: per-row running aggregates over a partition.

Analog of the reference's StreamExecOverAggregate + table-runtime
operators/over/ (RowTimeRangeUnboundedPrecedingFunction et al.): every input
row is emitted once, extended with aggregate values computed over the
partition's rows from UNBOUNDED PRECEDING (or a ROWS window of size n) up to
and including the current row, ordered by event time.

TPU-first shape: a batch is sorted by (partition, ts) once, each partition
run's aggregates computed as vectorized prefix scans (np.cumsum / running
min-max), and only one state merge per partition carries the running
accumulator across batches. Append-only input (the reference restricts OVER
to append-only streams too).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..core.keygroups import assign_to_key_group
from ..core.records import RecordBatch, Schema
from ..runtime.operators.base import OneInputOperator
from .group_agg import SqlAggSpec

__all__ = ["OverAggOperator"]


class OverAggOperator(OneInputOperator):
    """Unbounded-preceding OVER aggregation, one partition key column."""

    def __init__(self, key_column: str, aggs: Sequence[SqlAggSpec],
                 rows_window: Optional[int] = None, name: str = "OverAgg"):
        super().__init__(name)
        self.key_column = key_column
        self.aggs = list(aggs)
        self.rows_window = rows_window  # None = UNBOUNDED PRECEDING
        # kg -> key -> accumulator dict per agg index
        self._state: dict[int, dict[Any, list]] = {}
        # ROWS window needs the trailing rows_window-1 values per agg
        self._tails: dict[int, dict[Any, list]] = {}
        self._out_schema: Optional[Schema] = None

    def _init_acc(self) -> list:
        acc = []
        for a in self.aggs:
            if a.kind == "count":
                acc.append(0.0)
            elif a.kind in ("sum", "avg"):
                acc.append([0.0, 0.0])  # sum, count
            elif a.kind == "min":
                acc.append(np.inf)
            else:
                acc.append(-np.inf)
        return acc

    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        if self._out_schema is None:
            self._out_schema = Schema(
                [(f.name, f.dtype) for f in batch.schema.fields]
                + [(a.out_name, np.float64) for a in self.aggs])
        keys = batch.column(self.key_column)
        ts = batch.timestamps
        # stable sort by (key-run, ts): group rows per key, keep time order
        from .group_agg import _unique_inverse
        uniq, inverse = _unique_inverse(keys)
        order = np.lexsort((ts, inverse))
        n = batch.n
        agg_out = np.zeros((n, len(self.aggs)), np.float64)
        sorted_inv = inverse[order]
        starts = np.searchsorted(sorted_inv, np.arange(len(uniq)))
        ends = np.append(starts[1:], n)
        agg_cols = [None if a.field is None
                    else batch.column(a.field).astype(np.float64)
                    for a in self.aggs]

        for gi in range(len(uniq)):
            key = uniq[gi]
            key = key.item() if isinstance(key, np.generic) else key
            kg = assign_to_key_group(key, self.ctx.max_parallelism)
            idx = order[starts[gi]:ends[gi]]
            m = len(idx)
            if self.rows_window is None:
                acc = self._state.setdefault(kg, {}).get(key)
                if acc is None:
                    acc = self._init_acc()
                self._unbounded_run(acc, idx, m, agg_cols, agg_out)
                self._state[kg][key] = acc
            else:
                # ROWS windows only need the trailing values, no accumulator
                tail = self._tails.setdefault(kg, {}).setdefault(
                    key, [[] for _ in self.aggs])
                self._rows_run(tail, idx, m, agg_cols, agg_out)
        out_cols = {f.name: batch.column(f.name)
                    for f in batch.schema.fields}
        for j, a in enumerate(self.aggs):
            out_cols[a.out_name] = agg_out[:, j]
        self.output.emit(RecordBatch(self._out_schema, out_cols, ts))

    def _unbounded_run(self, acc: list, idx: np.ndarray, m: int,
                       agg_cols: list, agg_out: np.ndarray) -> None:
        for j, a in enumerate(self.aggs):
            if a.kind == "count":
                vals = np.ones(m)
                run = acc[j] + np.cumsum(vals)
                acc[j] = float(run[-1])
                agg_out[idx, j] = run
            elif a.kind in ("sum", "avg"):
                vals = agg_cols[j][idx]
                run_sum = acc[j][0] + np.cumsum(vals)
                run_cnt = acc[j][1] + np.arange(1, m + 1)
                acc[j][0] = float(run_sum[-1])
                acc[j][1] = float(run_cnt[-1])
                agg_out[idx, j] = (run_sum if a.kind == "sum"
                                   else run_sum / run_cnt)
            elif a.kind == "min":
                vals = np.minimum.accumulate(agg_cols[j][idx])
                run = np.minimum(acc[j], vals)
                acc[j] = float(run[-1])
                agg_out[idx, j] = run
            else:
                vals = np.maximum.accumulate(agg_cols[j][idx])
                run = np.maximum(acc[j], vals)
                acc[j] = float(run[-1])
                agg_out[idx, j] = run

    def _rows_run(self, tail: list, idx: np.ndarray, m: int,
                  agg_cols: list, agg_out: np.ndarray) -> None:
        """ROWS BETWEEN n-1 PRECEDING AND CURRENT ROW via a per-key tail of
        the last n-1 values."""
        w = self.rows_window
        for j, a in enumerate(self.aggs):
            vals = (np.ones(m) if a.field is None and a.kind == "count"
                    else agg_cols[j][idx])
            full = np.concatenate([np.asarray(tail[j], np.float64), vals])
            k = len(tail[j])
            for p in range(m):
                lo = max(0, k + p - w + 1)
                window = full[lo:k + p + 1]
                if a.kind == "count":
                    agg_out[idx[p], j] = len(window)
                elif a.kind == "sum":
                    agg_out[idx[p], j] = window.sum()
                elif a.kind == "avg":
                    agg_out[idx[p], j] = window.mean()
                elif a.kind == "min":
                    agg_out[idx[p], j] = window.min()
                else:
                    agg_out[idx[p], j] = window.max()
            tail[j] = list(full[-(w - 1):]) if w > 1 else []

    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"keyed": {"backend": {
            "over": {kg: {k: _copy_acc(a) for k, a in m.items()}
                     for kg, m in self._state.items()},
            "over-tails": {kg: {k: [list(t) for t in ts]
                                for k, ts in m.items()}
                           for kg, m in self._tails.items()}}}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        for snap in keyed_snapshots:
            table = snap.get("backend", {})
            for kg, entries in table.get("over", {}).items():
                if kg in self.ctx.key_group_range:
                    self._state.setdefault(kg, {}).update(
                        {k: _copy_acc(a) for k, a in entries.items()})
            for kg, entries in table.get("over-tails", {}).items():
                if kg in self.ctx.key_group_range:
                    self._tails.setdefault(kg, {}).update(
                        {k: [list(t) for t in ts]
                         for k, ts in entries.items()})


def _copy_acc(acc: list) -> list:
    return [list(a) if isinstance(a, list) else a for a in acc]
