"""TableEnvironment: the SQL entry point.

Analog of the reference's TableEnvironment
(flink-table-api-java internal/TableEnvironmentImpl.java:145 —
executeSql:727, executeInternal:839) fused with its
StreamTableEnvironment bridge (from_data_stream/to_data_stream/
to_changelog_stream): a catalog of named tables over DataStreams, a parser +
planner, and result collection.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..api.datastream import DataStream
from ..api.environment import StreamExecutionEnvironment
from ..core.config import Configuration
from ..core.records import RecordBatch, Schema
from . import rowkind as rk
from .ddl import (
    Catalog, CatalogTable, CreateTableStmt, CreateViewStmt, DescribeStmt,
    DropStmt, ExplainStmt, InsertStmt, ShowCreateStmt, ShowTablesStmt,
    ShowViewsStmt, dtype_to_sql_type, instantiate_sink, instantiate_source,
    parse_statement, sql_type_to_dtype,
)
from .parser import parse
from .planner import PlanError, plan

__all__ = ["TableEnvironment", "Table", "TableResult"]


class Table:
    """A named or derived table: a DataStream + schema pair."""

    def __init__(self, t_env: "TableEnvironment", stream: DataStream,
                 schema: Schema):
        self._t_env = t_env
        self.stream = stream
        self.schema = schema

    def to_data_stream(self) -> DataStream:
        return self.stream

    def execute(self, timeout: Optional[float] = 120.0) -> "TableResult":
        return self._t_env._execute_table(self, timeout)


class TableResult:
    """Materialized query result (reference TableResult#collect)."""

    def __init__(self, schema: Schema, rows: list):
        self.schema = schema
        self._rows = rows

    def collect(self) -> list:
        return list(self._rows)

    def collect_final(self) -> list:
        """Fold the changelog: apply +I/+U/-U/-D and return the final rows
        (order of last insertion)."""
        if rk.ROWKIND_COLUMN not in self.schema:
            return list(self._rows)
        kind_idx = self.schema.index_of(rk.ROWKIND_COLUMN)
        alive: dict[tuple, int] = {}
        order: list[tuple] = []
        for row in self._rows:
            data = tuple(v for i, v in enumerate(row) if i != kind_idx)
            kind = row[kind_idx]
            if kind in (int(rk.UPDATE_BEFORE), int(rk.DELETE)):
                m = alive.get(data, 0) - 1
                if m <= 0:
                    alive.pop(data, None)
                else:
                    alive[data] = m
            else:
                alive[data] = alive.get(data, 0) + 1
                order.append(data)
        seen: set = set()
        out: list[tuple] = []
        for data in reversed(order):
            if data in alive and data not in seen:
                out.extend([data] * alive[data])
                seen.add(data)
        out.reverse()
        return out

    def print(self) -> None:
        names = self.schema.names
        print(" | ".join(names))
        for row in self._rows:
            print(" | ".join(str(v) for v in row))


class TableEnvironment:
    def __init__(self, env: Optional[StreamExecutionEnvironment] = None):
        self.env = env or StreamExecutionEnvironment()
        self._catalog: dict[str, tuple[DataStream, Schema]] = {}
        # DDL catalog: connector specs + views, re-plannable into a fresh
        # execution environment per query (reference GenericInMemoryCatalog)
        self.catalog = Catalog()

    @staticmethod
    def create(env: Optional[StreamExecutionEnvironment] = None
               ) -> "TableEnvironment":
        return TableEnvironment(env)

    # -- catalog -----------------------------------------------------------
    def create_temporary_view(self, name: str, stream: DataStream,
                              schema: Optional[Schema] = None) -> None:
        """Register a DataStream as a queryable table
        (reference createTemporaryView)."""
        if schema is None:
            schema = getattr(stream.transformation, "schema", None) \
                or getattr(stream, "_sql_schema", None)
            if schema is None:
                raise ValueError(
                    f"cannot infer schema for view {name!r}; pass schema=")
        self._catalog[name.lower()] = (stream, schema)

    def from_data_stream(self, stream: DataStream,
                         schema: Optional[Schema] = None) -> Table:
        if schema is None:
            schema = getattr(stream.transformation, "schema", None)
        return Table(self, stream, schema)

    def _resolve(self, name: str) -> tuple[DataStream, Schema]:
        return self._make_resolver(self.env)(name)

    def _make_resolver(self, env: StreamExecutionEnvironment):
        """Name resolution for one query: bound streams as-is; catalog
        specs instantiated into ``env`` (cached so a self-join shares one
        source); views re-planned recursively."""
        instantiated: dict[str, tuple[DataStream, Schema]] = {}

        def resolve(name: str) -> tuple[DataStream, Schema]:
            key = name.lower()
            bound = self._catalog.get(key)
            if bound is not None:
                return bound
            if key in instantiated:
                return instantiated[key]
            entry = self.catalog.get(key)
            if entry is None:
                raise PlanError(
                    f"table {name!r} not found; registered: "
                    f"{sorted(set(self._catalog) | set(self.catalog.names()))}")
            if entry.kind == "view":
                stream = plan(entry.view_select, resolve, env)
                out = (stream, stream._sql_schema)
            else:
                out = (instantiate_source(env, entry), entry.schema)
            instantiated[key] = out
            return out

        return resolve

    def _fresh_env(self) -> StreamExecutionEnvironment:
        """Spec-backed queries get their own execution environment (same
        config), so one TableEnvironment can run many statements without
        re-executing earlier pipelines. Queries over bound user streams
        must keep the user's env."""
        if self._catalog:
            return self.env
        return StreamExecutionEnvironment(
            Configuration(dict(self.env.config._data)))

    # -- SQL ---------------------------------------------------------------
    def sql_query(self, sql: str) -> Table:
        stmt = parse(sql)
        env = self._fresh_env()
        out = plan(stmt, self._make_resolver(env), env)
        return Table(self, out, out._sql_schema)

    def execute_sql(self, sql: str,
                    timeout: Optional[float] = 120.0) -> TableResult:
        """Route one statement: queries plan+execute; DDL mutates the
        catalog (reference TableEnvironmentImpl.executeSql:727)."""
        stmt = parse_statement(sql)
        if isinstance(stmt, CreateTableStmt):
            schema = Schema([(c, sql_type_to_dtype(t))
                             for c, t in stmt.columns])
            self.catalog.create(
                CatalogTable(stmt.name, "spec", schema, stmt.options,
                             stmt.watermark_col, stmt.watermark_delay_ms),
                if_not_exists=stmt.if_not_exists)
            return self._ok()
        if isinstance(stmt, CreateViewStmt):
            self.catalog.create(
                CatalogTable(stmt.name, "view", view_select=stmt.select))
            return self._ok()
        if isinstance(stmt, DropStmt):
            # temporary views registered through create_temporary_view live
            # in _catalog; SHOW/resolve and DROP must agree on both stores
            if stmt.name.lower() in self._catalog:
                del self._catalog[stmt.name.lower()]
                return self._ok()
            self.catalog.drop(stmt.name, stmt.kind, stmt.if_exists)
            return self._ok()
        if isinstance(stmt, ShowTablesStmt):
            names = sorted(set(self.catalog.names())
                           | set(self._catalog))
            return TableResult(Schema([("table name", object)]),
                               [(n,) for n in names])
        if isinstance(stmt, ShowViewsStmt):
            views = sorted(
                {n for n in self.catalog.names()
                 if self.catalog.get(n).kind == "view"}
                | set(self._catalog))
            return TableResult(Schema([("view name", object)]),
                               [(n,) for n in views])
        if isinstance(stmt, ShowCreateStmt):
            return self._show_create(stmt.name)
        if isinstance(stmt, DescribeStmt):
            entry = self.catalog.get(stmt.name)
            if entry is not None and entry.schema is not None:
                schema = entry.schema
            elif entry is not None and entry.kind == "view":
                # derive the view's schema by planning it (no execution)
                env = self._fresh_env()
                schema = plan(entry.view_select,
                              self._make_resolver(env), env)._sql_schema
            elif stmt.name.lower() in self._catalog:
                schema = self._catalog[stmt.name.lower()][1]
            else:
                raise PlanError(f"table {stmt.name!r} not found")
            return TableResult(
                Schema([("name", object), ("type", object)]),
                [(f.name, dtype_to_sql_type(f.dtype))
                 for f in schema.fields])
        if isinstance(stmt, ExplainStmt):
            return self._explain(stmt)
        if isinstance(stmt, InsertStmt):
            return self._execute_insert(stmt, timeout)
        # plain query
        env = self._fresh_env()
        out = plan(stmt, self._make_resolver(env), env)
        return Table(self, out, out._sql_schema).execute(timeout)

    def _validate_insert(self, stmt: InsertStmt, env) -> tuple:
        """Shared by execution AND EXPLAIN, so EXPLAIN surfaces the same
        errors the real INSERT would (target kind, changelog, arity).
        Returns (target entry, planned stream)."""
        target = self.catalog.get(stmt.target)
        if target is None:
            raise PlanError(f"sink table {stmt.target!r} not found")
        if target.kind != "spec":
            raise PlanError(f"cannot INSERT INTO {target.kind} "
                            f"{stmt.target!r}; target must be a connector-"
                            f"backed table")
        stream = plan(stmt.select, self._make_resolver(env), env)
        out_schema = stream._sql_schema
        if rk.ROWKIND_COLUMN in out_schema:
            raise PlanError(
                f"INSERT INTO {stmt.target}: the query produces a "
                "retracting changelog; only append-only queries can feed "
                "a table sink (aggregate before inserting or collect the "
                "result instead)")
        if len(out_schema) != len(target.schema):
            raise PlanError(
                f"INSERT INTO {stmt.target}: query produces "
                f"{len(out_schema)} columns, table has "
                f"{len(target.schema)}")
        return target, stream

    def _execute_insert(self, stmt: InsertStmt,
                        timeout: Optional[float]) -> TableResult:
        """INSERT INTO sink_table SELECT ... (reference executeInternal
        with a ModifyOperation -> DynamicTableSink)."""
        env = self._fresh_env()
        target, stream = self._validate_insert(stmt, env)
        out_schema = stream._sql_schema
        # map query columns to the TARGET's names positionally (reference
        # maps insert columns by position): formats like json encode field
        # names, so aliased query outputs must be renamed before the sink
        target_schema = target.schema
        src_names = out_schema.names
        # rebuild batches whenever names OR dtypes differ: RecordBatch
        # construction against the target schema both renames positionally
        # and coerces column dtypes to the sink's declared types
        if out_schema.fields != target_schema.fields:
            def rename(batch: RecordBatch):
                cols = {t: batch.columns[s]
                        for s, t in zip(src_names, target_schema.names)}
                return RecordBatch(target_schema, cols, batch.timestamps)

            from ..runtime.operators.simple import BatchFnOperator
            stream = stream.transform(
                "InsertRename",
                lambda: BatchFnOperator(rename, "InsertRename"))
        sink = instantiate_sink(target, config=stream.env.config)
        rows = _CountingSink()
        stream.add_sink(rows.wrap(sink), f"insert-{stmt.target}")
        stream.env.execute(f"insert-{stmt.target}", timeout=timeout)
        return TableResult(Schema([("rows", np.int64)], ), [(rows.count,)])

    def _execute_table(self, table: Table,
                       timeout: Optional[float]) -> TableResult:
        from ..connectors.core import CollectSink
        sink = CollectSink()
        table.stream.add_sink(sink, "SqlCollect")
        # execute on the env the query was PLANNED into (a fresh one for
        # spec-backed queries, the user's for bound streams)
        table.stream.env.execute("sql-query", timeout=timeout)
        return TableResult(table.schema, sink.rows)

    def _explain(self, stmt: ExplainStmt) -> TableResult:
        """EXPLAIN <query | INSERT>: plan without executing and render the
        physical JobGraph — chained vertices, parallelism, exchanges
        (reference TableEnvironment.explainSql). The graph is built
        directly from the planned terminal transformation: nothing is
        registered on the (possibly user-owned) environment, so EXPLAIN
        never leaks sinks into a later execute()."""
        env = self._fresh_env()
        inner = stmt.select
        sink_line = None
        if isinstance(inner, InsertStmt):
            # same validation as execution: EXPLAIN must fail where the
            # real INSERT would (view target, arity, retracting query)
            target, stream = self._validate_insert(inner, env)
            sink_line = (f"sink: {inner.target} "
                         f"[{target.options.get('connector')}]")
        else:
            stream = plan(inner, self._make_resolver(env), env)
        from ..graph.stream_graph import build_job_graph, build_stream_graph
        sg = build_stream_graph([stream.transformation], env.config)
        jg = build_job_graph(sg, env.config, "explain")
        lines = ["== Physical Execution Plan =="]
        for vid, v in jg.vertices.items():
            lines.append(f"{vid}: {v.name} (parallelism={v.parallelism}, "
                         f"max={v.max_parallelism})")
            for e in jg.in_edges(vid):
                tag = " [feedback]" if e.feedback else ""
                lines.append(f"  <- {e.source_vertex} "
                             f"[{e.partitioner_name}]{tag}")
        if sink_line:
            lines.append(sink_line)
        return TableResult(Schema([("plan", object)]),
                           [(ln,) for ln in lines])

    def _show_create(self, name: str) -> "TableResult":
        """Reconstruct the DDL from the catalog entry (reference SHOW
        CREATE TABLE)."""
        entry = self.catalog.get(name)
        if entry is None or entry.kind != "spec":
            raise PlanError(
                f"SHOW CREATE TABLE: {name!r} is not a connector-backed "
                "table in the catalog")
        cols = [f"  {f.name} {dtype_to_sql_type(f.dtype)}"
                for f in entry.schema.fields]
        if entry.watermark_col:
            # MILLISECOND keeps the delay exact (float formatting would
            # silently round it on round-trip)
            cols.append(f"  WATERMARK FOR {entry.watermark_col} AS "
                        f"{entry.watermark_col} - INTERVAL "
                        f"'{entry.watermark_delay_ms}' MILLISECOND")

        def q(s: str) -> str:
            return str(s).replace("'", "''")   # SQL string escaping

        opts = ",\n".join(f"  '{q(k)}' = '{q(v)}'"
                          for k, v in sorted(entry.options.items()))
        ddl = (f"CREATE TABLE {entry.name} (\n" + ",\n".join(cols)
               + f"\n) WITH (\n{opts}\n)")
        return TableResult(Schema([("create statement", object)]),
                           [(ddl,)])

    @staticmethod
    def _ok() -> "TableResult":
        return TableResult(Schema([("result", object)]), [("OK",)])


class _CountingSink:
    """Wraps the target sink so INSERT INTO can report rows written."""

    def __init__(self):
        self.count = 0
        import threading
        self._lock = threading.Lock()

    def _add(self, n: int) -> None:
        with self._lock:
            self.count += n

    def wrap(self, sink):
        from ..connectors.core import Sink, SinkWriter
        from ..core.functions import SinkFunction

        outer = self
        if isinstance(sink, Sink):
            class _CountingWrapper(Sink):
                def create_writer(self, subtask_index: int) -> SinkWriter:
                    inner = sink.create_writer(subtask_index)

                    class _W(SinkWriter):
                        def write_batch(self, batch):
                            outer._add(batch.n)
                            return inner.write_batch(batch)

                        def flush(self):
                            inner.flush()

                        def prepare_commit(self, checkpoint_id):
                            inner.prepare_commit(checkpoint_id)

                        def commit(self, checkpoint_id):
                            inner.commit(checkpoint_id)

                        def snapshot(self):
                            return inner.snapshot()

                        def restore(self, state):
                            inner.restore(state)

                        def close(self):
                            inner.close()

                    return _W()

            return _CountingWrapper()

        class _CountingFn(SinkFunction):
            def invoke_batch(self, batch):
                outer._add(batch.n)
                return sink.invoke_batch(batch)

        return _CountingFn()
