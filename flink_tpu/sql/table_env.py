"""TableEnvironment: the SQL entry point.

Analog of the reference's TableEnvironment
(flink-table-api-java internal/TableEnvironmentImpl.java:145 —
executeSql:727, executeInternal:839) fused with its
StreamTableEnvironment bridge (from_data_stream/to_data_stream/
to_changelog_stream): a catalog of named tables over DataStreams, a parser +
planner, and result collection.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..api.datastream import DataStream
from ..api.environment import StreamExecutionEnvironment
from ..core.records import RecordBatch, Schema
from . import rowkind as rk
from .parser import parse
from .planner import PlanError, plan

__all__ = ["TableEnvironment", "Table", "TableResult"]


class Table:
    """A named or derived table: a DataStream + schema pair."""

    def __init__(self, t_env: "TableEnvironment", stream: DataStream,
                 schema: Schema):
        self._t_env = t_env
        self.stream = stream
        self.schema = schema

    def to_data_stream(self) -> DataStream:
        return self.stream

    def execute(self, timeout: Optional[float] = 120.0) -> "TableResult":
        return self._t_env._execute_table(self, timeout)


class TableResult:
    """Materialized query result (reference TableResult#collect)."""

    def __init__(self, schema: Schema, rows: list):
        self.schema = schema
        self._rows = rows

    def collect(self) -> list:
        return list(self._rows)

    def collect_final(self) -> list:
        """Fold the changelog: apply +I/+U/-U/-D and return the final rows
        (order of last insertion)."""
        if rk.ROWKIND_COLUMN not in self.schema:
            return list(self._rows)
        kind_idx = self.schema.index_of(rk.ROWKIND_COLUMN)
        alive: dict[tuple, int] = {}
        order: list[tuple] = []
        for row in self._rows:
            data = tuple(v for i, v in enumerate(row) if i != kind_idx)
            kind = row[kind_idx]
            if kind in (int(rk.UPDATE_BEFORE), int(rk.DELETE)):
                m = alive.get(data, 0) - 1
                if m <= 0:
                    alive.pop(data, None)
                else:
                    alive[data] = m
            else:
                alive[data] = alive.get(data, 0) + 1
                order.append(data)
        seen: set = set()
        out: list[tuple] = []
        for data in reversed(order):
            if data in alive and data not in seen:
                out.extend([data] * alive[data])
                seen.add(data)
        out.reverse()
        return out

    def print(self) -> None:
        names = self.schema.names
        print(" | ".join(names))
        for row in self._rows:
            print(" | ".join(str(v) for v in row))


class TableEnvironment:
    def __init__(self, env: Optional[StreamExecutionEnvironment] = None):
        self.env = env or StreamExecutionEnvironment()
        self._catalog: dict[str, tuple[DataStream, Schema]] = {}

    @staticmethod
    def create(env: Optional[StreamExecutionEnvironment] = None
               ) -> "TableEnvironment":
        return TableEnvironment(env)

    # -- catalog -----------------------------------------------------------
    def create_temporary_view(self, name: str, stream: DataStream,
                              schema: Optional[Schema] = None) -> None:
        """Register a DataStream as a queryable table
        (reference createTemporaryView)."""
        if schema is None:
            schema = getattr(stream.transformation, "schema", None) \
                or getattr(stream, "_sql_schema", None)
            if schema is None:
                raise ValueError(
                    f"cannot infer schema for view {name!r}; pass schema=")
        self._catalog[name.lower()] = (stream, schema)

    def from_data_stream(self, stream: DataStream,
                         schema: Optional[Schema] = None) -> Table:
        if schema is None:
            schema = getattr(stream.transformation, "schema", None)
        return Table(self, stream, schema)

    def _resolve(self, name: str) -> tuple[DataStream, Schema]:
        entry = self._catalog.get(name.lower())
        if entry is None:
            raise PlanError(f"table {name!r} not found; registered: "
                            f"{sorted(self._catalog)}")
        return entry

    # -- SQL ---------------------------------------------------------------
    def sql_query(self, sql: str) -> Table:
        stmt = parse(sql)
        out = plan(stmt, self._resolve, self.env)
        return Table(self, out, out._sql_schema)

    def execute_sql(self, sql: str,
                    timeout: Optional[float] = 120.0) -> TableResult:
        return self.sql_query(sql).execute(timeout)

    def _execute_table(self, table: Table,
                       timeout: Optional[float]) -> TableResult:
        from ..connectors.core import CollectSink
        sink = CollectSink()
        table.stream.add_sink(sink, "SqlCollect")
        self.env.execute("sql-query", timeout=timeout)
        return TableResult(table.schema, sink.rows)
