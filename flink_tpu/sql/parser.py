"""SQL parser: tokenizer + recursive descent over the streaming subset.

The analog of the reference's Calcite/JavaCC dialect (flink-sql-parser) for
the surface the planner supports:

    SELECT items FROM table_ref [WHERE e] [GROUP BY e, ...] [HAVING e]
        [ORDER BY e [ASC|DESC], ...] [LIMIT n]

``table_ref`` is a table name, a windowing TVF over one —
``TUMBLE(TABLE t, DESCRIPTOR(ts_col), INTERVAL '5' SECOND)`` /
``HOP(TABLE t, DESCRIPTOR(ts_col), INTERVAL slide, INTERVAL size)``
(FLIP-145 window TVFs; reference SqlWindowTableFunction) — or a
parenthesized subquery. Aggregates: COUNT(*)/COUNT/SUM/MIN/MAX/AVG
[DISTINCT]. No external parser dependency: the grammar is small enough that
a hand-rolled LL(1) parser is clearer than bundling a generator.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Union

from .expressions import (
    AggCall, BinaryOp, Cast, CaseWhen, Column, Expr, FuncCall, Literal, Star,
    UnaryOp,
)

__all__ = ["parse", "SelectStmt", "TableRef", "JoinClause", "WindowTVF",
           "MatchRecognize",
           "OrderItem", "SelectItem", "SqlError"]

_AGG_FUNCS = {"COUNT", "SUM", "MIN", "MAX", "AVG"}

_UNITS_MS = {
    "MILLISECOND": 1, "SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
    "DAY": 86_400_000,
}


class SqlError(ValueError):
    pass


@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass
class JoinClause:
    """FROM a JOIN b ON cond (reference SqlJoin). ``kind`` in
    INNER|LEFT|RIGHT|FULL; multi-way joins left-nest.
    ``temporal_time`` set => ``b FOR SYSTEM_TIME AS OF <expr>``: b is a
    versioned table and the join picks the version valid at the left
    row's time (reference SqlSnapshot -> StreamExecTemporalJoin)."""

    kind: str
    left: "FromClause"
    right: "FromClause"
    on: Expr
    temporal_time: Optional[Expr] = None


@dataclass
class WindowTVF:
    kind: str                   # "TUMBLE" | "HOP" | "CUMULATE"
    table: "FromClause"
    time_col: str
    size_ms: int
    slide_ms: Optional[int] = None   # HOP slide / CUMULATE step


@dataclass
class MatchRecognize:
    """MATCH_RECOGNIZE over a table (reference flink-table match-recognize
    -> flink-cep lowering; SQL:2016 row pattern recognition)."""

    table: "TableRef"
    partition_by: list          # [column name]
    order_by: str               # time attribute column
    measures: list              # [(Expr, alias)]
    pattern: list               # [(var, quantifier)] quantifier in
                                # {"", "+", "*", "?"} or (min, max|None)
    defines: dict               # var -> Expr
    after_match: str = "SKIP PAST LAST ROW"
    within_ms: Optional[int] = None
    alias: Optional[str] = None


@dataclass
class OrderItem:
    expr: Expr
    descending: bool = False


@dataclass
class SelectStmt:
    items: list
    from_: "FromClause"
    where: Optional[Expr] = None
    group_by: list = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: list = field(default_factory=list)
    limit: Optional[int] = None
    alias: Optional[str] = None  # derived-table alias: (SELECT ...) s


FromClause = Union[TableRef, WindowTVF, SelectStmt, "JoinClause",
                   "MatchRecognize"]


_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<num>\d+\.\d+|\d+)
    | (?P<str>'(?:[^']|'')*')
    | (?P<id>[A-Za-z_][A-Za-z_0-9]*)
    | (?P<op><=|>=|<>|!=|=|<|>|\(|\)|,|\*|\+|-|/|%|\.|\?)
    )""", re.VERBOSE)


def _tokenize(sql: str) -> list[tuple[str, str]]:
    tokens: list[tuple[str, str]] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            if sql[pos:].strip() == "" or sql[pos] == ";":
                break
            raise SqlError(f"cannot tokenize at: {sql[pos:pos+20]!r}")
        pos = m.end()
        if m.lastgroup == "num":
            tokens.append(("num", m.group("num")))
        elif m.lastgroup == "str":
            tokens.append(("str", m.group("str")[1:-1].replace("''", "'")))
        elif m.lastgroup == "id":
            tokens.append(("id", m.group("id")))
        else:
            tokens.append(("op", m.group("op")))
    tokens.append(("eof", ""))
    return tokens


class _Parser:
    def __init__(self, sql: str):
        self.toks = _tokenize(sql)
        self.i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self) -> tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        k, v = self.peek()
        return k == "id" and v.upper() in kws

    def eat_kw(self, kw: str) -> bool:
        if self.at_kw(kw):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            raise SqlError(f"expected {kw}, got {self.peek()[1]!r}")

    def eat_op(self, op: str) -> bool:
        k, v = self.peek()
        if k == "op" and v == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            raise SqlError(f"expected {op!r}, got {self.peek()[1]!r}")

    # -- grammar -----------------------------------------------------------
    def parse_select(self) -> SelectStmt:
        self.expect_kw("SELECT")
        items = [self.select_item()]
        while self.eat_op(","):
            items.append(self.select_item())
        self.expect_kw("FROM")
        from_ = self.from_clause()
        stmt = SelectStmt(items, from_)
        if self.eat_kw("WHERE"):
            stmt.where = self.expr()
        if self.eat_kw("GROUP"):
            self.expect_kw("BY")
            stmt.group_by = [self.expr()]
            while self.eat_op(","):
                stmt.group_by.append(self.expr())
        if self.eat_kw("HAVING"):
            stmt.having = self.expr()
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            stmt.order_by = [self.order_item()]
            while self.eat_op(","):
                stmt.order_by.append(self.order_item())
        if self.eat_kw("LIMIT"):
            k, v = self.next()
            if k != "num":
                raise SqlError("LIMIT expects a number")
            stmt.limit = int(v)
        return stmt

    def select_item(self) -> SelectItem:
        if self.eat_op("*"):
            return SelectItem(Star())
        e = self.expr()
        alias = None
        if self.eat_kw("AS"):
            k, v = self.next()
            if k != "id":
                raise SqlError("expected alias after AS")
            alias = v
        elif self.peek()[0] == "id" and not self.at_kw(
                "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT"):
            alias = self.next()[1]
        return SelectItem(e, alias)

    def order_item(self) -> OrderItem:
        e = self.expr()
        desc = False
        if self.eat_kw("DESC"):
            desc = True
        else:
            self.eat_kw("ASC")
        return OrderItem(e, desc)

    def from_clause(self) -> FromClause:
        left = self.from_primary()
        while True:
            kind = None
            if self.at_kw("JOIN"):
                kind = "INNER"
            elif self.at_kw("INNER"):
                self.next()
                kind = "INNER"
            elif self.at_kw("LEFT", "RIGHT", "FULL"):
                kind = self.next()[1].upper()
                self.eat_kw("OUTER")
            else:
                return left
            self.expect_kw("JOIN")
            right = self.from_primary()
            temporal_time = None
            if self.eat_kw("FOR"):
                # b FOR SYSTEM_TIME AS OF l.rowtime [AS alias]
                self.expect_kw("SYSTEM_TIME")
                self.expect_kw("AS")
                self.expect_kw("OF")
                temporal_time = self.expr()
                alias = self.maybe_alias()
                if alias is not None:
                    if isinstance(right, TableRef):
                        right.alias = alias
                    else:
                        raise SqlError(
                            "FOR SYSTEM_TIME alias requires a plain table")
            self.expect_kw("ON")
            cond = self.expr()
            left = JoinClause(kind, left, right, cond,
                              temporal_time=temporal_time)

    def from_primary(self) -> FromClause:
        if self.eat_op("("):
            inner = self.from_clause_inner()
            self.expect_op(")")
            alias = self.maybe_alias()
            if alias is not None and isinstance(inner, (TableRef, SelectStmt)):
                inner.alias = alias
            return inner
        k, v = self.peek()
        if k == "id" and v.upper() in ("TUMBLE", "HOP", "CUMULATE",
                               "SESSION"):
            return self.window_tvf()
        if k != "id":
            raise SqlError(f"expected table name, got {v!r}")
        self.next()
        if self.at_kw("MATCH_RECOGNIZE"):
            return self.match_recognize(TableRef(v))
        return TableRef(v, self.maybe_alias())

    def from_clause_inner(self) -> FromClause:
        if self.at_kw("SELECT"):
            return self.parse_select()
        if self.at_kw("TUMBLE", "HOP", "CUMULATE", "SESSION"):
            return self.window_tvf()
        if self.at_kw("TABLE"):
            self.next()
            k, v = self.next()
            if k != "id":
                raise SqlError("expected table name after TABLE")
            return TableRef(v)
        k, v = self.next()
        if k != "id":
            raise SqlError(f"expected table reference, got {v!r}")
        return TableRef(v)

    def maybe_alias(self) -> Optional[str]:
        if self.eat_kw("AS"):
            return self.next()[1]
        if (self.peek()[0] == "id"
                and not self.at_kw("WHERE", "GROUP", "HAVING", "ORDER",
                                   "LIMIT", "ON", "JOIN", "INNER", "LEFT",
                                   "RIGHT", "FULL", "OUTER", "FOR")):
            return self.next()[1]
        return None

    def window_tvf(self) -> WindowTVF:
        kind = self.next()[1].upper()
        self.expect_op("(")
        self.expect_kw("TABLE")
        k, tname = self.next()
        if k != "id":
            raise SqlError("expected table name after TABLE")
        self.expect_op(",")
        self.expect_kw("DESCRIPTOR")
        self.expect_op("(")
        k, time_col = self.next()
        if k != "id":
            raise SqlError("expected column in DESCRIPTOR")
        self.expect_op(")")
        self.expect_op(",")
        first = self.interval()
        slide = None
        size = first
        if self.eat_op(","):
            second = self.interval()
            slide, size = first, second
        self.expect_op(")")
        self.maybe_alias()
        if kind in ("TUMBLE", "SESSION"):
            if slide is not None:
                raise SqlError(
                    f"{kind} takes exactly one INTERVAL "
                    f"({'the gap' if kind == 'SESSION' else 'the size'}); "
                    "two intervals are HOP/CUMULATE syntax")
            # SESSION's single interval is the gap (reference SESSION TVF)
            return WindowTVF(kind, TableRef(tname), time_col, size)
        if slide is None:
            raise SqlError(
                f"{kind} takes two INTERVALs "
                f"({'slide, size' if kind == 'HOP' else 'step, size'})")
        return WindowTVF(kind, TableRef(tname), time_col, size, slide)

    def match_recognize(self, table: TableRef) -> MatchRecognize:
        """MATCH_RECOGNIZE ( PARTITION BY col ORDER BY col MEASURES ...
        [ONE ROW PER MATCH] [AFTER MATCH SKIP ...] PATTERN (A B+ C)
        [WITHIN INTERVAL ...] DEFINE var AS expr, ... )"""
        self.expect_kw("MATCH_RECOGNIZE")
        self.expect_op("(")
        partition_by: list[str] = []
        if self.eat_kw("PARTITION"):
            self.expect_kw("BY")
            partition_by.append(self._ident("PARTITION BY column"))
            while self.eat_op(","):
                partition_by.append(self._ident("PARTITION BY column"))
        self.expect_kw("ORDER")
        self.expect_kw("BY")
        order_by = self._ident("ORDER BY column")
        self.expect_kw("MEASURES")
        measures = [self._measure()]
        while self.eat_op(","):
            measures.append(self._measure())
        if self.eat_kw("ONE"):
            self.expect_kw("ROW")
            self.expect_kw("PER")
            self.expect_kw("MATCH")
        after = "SKIP PAST LAST ROW"
        if self.eat_kw("AFTER"):
            self.expect_kw("MATCH")
            self.expect_kw("SKIP")
            if self.eat_kw("PAST"):
                self.expect_kw("LAST")
                self.expect_kw("ROW")
            elif self.eat_kw("TO"):
                self.expect_kw("NEXT")
                self.expect_kw("ROW")
                after = "SKIP TO NEXT ROW"
            else:
                raise SqlError("AFTER MATCH SKIP supports PAST LAST ROW "
                               "and TO NEXT ROW")
        self.expect_kw("PATTERN")
        self.expect_op("(")
        pattern: list[tuple[str, Any]] = []
        while not self.eat_op(")"):
            var = self._ident("pattern variable")
            quant: Any = ""
            if self.eat_op("+"):
                quant = "+"
            elif self.eat_op("*"):
                quant = "*"
            elif self.eat_op("?"):
                quant = "?"
            pattern.append((var, quant))
        if not pattern:
            raise SqlError("empty PATTERN")
        within_ms = None
        if self.eat_kw("WITHIN"):
            within_ms = self.interval()
        self.expect_kw("DEFINE")
        defines: dict[str, Expr] = {}
        var = self._ident("DEFINE variable")
        self.expect_kw("AS")
        defines[var] = self.expr()
        while self.eat_op(","):
            var = self._ident("DEFINE variable")
            self.expect_kw("AS")
            defines[var] = self.expr()
        self.expect_op(")")
        alias = self.maybe_alias()
        known = {v for v, _ in pattern}
        for var in defines:
            if var not in known:
                raise SqlError(f"DEFINE references unknown pattern "
                               f"variable {var!r} (pattern: {sorted(known)})")

        def check_vars(e) -> None:
            if isinstance(e, Column) and e.table is not None \
                    and e.table not in known:
                raise SqlError(
                    f"MEASURES/DEFINE references unknown pattern variable "
                    f"{e.table!r} (pattern: {sorted(known)})")
            for attr in ("left", "right", "operand"):
                sub = getattr(e, attr, None)
                if sub is not None:
                    check_vars(sub)
            for sub in getattr(e, "args", ()) or ():
                check_vars(sub)
            for c, t in getattr(e, "branches", ()) or ():
                check_vars(c)
                check_vars(t)
            default = getattr(e, "default", None)
            if default is not None:
                check_vars(default)

        for m_expr, _alias in measures:
            check_vars(m_expr)
        for d_expr in defines.values():
            check_vars(d_expr)
        return MatchRecognize(table, partition_by, order_by, measures,
                              pattern, defines, after, within_ms, alias)

    def _ident(self, what: str) -> str:
        k, v = self.next()
        if k != "id":
            raise SqlError(f"expected {what}, got {v!r}")
        return v

    def _measure(self) -> tuple:
        e = self.expr()
        self.expect_kw("AS")
        return (e, self._ident("measure alias"))

    def interval(self) -> int:
        self.expect_kw("INTERVAL")
        k, v = self.next()
        if k == "str":
            amount = float(v)
        elif k == "num":
            amount = float(v)
        else:
            raise SqlError("INTERVAL expects a quoted number")
        k, unit = self.next()
        if k != "id" or unit.upper().rstrip("S") not in _UNITS_MS:
            raise SqlError(f"unknown interval unit {unit!r}")
        return int(amount * _UNITS_MS[unit.upper().rstrip("S")])

    # -- expressions (precedence: OR < AND < NOT < cmp < add < mul < unary)
    def expr(self) -> Expr:
        return self.or_expr()

    def or_expr(self) -> Expr:
        e = self.and_expr()
        while self.at_kw("OR"):
            self.next()
            e = BinaryOp("OR", e, self.and_expr())
        return e

    def and_expr(self) -> Expr:
        e = self.not_expr()
        while self.at_kw("AND"):
            self.next()
            e = BinaryOp("AND", e, self.not_expr())
        return e

    def not_expr(self) -> Expr:
        if self.at_kw("NOT"):
            self.next()
            return UnaryOp("NOT", self.not_expr())
        return self.cmp_expr()

    def cmp_expr(self) -> Expr:
        e = self.add_expr()
        if self.at_kw("BETWEEN"):
            self.next()
            lo = self.add_expr()
            self.expect_kw("AND")
            hi = self.add_expr()
            return BinaryOp("AND", BinaryOp(">=", e, lo),
                            BinaryOp("<=", e, hi))
        if self.at_kw("IN"):
            self.next()
            self.expect_op("(")
            opts = [self.expr()]
            while self.eat_op(","):
                opts.append(self.expr())
            self.expect_op(")")
            out: Expr = BinaryOp("=", e, opts[0])
            for o in opts[1:]:
                out = BinaryOp("OR", out, BinaryOp("=", e, o))
            return out
        k, v = self.peek()
        if k == "op" and v in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            return BinaryOp(v, e, self.add_expr())
        return e

    def add_expr(self) -> Expr:
        e = self.mul_expr()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("+", "-"):
                self.next()
                e = BinaryOp(v, e, self.mul_expr())
            else:
                return e

    def mul_expr(self) -> Expr:
        e = self.unary_expr()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("*", "/", "%"):
                self.next()
                e = BinaryOp(v, e, self.unary_expr())
            else:
                return e

    def unary_expr(self) -> Expr:
        if self.eat_op("-"):
            return UnaryOp("-", self.unary_expr())
        self.eat_op("+")
        return self.primary()

    def primary(self) -> Expr:
        k, v = self.peek()
        if k == "num":
            self.next()
            return Literal(float(v) if "." in v else int(v))
        if k == "str":
            self.next()
            return Literal(v)
        if self.eat_op("("):
            e = self.expr()
            self.expect_op(")")
            return e
        if k != "id":
            raise SqlError(f"unexpected token {v!r}")
        upper = v.upper()
        if upper == "CASE":
            return self.case_when()
        if upper == "CAST":
            self.next()
            self.expect_op("(")
            inner = self.expr()
            self.expect_kw("AS")
            tk, tv = self.next()
            if tk != "id":
                raise SqlError("expected type after CAST(expr AS")
            self.expect_op(")")
            return Cast(inner, tv)
        if upper == "TRUE":
            self.next()
            return Literal(True)
        if upper == "FALSE":
            self.next()
            return Literal(False)
        if upper == "NULL":
            self.next()
            return Literal(None)
        self.next()
        # function call?
        if self.eat_op("("):
            if upper in _AGG_FUNCS:
                distinct = self.eat_kw("DISTINCT")
                if self.eat_op("*"):
                    self.expect_op(")")
                    return AggCall("count", None, distinct)
                arg = self.expr()
                self.expect_op(")")
                return AggCall(upper.lower(), arg, distinct)
            args: list[Expr] = []
            if not self.eat_op(")"):
                args.append(self.expr())
                while self.eat_op(","):
                    args.append(self.expr())
                self.expect_op(")")
            return FuncCall(upper, tuple(args))
        # qualified name t.col: carry the qualifier for join resolution
        if self.eat_op("."):
            ck, cv = self.next()
            if ck != "id":
                raise SqlError("expected column after '.'")
            return Column(cv, table=v)
        return Column(v)

    def case_when(self) -> Expr:
        self.expect_kw("CASE")
        branches = []
        while self.eat_kw("WHEN"):
            cond = self.expr()
            self.expect_kw("THEN")
            branches.append((cond, self.expr()))
        default = self.expr() if self.eat_kw("ELSE") else None
        self.expect_kw("END")
        if not branches:
            raise SqlError("CASE needs at least one WHEN")
        return CaseWhen(tuple(branches), default)


def parse(sql: str) -> SelectStmt:
    p = _Parser(sql)
    stmt = p.parse_select()
    if p.peek()[0] != "eof":
        raise SqlError(f"trailing tokens at {p.peek()[1]!r}")
    return stmt
