"""SQL gateway: REST sessions + statement execution.

Analog of the reference's SQL gateway
(flink-table/flink-sql-gateway .../rest/SqlGatewayRestEndpoint.java:63 +
SqlGatewayServiceImpl): long-lived SESSIONS each own a TableEnvironment
(catalog state persists across statements), and clients drive them over
plain HTTP/JSON:

    POST   /v1/sessions                       -> {"session_id"}
    POST   /v1/sessions/{id}/statements       {"statement": "..."}
                                              -> {"columns", "rows"}
    GET    /v1/sessions/{id}                  -> session info
    DELETE /v1/sessions/{id}                  -> close
    GET    /v1/info                           -> gateway version info

Queries execute synchronously and return their FINAL table (changelog
folded) — the micro-batch model makes bounded SQL complete quickly, so
the reference's operation-handle polling collapses to one round trip.
Statement errors return 400 with the message; the session survives.

The transport carries only JSON — no pickle deserialization on this
surface (rows are rendered to JSON-safe scalars). That removes the
remote-code-execution vector of the intra-cluster control sockets, but it
does NOT make the gateway safe to expose: every caller gets full
unauthenticated SQL, and DDL can create filesystem-connector tables —
i.e. arbitrary file read/write as the server user. Keep the default
loopback bind; a non-loopback deployment needs authentication or
network-level access control in front.
"""

from __future__ import annotations

import json
import threading
import uuid
from typing import Any, Optional

from ..utils.httpd import ThreadedHTTPServer

__all__ = ["SqlGateway"]


def _json_safe(v: Any):
    import numpy as np

    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


class _Session:
    def __init__(self, state_backend: str = ""):
        from ..api.environment import StreamExecutionEnvironment
        from ..core.config import StateOptions
        from . import TableEnvironment

        self.env = StreamExecutionEnvironment()
        if state_backend:
            self.env.config.set(StateOptions.BACKEND, state_backend)
        self.t_env = TableEnvironment(self.env)
        self.lock = threading.Lock()  # one statement at a time per session


class SqlGateway:
    """Embeddable gateway server (also `flink-tpu sql-gateway`)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 state_backend: str = ""):
        self._sessions: dict[str, _Session] = {}
        self._lock = threading.Lock()
        self._backend = state_backend
        gateway = self

        import http.server

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length") or 0)
                if not n:
                    return {}
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                if parts[:2] == ["v1", "info"]:
                    return self._send(200, {"productName": "flink-tpu",
                                            "version": "0.1"})
                if len(parts) == 3 and parts[:2] == ["v1", "sessions"]:
                    sid = parts[2]
                    if sid in gateway._sessions:
                        return self._send(200, {"session_id": sid})
                    return self._send(404, {"error": "unknown session"})
                return self._send(404, {"error": "not found"})

            def do_POST(self):
                parts = self.path.strip("/").split("/")
                if parts == ["v1", "sessions"]:
                    sid = gateway.open_session()
                    return self._send(200, {"session_id": sid})
                if (len(parts) == 4 and parts[:2] == ["v1", "sessions"]
                        and parts[3] == "statements"):
                    sid = parts[2]
                    stmt = self._body().get("statement", "")
                    try:
                        out = gateway.execute(sid, stmt)
                    except KeyError:
                        return self._send(404,
                                          {"error": "unknown session"})
                    except Exception as e:
                        return self._send(400, {"error": str(e)})
                    return self._send(200, out)
                return self._send(404, {"error": "not found"})

            def do_DELETE(self):
                parts = self.path.strip("/").split("/")
                if len(parts) == 3 and parts[:2] == ["v1", "sessions"]:
                    gateway.close_session(parts[2])
                    return self._send(200, {"status": "closed"})
                return self._send(404, {"error": "not found"})

        self._server = ThreadedHTTPServer(Handler, port=port, host=host,
                                          name="sql-gateway")
        self.port: int = self._server.port

    # -- service -----------------------------------------------------------
    def open_session(self) -> str:
        sid = uuid.uuid4().hex[:16]
        with self._lock:
            self._sessions[sid] = _Session(self._backend)
        return sid

    def close_session(self, sid: str) -> None:
        with self._lock:
            self._sessions.pop(sid, None)

    def execute(self, sid: str, statement: str) -> dict:
        from . import rowkind as rk

        sess = self._sessions[sid]
        with sess.lock:
            res = sess.t_env.execute_sql(statement)
        names = [n for n in res.schema.names if n != rk.ROWKIND_COLUMN]
        rows = [[_json_safe(v) for v in r] for r in res.collect_final()]
        return {"columns": names, "rows": rows}

    def start(self) -> int:
        return self._server.start()

    def stop(self) -> None:
        self._server.stop()
        with self._lock:
            self._sessions.clear()
