"""Unbounded keyed GROUP BY aggregation with changelog output.

The analog of the reference table-runtime's GroupAggFunction
(flink-table-runtime operators/aggregate/GroupAggFunction.java:43,
processElement:125): per group key, maintain accumulators; on change emit
UPDATE_BEFORE with the previous aggregate row and UPDATE_AFTER with the new
one (INSERT for a first-seen key, DELETE when the group's count drains to
zero under retraction input).

TPU-first difference: instead of one state read-modify-write per record, each
micro-batch is folded per-key with ``np.add.reduceat``-style grouped
reductions (sort by in-batch group id, reduce each contiguous run), then ONE
state merge per distinct key in the batch — the same two-phase shape as the
reference's MiniBatchGroupAggFunction (local pre-aggregation, then a single
accumulator merge), which is what makes the op lowerable to the device
scatter-fold path for integer keys.

State is laid out per key group (``_state[kg][key] -> float64[n_slots]``)
so snapshots re-shard on rescale exactly like the heap backend.

Retraction: SUM/COUNT/AVG retract exactly (additive). MIN/MAX are exact
too when constructed with ``retract_minmax=True`` (a value->multiplicity
map per key per aggregate, the reference's
MinWithRetractAggFunction.java:36 MapView accumulator) — the planner
enables it whenever the input is a changelog; append-only input keeps
the cheap scalar fold (reference planner picks the no-retract variants
the same way).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..core.keygroups import assign_to_key_group
from ..core.records import RecordBatch, Schema, scalar as _scalar
from ..runtime.operators.base import OneInputOperator, OperatorContext, Output
from . import rowkind as rk

__all__ = ["GroupAggOperator", "LocalGroupAggOperator", "SqlAggSpec"]


class SqlAggSpec:
    """One aggregate: kind in count|sum|min|max|avg, over input column
    ``field`` (None for COUNT(*)), emitted as ``out_name``."""

    def __init__(self, kind: str, field: Optional[str], out_name: str,
                 distinct: bool = False):
        if kind not in ("count", "sum", "min", "max", "avg"):
            raise ValueError(f"unsupported aggregate {kind}")
        self.kind = kind
        self.field = field
        self.out_name = out_name
        self.distinct = distinct


# accumulator slots per agg: count->1, sum->1, min->1, max->1, avg->2
_SLOTS = {"count": 1, "sum": 1, "min": 1, "max": 1, "avg": 2}
_INITS = {"count": 0.0, "sum": 0.0, "min": np.inf, "max": -np.inf}


class GroupAggOperator(OneInputOperator):
    """Vectorized unbounded group aggregation emitting a changelog."""

    def __init__(self, key_columns: Sequence[str], aggs: Sequence[SqlAggSpec],
                 count_star_index: Optional[int] = None,
                 partial_input: bool = False,
                 retract_minmax: bool = False,
                 name: str = "GroupAgg"):
        """``retract_minmax``: maintain a per-key value->multiplicity map
        for every MIN/MAX aggregate so retractions are EXACT (reference
        MinWithRetractAggFunction.java:36's MapView accumulator). The
        planner enables it when the input is a changelog; append-only
        input keeps the cheap scalar fold."""
        super().__init__(name)
        self._key_columns = list(key_columns)
        self._aggs = list(aggs)
        self._partial_input = bool(partial_input)
        self._retract_minmax = bool(retract_minmax)
        self._mm_idx = [i for i, a in enumerate(aggs)
                        if a.kind in ("min", "max")]
        if self._partial_input and self._retract_minmax and self._mm_idx:
            raise ValueError(
                "retractable MIN/MAX cannot consume pre-reduced partials "
                "(the local combine folds extrema lossily); the planner "
                "disables the two-phase split in this case")
        # kg -> key -> [value->count dict per min/max agg]
        self._mm_counts: dict[int, dict[Any, list]] = {}
        for a in self._aggs:
            if a.distinct:
                raise NotImplementedError(
                    "DISTINCT aggregates need per-key value sets; not "
                    "supported yet")
        # slot layout: [0]=group row count, then per-agg slots
        self._offsets: list[int] = []
        off = 1
        for a in self._aggs:
            self._offsets.append(off)
            off += _SLOTS[a.kind]
        self._n_slots = off
        self._state: dict[int, dict[Any, np.ndarray]] = {}  # kg -> key -> acc
        self._out_schema: Optional[Schema] = None
        self._key_dtypes: Optional[list] = None

    # -- state layout ------------------------------------------------------
    def _new_acc(self) -> np.ndarray:
        acc = np.zeros(self._n_slots, np.float64)
        for a, off in zip(self._aggs, self._offsets):
            if a.kind in ("min", "max"):
                acc[off] = _INITS[a.kind]
        return acc

    def _results_from_acc(self, acc: np.ndarray) -> list:
        out = []
        for a, off in zip(self._aggs, self._offsets):
            if a.kind == "avg":
                cnt = acc[off + 1]
                out.append(acc[off] / cnt if cnt else 0.0)
            else:
                out.append(acc[off])
        return out

    # -- data path ---------------------------------------------------------
    def _local_partials(self, batch: RecordBatch
                        ) -> tuple[np.ndarray, list, np.ndarray]:
        """The LOCAL phase: fold one batch into per-distinct-key partial
        accumulator rows (uniq keys, key rows, partials [G, n_slots])."""
        keys, single_key = self._group_ids(batch)
        kinds = (batch.column(rk.ROWKIND_COLUMN).astype(np.int8)
                 if rk.ROWKIND_COLUMN in batch.schema
                 else np.zeros(batch.n, np.int8))
        # accumulate (+I/+U) rows add, retract (-U/-D) rows subtract
        sign = np.where((kinds == rk.UPDATE_BEFORE) | (kinds == rk.DELETE),
                        -1.0, 1.0)

        uniq, inverse = _unique_inverse(keys)
        key_rows = [(k,) if single_key else k for k in uniq]
        order = np.argsort(inverse, kind="stable")
        sorted_inv = inverse[order]
        starts = np.searchsorted(sorted_inv, np.arange(len(uniq)))

        partials = np.zeros((len(uniq), self._n_slots), np.float64)
        s = sign[order]
        partials[:, 0] = np.add.reduceat(s, starts)
        for a, off in zip(self._aggs, self._offsets):
            if a.kind == "count":
                vals = (s if a.field is None
                        else s * ~_is_null(batch.column(a.field)[order]))
                partials[:, off] = np.add.reduceat(vals, starts)
            elif a.kind in ("sum", "avg"):
                col = batch.column(a.field)[order].astype(np.float64)
                partials[:, off] = np.add.reduceat(col * s, starts)
                if a.kind == "avg":
                    partials[:, off + 1] = np.add.reduceat(s, starts)
            else:  # min/max
                col = batch.column(a.field)[order].astype(np.float64)
                if self._retract_minmax:
                    # exact under retraction: ship the per-group raw
                    # (value, delta) runs to the count-map merge instead
                    # of a lossy extremum fold
                    continue
                red = np.minimum if a.kind == "min" else np.maximum
                partials[:, off] = red.reduceat(col, starts)
        extras = None
        if self._retract_minmax and self._mm_idx:
            extras = []
            ends = np.append(starts[1:], len(keys))
            cols_sorted = {a.field: batch.column(a.field)[order]
                           .astype(np.float64)
                           for i, a in enumerate(self._aggs)
                           if i in self._mm_idx}
            s_sorted = s
            for gi in range(len(uniq)):
                lo, hi = int(starts[gi]), int(ends[gi])
                extras.append([
                    (cols_sorted[self._aggs[i].field][lo:hi],
                     s_sorted[lo:hi])
                    for i in self._mm_idx])
        return uniq, key_rows, partials, extras

    def _combine_partials(self, batch: RecordBatch
                          ) -> tuple[np.ndarray, list, np.ndarray]:
        """Partial-input mode (downstream of LocalGroupAggOperator): the
        batch's rows ARE partial accumulator rows; combine per distinct
        key (sum for additive slots, min/max-combine for extrema)."""
        keys, single_key = self._group_ids(batch)
        uniq, inverse = _unique_inverse(keys)
        key_rows = [(k,) if single_key else k for k in uniq]
        order = np.argsort(inverse, kind="stable")
        starts = np.searchsorted(inverse[order], np.arange(len(uniq)))
        partials = np.zeros((len(uniq), self._n_slots), np.float64)
        pc = batch.column(_PARTIAL_COUNT)[order].astype(np.float64)
        partials[:, 0] = np.add.reduceat(pc, starts)
        for a, off in zip(self._aggs, self._offsets):
            for j in range(_SLOTS[a.kind]):
                col = batch.column(_partial_col(a.out_name, j))[order] \
                    .astype(np.float64)
                if a.kind == "min" and j == 0:
                    partials[:, off] = np.minimum.reduceat(col, starts)
                elif a.kind == "max" and j == 0:
                    partials[:, off] = np.maximum.reduceat(col, starts)
                else:
                    partials[:, off + j] = np.add.reduceat(col, starts)
        return uniq, key_rows, partials

    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        if self._partial_input:
            uniq, key_rows, partials = self._combine_partials(batch)
            extras = None
        else:
            uniq, key_rows, partials, extras = self._local_partials(batch)

        # global phase: one state merge per distinct key + changelog emit
        out_rows: list[tuple] = []
        out_ts: list[int] = []
        ts_max = int(batch.timestamps.max())
        for gi, key in enumerate(uniq):
            key = key.item() if isinstance(key, np.generic) else key
            key_rows[gi] = tuple(
                v.item() if isinstance(v, np.generic) else v
                for v in key_rows[gi])
            kg = self._key_group_for(key)
            kg_map = self._state.setdefault(kg, {})
            acc = kg_map.get(key)
            first = acc is None
            prev_row = (None if first
                        else self._emit_row(key_rows[gi], acc,
                                            rk.UPDATE_BEFORE))
            if first:
                acc = self._new_acc()
            self._merge(acc, partials[gi])
            if extras is not None:
                self._merge_minmax_counts(kg, key, acc, extras[gi])
            if acc[0] <= 0:
                # group fully retracted: DELETE carries the pre-merge row
                # (reference GroupAggFunction emits -D of the old aggregate)
                if not first:
                    kg_map.pop(key, None)
                    self._mm_counts.get(kg, {}).pop(key, None)
                    out_rows.append(prev_row[:-1] + (int(rk.DELETE),))
                    out_ts.append(ts_max)
                continue
            kg_map[key] = acc
            if not first:
                out_rows.append(prev_row)
                out_ts.append(ts_max)
            out_rows.append(self._emit_row(
                key_rows[gi], acc, rk.INSERT if first else rk.UPDATE_AFTER))
            out_ts.append(ts_max)
        if out_rows:
            self._emit_batch(out_rows, out_ts)

    def _merge(self, acc: np.ndarray, partial: np.ndarray) -> None:
        acc[0] += partial[0]
        for a, off in zip(self._aggs, self._offsets):
            if a.kind in ("count", "sum"):
                acc[off] += partial[off]
            elif a.kind == "avg":
                acc[off] += partial[off]
                acc[off + 1] += partial[off + 1]
            elif self._retract_minmax:
                pass  # extrema maintained by _merge_minmax_counts
            elif a.kind == "min":
                acc[off] = min(acc[off], partial[off])
            else:
                acc[off] = max(acc[off], partial[off])

    def _merge_minmax_counts(self, kg: int, key: Any, acc: np.ndarray,
                             group_extras: list) -> None:
        """Exact MIN/MAX under retraction: per-agg value->multiplicity
        maps (reference MinWithRetractAggFunction.java:36). The extremum
        recomputes over the key's live-value map once per touched group
        per batch — O(distinct live values), the same order the reference
        pays iterating its MapView when the extremum retracts."""
        maps = self._mm_counts.setdefault(kg, {}).setdefault(
            key, [dict() for _ in self._mm_idx])
        for slot, (vals, signs) in zip(range(len(self._mm_idx)),
                                       group_extras):
            agg_i = self._mm_idx[slot]
            a = self._aggs[agg_i]
            off = self._offsets[agg_i]
            m = maps[slot]
            for v, sgn in zip(vals.tolist(), signs.tolist()):
                if sgn > 0:
                    m[v] = m.get(v, 0) + 1
                else:
                    c = m.get(v, 0) - 1
                    if c > 0:
                        m[v] = c
                    else:
                        m.pop(v, None)
            if not m:
                acc[off] = _INITS[a.kind]
            elif a.kind == "min":
                acc[off] = min(m)
            else:
                acc[off] = max(m)

    def _emit_row(self, key_row: tuple, acc: np.ndarray, kind) -> tuple:
        return key_row + tuple(self._results_from_acc(acc)) + (int(kind),)

    def _emit_batch(self, rows: list, ts: list[int]) -> None:
        if self._out_schema is None:
            key_fields = [(n, d) for n, d in zip(self._key_columns,
                                                 self._key_dtypes)]
            agg_fields = [(a.out_name, np.float64) for a in self._aggs]
            self._out_schema = Schema(
                key_fields + agg_fields + [(rk.ROWKIND_COLUMN, np.int8)])
        self.output.emit(RecordBatch.from_rows(self._out_schema, rows, ts))

    # -- keys --------------------------------------------------------------
    def _group_ids(self, batch: RecordBatch) -> tuple[np.ndarray, bool]:
        """Per-row group key array (hashable) + whether it's a single
        column (vs composite tuple keys)."""
        cols = [batch.column(c) for c in self._key_columns]
        if self._key_dtypes is None:
            self._key_dtypes = [batch.schema.field(c).dtype
                                for c in self._key_columns]
        if len(cols) == 1:
            return cols[0], True
        # composite key: build object array of tuples
        keys = np.empty(batch.n, dtype=object)
        for i in range(batch.n):
            keys[i] = tuple(_scalar(c[i]) for c in cols)
        return keys, False

    def _key_group_for(self, key: Any) -> int:
        return assign_to_key_group(key, self.ctx.max_parallelism)

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        snap = {"group-agg": {kg: dict(m)
                              for kg, m in self._state.items()}}
        if self._mm_counts:
            snap["group-agg-mm"] = {
                kg: {k: [dict(m) for m in maps]
                     for k, maps in keys.items()}
                for kg, keys in self._mm_counts.items()}
        return {"keyed": {"backend": snap}}

    def initialize_state(self, keyed_snapshots: list, operator_snapshot) -> None:
        for snap in keyed_snapshots:
            table = snap["backend"].get("group-agg", {})
            for kg, entries in table.items():
                if kg in self.ctx.key_group_range:
                    self._state.setdefault(kg, {}).update(entries)
            for kg, keys in snap["backend"].get("group-agg-mm", {}).items():
                if kg in self.ctx.key_group_range:
                    tgt = self._mm_counts.setdefault(kg, {})
                    for k, maps in keys.items():
                        tgt[k] = [dict(m) for m in maps]



_PARTIAL_COUNT = "__pc__"


def _partial_col(out_name: str, j: int) -> str:
    return f"{out_name}.__p{j}__"


class LocalGroupAggOperator(OneInputOperator):
    """The LOCAL half of two-phase GROUP BY (reference
    StreamExecLocalGroupAggregate / MiniBatchLocalGroupAggFunction): runs
    BEFORE the keyed exchange on every upstream subtask, folding each
    micro-batch into one partial-accumulator row per distinct key, so the
    exchange ships O(distinct keys) rows instead of O(records). Stateless
    (nothing to checkpoint); the global GroupAggOperator(partial_input=
    True) downstream combines partials and owns the changelog."""

    def __init__(self, key_columns: Sequence[str], aggs: Sequence[SqlAggSpec],
                 name: str = "LocalGroupAgg"):
        super().__init__(name)
        # reuse the partial computation via a throwaway global op core
        self._core = GroupAggOperator(key_columns, aggs, name=name)
        self._key_columns = list(key_columns)
        self._aggs = list(aggs)
        self._out_schema: Optional[Schema] = None

    def setup(self, ctx: OperatorContext, output: Output) -> None:
        super().setup(ctx, output)
        self._core.ctx = ctx

    def _schema_for(self, in_schema: Schema) -> Schema:
        if self._out_schema is None:
            fields = [(n, in_schema.field(n).dtype)
                      for n in self._key_columns]
            fields.append((_PARTIAL_COUNT, np.float64))
            for a in self._aggs:
                for j in range(_SLOTS[a.kind]):
                    fields.append((_partial_col(a.out_name, j), np.float64))
            self._out_schema = Schema(fields)
        return self._out_schema

    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        schema = self._schema_for(batch.schema)
        _uniq, key_rows, partials, _extras = \
            self._core._local_partials(batch)
        g = len(key_rows)
        cols: dict[str, np.ndarray] = {}
        for i, n in enumerate(self._key_columns):
            dtype = schema.field(n).dtype
            if dtype is object:
                arr = np.empty(g, object)
                arr[:] = [kr[i] for kr in key_rows]
            else:
                arr = np.asarray([kr[i] for kr in key_rows], dtype=dtype)
            cols[n] = arr
        cols[_PARTIAL_COUNT] = partials[:, 0]
        for a, off in zip(self._aggs, self._core._offsets):
            for j in range(_SLOTS[a.kind]):
                cols[_partial_col(a.out_name, j)] = partials[:, off + j]
        ts = np.full(g, int(batch.timestamps.max()), np.int64)
        self.output.emit(RecordBatch(schema, cols, ts))


def _unique_inverse(keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """np.unique(return_inverse=True) that tolerates None / mixed-type
    object keys (which break numpy's sort): dict-order first-seen unique."""
    if keys.dtype != object:
        return np.unique(keys, return_inverse=True)
    index: dict = {}
    uniq: list = []
    inv = np.empty(len(keys), np.int64)
    for i, k in enumerate(keys):
        j = index.get(k)
        if j is None:
            j = index[k] = len(uniq)
            uniq.append(k)
        inv[i] = j
    out = np.empty(len(uniq), dtype=object)
    out[:] = uniq
    return out, inv


def _is_null(col: np.ndarray) -> np.ndarray:
    if col.dtype == object:
        return np.array([v is None for v in col], dtype=bool)
    return np.zeros(len(col), dtype=bool)
