"""SQL planner: SelectStmt -> DataStream pipeline.

The analog of the reference's planner + codegen chain (flink-table-planner
delegation/PlannerBase.scala:170 translate -> ExecNode graph -> Janino
codegen), collapsed: "codegen" here is compiling expressions to vectorized
column closures (expressions.compile_expr) and picking operators —

* stateless SELECT/WHERE      -> one BatchFnOperator (fused by chaining,
  reference StreamExecCalc)
* GROUP BY window_start/end over a window TVF
                              -> keyBy + window aggregation, lowered to the
  device slice-window operator when eligible (reference
  StreamExecWindowAggregate -> SliceSharedWindowAggProcessor)
* plain GROUP BY              -> GroupAggOperator changelog aggregation
  (reference StreamExecGroupAggregate -> GroupAggFunction)
* ORDER BY <agg> DESC LIMIT n over a changelog -> host TopN operator
  (reference StreamExecRank)

Aggregate inputs and group keys are materialized as generated columns
(``__agg0__``, ...) by a projection ahead of the exchange, which is what the
two-phase local/global split needs (reference StreamExecLocalGroupAggregate).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..api.datastream import DataStream
from ..core.records import RecordBatch, Schema
from ..runtime.operators.simple import BatchFnOperator
from ..window.assigners import (
    SlidingEventTimeWindows, TumblingEventTimeWindows,
)
from . import rowkind as rk
from .expressions import (
    AggCall, BinaryOp, Column, Expr, ExprError, Star, collect_aggs,
    collect_columns, compile_expr, rewrite_expr,
)
from .group_agg import GroupAggOperator, SqlAggSpec
from .join import StreamingJoinOperator, TemporalJoinOperator
from .parser import JoinClause, SelectItem, SelectStmt, TableRef, WindowTVF
from .topn import TopNOperator

__all__ = ["plan", "PlanError"]

WINDOW_COLS = ("window_start", "window_end")


class PlanError(ValueError):
    pass


def plan(stmt: SelectStmt, resolve_table, env) -> DataStream:
    """Translate ``stmt`` onto DataStream ops. ``resolve_table(name)``
    returns the registered catalog entry's (DataStream, Schema)."""
    return _Planner(resolve_table, env).plan_select(stmt)


class _Planner:
    def __init__(self, resolve_table, env):
        self.resolve = resolve_table
        self.env = env

    # -- FROM --------------------------------------------------------------
    def plan_from(self, from_) -> tuple[
            DataStream, Schema, Optional[WindowTVF], dict]:
        """Returns (stream, schema, window_tvf, qualifiers) where
        ``qualifiers`` maps table alias -> {original column -> current
        column name in the stream's schema} for qualified-name resolution."""
        if isinstance(from_, TableRef):
            ds, schema = self.resolve(from_.name)
            alias = from_.alias or from_.name
            quals = {alias: {f.name: f.name for f in schema.fields}}
            return ds, schema, None, quals
        if isinstance(from_, WindowTVF):
            ds, schema, inner_tvf, quals = self.plan_from(from_.table)
            if inner_tvf is not None:
                raise PlanError("nested window TVFs are not supported")
            if from_.time_col not in schema:
                raise PlanError(
                    f"DESCRIPTOR column {from_.time_col!r} not in table")
            return ds, schema, from_, quals
        if isinstance(from_, SelectStmt):
            sub = self.plan_select(from_)
            if sub._sql_schema is None:
                raise PlanError("subquery output schema unknown")
            quals = ({from_.alias: {f.name: f.name
                                    for f in sub._sql_schema.fields}}
                     if from_.alias else {})
            return sub, sub._sql_schema, None, quals
        if isinstance(from_, JoinClause):
            return self.plan_join(from_)
        from .parser import MatchRecognize
        if isinstance(from_, MatchRecognize):
            from .match_recognize import plan_match_recognize
            ds, schema = self.resolve(from_.table.name)
            out = plan_match_recognize(from_, ds, schema, self.env)
            alias = from_.alias
            quals = ({alias: {f.name: f.name
                              for f in out._sql_schema.fields}}
                     if alias else {})
            return out, out._sql_schema, None, quals
        raise PlanError(f"unsupported FROM clause {from_!r}")

    # -- JOIN --------------------------------------------------------------
    def plan_join(self, jc: JoinClause) -> tuple[
            DataStream, Schema, None, dict]:
        """Equi-join of two streams (reference StreamExecJoin ->
        StreamingJoinOperator): key both sides by the equi columns, connect
        through a two-input vertex; residual (non-equi) conjuncts become a
        post-join filter (inner only). Columns colliding across sides are
        renamed ``{alias}_{name}``; the other side's numeric fields are
        promoted to float64 when nullable (outer joins pad with NaN/None)."""
        lds, lschema, ltvf, lq = self.plan_from(jc.left)
        rds, rschema, rtvf, rq = self.plan_from(jc.right)
        if ltvf is not None or rtvf is not None:
            raise PlanError("window TVFs cannot be direct join inputs; wrap "
                            "the windowed aggregation in a subquery")
        dup = set(lq) & set(rq)
        if dup:
            raise PlanError(
                f"duplicate table alias(es) in join: {sorted(dup)}")
        join_type = {"INNER": "inner", "LEFT": "left", "RIGHT": "right",
                     "FULL": "full"}[jc.kind]

        lnames = [f.name for f in lschema.fields
                  if f.name != rk.ROWKIND_COLUMN]
        rnames = [f.name for f in rschema.fields
                  if f.name != rk.ROWKIND_COLUMN]
        lprefix = next(iter(lq)) if len(lq) == 1 else "l"
        rprefix = next(iter(rq)) if len(rq) == 1 else "r"
        out_l = {n: n if n not in set(rnames) else f"{lprefix}_{n}"
                 for n in lnames}
        out_r = {n: n if n not in set(lnames) else f"{rprefix}_{n}"
                 for n in rnames}
        if set(out_l.values()) & set(out_r.values()):
            raise PlanError("join column renaming collision; add aliases")

        # resolve one ON-condition column to (side, renamed name)
        def resolve_on(c: Column) -> tuple[str, str]:
            if c.table is not None:
                if c.table in lq and c.name in lq[c.table]:
                    return "l", out_l[lq[c.table][c.name]]
                if c.table in rq and c.name in rq[c.table]:
                    return "r", out_r[rq[c.table][c.name]]
                raise PlanError(f"cannot resolve {c.table}.{c.name} in ON")
            in_l, in_r = c.name in out_l, c.name in out_r
            if in_l and in_r:
                raise PlanError(f"ambiguous column {c.name!r} in ON")
            if in_l:
                return "l", out_l[c.name]
            if in_r:
                return "r", out_r[c.name]
            raise PlanError(f"unknown column {c.name!r} in ON")

        equi: list[tuple[str, str]] = []   # (left col, right col), renamed
        residual: list[Expr] = []
        for conj in _conjuncts(jc.on):
            if (isinstance(conj, BinaryOp) and conj.op == "="
                    and isinstance(conj.left, Column)
                    and isinstance(conj.right, Column)):
                s1, n1 = resolve_on(conj.left)
                s2, n2 = resolve_on(conj.right)
                if s1 != s2:
                    equi.append((n1, n2) if s1 == "l" else (n2, n1))
                    continue
            residual.append(conj)
        if not equi:
            raise PlanError("streaming join needs at least one equi "
                            "condition a.x = b.y")
        if residual and join_type != "inner":
            raise PlanError("non-equi ON conditions are only supported for "
                            "INNER joins")

        l_nullable = join_type in ("right", "full")
        r_nullable = join_type in ("left", "full")
        renamed_l = self._rename_side(lds, lschema, out_l, "JoinLeftRename")
        renamed_r = self._rename_side(rds, rschema, out_r, "JoinRightRename")

        out_fields = (
            [(out_l[n], _nullable_dtype(lschema.field(n).dtype, l_nullable))
             for n in lnames]
            + [(out_r[n], _nullable_dtype(rschema.field(n).dtype, r_nullable))
               for n in rnames]
            + [(rk.ROWKIND_COLUMN, np.int8)])
        out_schema = Schema(out_fields)

        lkey_names = [p[0] for p in equi]
        rkey_names = [p[1] for p in equi]
        lkey_idx = (lnames.index(_orig(out_l, lkey_names[0]))
                    if len(equi) == 1
                    else tuple(lnames.index(_orig(out_l, n))
                               for n in lkey_names))
        rkey_idx = (rnames.index(_orig(out_r, rkey_names[0]))
                    if len(equi) == 1
                    else tuple(rnames.index(_orig(out_r, n))
                               for n in rkey_names))

        lkeyed = (renamed_l.key_by(lkey_names[0]) if len(equi) == 1
                  else renamed_l.key_by(
                      lambda row, _i=lkey_idx: tuple(row[i] for i in _i)))
        rkeyed = (renamed_r.key_by(rkey_names[0]) if len(equi) == 1
                  else renamed_r.key_by(
                      lambda row, _i=rkey_idx: tuple(row[i] for i in _i)))

        n_l, n_r = len(lnames), len(rnames)
        jt = join_type
        if jc.temporal_time is not None:
            # b FOR SYSTEM_TIME AS OF l.rowtime: versioned-table join
            # (reference StreamExecTemporalJoin.java:77). Event time rides
            # the record timestamps; the AS OF column must name the left
            # side's time attribute (documenting which side is probed).
            if join_type not in ("inner", "left"):
                raise PlanError(
                    "temporal join supports INNER and LEFT JOIN only")
            tcol = jc.temporal_time
            if not isinstance(tcol, Column):
                raise PlanError("FOR SYSTEM_TIME AS OF expects a column")
            # the time attribute is the stream's out-of-band record
            # timestamp, so the AS OF column need not be a data column —
            # but its qualifier must name the LEFT (probe) side
            on_left = (tcol.table in lq if tcol.table is not None
                       else tcol.name in out_l)
            if not on_left:
                raise PlanError(
                    "FOR SYSTEM_TIME AS OF must reference the left "
                    "(probe) side's time attribute")
            joined = lkeyed.connect(rkeyed).transform(
                "TemporalJoin",
                lambda: TemporalJoinOperator(jt, lkey_idx, rkey_idx,
                                             out_schema, n_l, n_r))
        else:
            joined = lkeyed.connect(rkeyed).transform(
                "Join",
                lambda: StreamingJoinOperator(jt, lkey_idx, rkey_idx,
                                              out_schema, n_l, n_r))
        if residual:
            cond = residual[0]
            for c in residual[1:]:
                cond = BinaryOp("AND", cond, c)
            cond = rewrite_expr(cond, lambda e: (
                Column(resolve_on(e)[1]) if isinstance(e, Column) else e))
            cond_fn = compile_expr(cond)

            def filt(batch: RecordBatch):
                mask = cond_fn(dict(batch.columns), batch.n).astype(bool)
                idx = np.flatnonzero(mask)
                return batch.take(idx)

            joined = joined.transform(
                "JoinFilter", lambda: BatchFnOperator(filt, "JoinFilter"))

        quals: dict = {}
        for q, m in lq.items():
            quals[q] = {orig: out_l[cur] for orig, cur in m.items()
                        if cur in out_l}
        for q, m in rq.items():
            quals[q] = {orig: out_r[cur] for orig, cur in m.items()
                        if cur in out_r}
        joined._sql_schema = out_schema
        return joined, out_schema, None, quals

    def _rename_side(self, ds: DataStream, schema: Schema,
                     rename: dict, name: str) -> DataStream:
        if all(k == v for k, v in rename.items()):
            return ds
        out_fields = [(rename.get(f.name, f.name), f.dtype)
                      for f in schema.fields]
        out_schema = Schema(out_fields)

        def project(batch: RecordBatch):
            cols = {rename.get(f.name, f.name): batch.column(f.name)
                    for f in batch.schema.fields}
            return RecordBatch(out_schema, cols, batch.timestamps)

        return ds.transform(name, lambda: BatchFnOperator(project, name))

    # -- SELECT ------------------------------------------------------------
    def plan_select(self, stmt: SelectStmt) -> DataStream:
        ds, schema, tvf, quals = self.plan_from(stmt.from_)
        stmt = _resolve_stmt(stmt, schema, quals)

        # hoist aggregates from select items + having
        agg_calls: list[AggCall] = []
        for item in stmt.items:
            if not isinstance(item.expr, Star):
                collect_aggs(item.expr, agg_calls)
        if stmt.having is not None:
            collect_aggs(stmt.having, agg_calls)

        if tvf is not None or stmt.group_by or agg_calls:
            out = self.plan_aggregate(stmt, ds, schema, tvf, agg_calls)
        else:
            out = self.plan_calc(stmt, ds, schema)
        out = self.plan_order_limit(stmt, out)
        return out

    # -- stateless calc (project + filter) ---------------------------------
    def plan_calc(self, stmt: SelectStmt, ds: DataStream,
                  schema: Schema) -> DataStream:
        where_fn = (compile_expr(stmt.where)
                    if stmt.where is not None else None)
        out_fields, item_fns = self._select_fns(stmt.items, schema)
        # changelog input: pass the rowkind column through so downstream
        # changelog consumers (TopN, sinks) keep retraction semantics
        if (rk.ROWKIND_COLUMN in schema
                and not any(n == rk.ROWKIND_COLUMN for n, _ in out_fields)):
            out_fields = out_fields + [(rk.ROWKIND_COLUMN, np.int8)]
            item_fns = item_fns + [(rk.ROWKIND_COLUMN,
                                    lambda cols, n: cols[rk.ROWKIND_COLUMN])]
        out_schema = Schema(out_fields)

        def calc(batch: RecordBatch) -> Optional[RecordBatch]:
            cols, n = dict(batch.columns), batch.n
            ts = batch.timestamps
            if where_fn is not None:
                mask = where_fn(cols, n).astype(bool)
                if not mask.all():
                    idx = np.flatnonzero(mask)
                    cols = {k: v[idx] for k, v in cols.items()}
                    ts = ts[idx]
                    n = len(idx)
            out_cols = {name: np.asarray(fn(cols, n))
                        for name, fn in item_fns}
            return RecordBatch(out_schema, out_cols, ts)

        out = ds.transform("Calc", lambda: BatchFnOperator(calc, "Calc"))
        out._sql_schema = out_schema
        return out

    def _select_fns(self, items, schema: Schema,
                    agg_slots: Optional[dict] = None):
        """[(out_name, fn)] + schema fields for the select list."""
        out_fields: list[tuple[str, Any]] = []
        fns: list[tuple[str, Any]] = []
        for i, item in enumerate(items):
            if isinstance(item.expr, Star):
                for f in schema.fields:
                    name = f.name
                    fns.append((name,
                                (lambda nm: lambda cols, n: cols[nm])(name)))
                    out_fields.append((name, f.dtype))
                continue
            name = item.alias or _default_name(item.expr, i)
            fn = compile_expr(item.expr, agg_slots)
            fns.append((name, fn))
            out_fields.append((name, _infer_dtype(item.expr, schema)))
        return out_fields, fns

    # -- aggregation -------------------------------------------------------
    def plan_aggregate(self, stmt: SelectStmt, ds: DataStream, schema: Schema,
                       tvf: Optional[WindowTVF],
                       agg_calls: list[AggCall]) -> DataStream:
        group_exprs = list(stmt.group_by)
        window_group = []
        if tvf is not None:
            window_group = [g for g in group_exprs
                            if isinstance(g, Column)
                            and g.name in WINDOW_COLS]
            group_exprs = [g for g in group_exprs
                           if not (isinstance(g, Column)
                                   and g.name in WINDOW_COLS)]
            if len(window_group) == 0:
                raise PlanError(
                    "window TVF queries must GROUP BY window_start/"
                    "window_end")

        # project: key columns + agg input columns (+ time for windows)
        key_names: list[str] = []
        key_fns = []
        for i, g in enumerate(group_exprs):
            if isinstance(g, Column):
                key_names.append(g.name)
                key_fns.append(None)
            else:
                key_names.append(f"__key{i}__")
                key_fns.append(compile_expr(g))
        agg_specs: list[SqlAggSpec] = []
        agg_in_fns = []
        for i, call in enumerate(agg_calls):
            if call.arg is None:
                agg_specs.append(SqlAggSpec("count", None, f"__out{i}__"))
                agg_in_fns.append(None)
            else:
                in_name = (call.arg.name if isinstance(call.arg, Column)
                           else f"__agg{i}__")
                agg_specs.append(SqlAggSpec(call.kind, in_name,
                                            f"__out{i}__", call.distinct))
                agg_in_fns.append(None if isinstance(call.arg, Column)
                                  else compile_expr(call.arg))

        where_fn = (compile_expr(stmt.where)
                    if stmt.where is not None else None)
        time_col = tvf.time_col if tvf is not None else None
        pre_fields: list[tuple[str, Any]] = []
        for name, g in zip(key_names, group_exprs):
            pre_fields.append(
                (name, schema.field(name).dtype if name in schema
                 else _infer_dtype(g, schema)))
        for spec, call in zip(agg_specs, agg_calls):
            if spec.field is not None:
                pre_fields.append(
                    (spec.field, schema.field(spec.field).dtype
                     if spec.field in schema
                     else _infer_dtype(call.arg, schema)))
        # changelog input (e.g. aggregating over a join's output): carry the
        # rowkind column so GroupAggOperator retracts correctly
        changelog_in = rk.ROWKIND_COLUMN in schema
        if changelog_in:
            if tvf is not None:
                raise PlanError(
                    "window aggregation over a changelog (updating) input "
                    "is not supported; aggregate before the window or use "
                    "an append-only input")
            pre_fields.append((rk.ROWKIND_COLUMN, np.int8))
        seen = set()
        pre_fields = [(n, d) for n, d in pre_fields
                      if not (n in seen or seen.add(n))]
        if not pre_fields:
            # global COUNT(*) reads no input columns; a unit column keeps
            # the batch's row count flowing through the exchange
            pre_fields = [("__rows__", np.int8)]
        pre_schema = Schema(pre_fields)

        def pre_project(batch: RecordBatch) -> Optional[RecordBatch]:
            cols, n = dict(batch.columns), batch.n
            ts = batch.timestamps
            if time_col is not None:
                ts = cols[time_col].astype(np.int64)
            if where_fn is not None:
                mask = where_fn(cols, n).astype(bool)
                idx = np.flatnonzero(mask)
                cols = {k: v[idx] for k, v in cols.items()}
                ts = ts[idx]
                n = len(idx)
            for name, fn in zip(key_names, key_fns):
                if fn is not None:
                    cols[name] = np.asarray(fn(cols, n))
            for spec, fn in zip(agg_specs, agg_in_fns):
                if fn is not None:
                    cols[spec.field] = np.asarray(fn(cols, n))
            out_cols = {f.name: cols[f.name] for f in pre_schema.fields
                        if f.name in cols}
            if "__rows__" in pre_schema and "__rows__" not in out_cols:
                out_cols["__rows__"] = np.zeros(n, np.int8)
            return RecordBatch(pre_schema, out_cols, ts)

        projected = ds.transform(
            "PreProject", lambda: BatchFnOperator(pre_project, "PreProject"))

        if tvf is not None:
            agged, agg_schema = self._window_agg(
                projected, pre_schema, tvf, key_names, agg_specs)
        else:
            agged, agg_schema = self._group_agg(
                projected, pre_schema, key_names, agg_specs)

        return self._post_project(stmt, agged, agg_schema, group_exprs,
                                  key_names, agg_calls, agg_specs,
                                  window=tvf is not None)

    def _group_agg(self, ds: DataStream, pre_schema: Schema,
                   key_names: list[str], agg_specs: list[SqlAggSpec]):
        from ..core.config import SqlOptions

        # two-phase split (reference StreamExecLocalGroupAggregate /
        # StreamExecGlobalGroupAggregate): a stateless local combine runs
        # BEFORE the keyed exchange on each upstream subtask, so the
        # exchange carries one partial row per distinct key per
        # micro-batch; the global operator merges partials into state
        two_phase = self.env.config.get(SqlOptions.TWO_PHASE_AGG)
        is_global = not key_names
        if is_global:
            # global aggregation: single pseudo key
            key_names = ["__global__"]

            def add_global(batch: RecordBatch):
                cols = dict(batch.columns)
                cols["__global__"] = np.zeros(batch.n, np.int64)
                schema = Schema([("__global__", np.int64)]
                                + [(f.name, f.dtype)
                                   for f in batch.schema.fields])
                return RecordBatch(schema, cols, batch.timestamps)

            ds = ds.transform(
                "GlobalKey", lambda: BatchFnOperator(add_global, "GlobalKey"))
        specs = list(agg_specs)
        names = list(key_names)
        # device lowering (VERDICT r3 #4): with the TPU backend and integer
        # group keys, the changelog aggregation runs on HBM accumulator
        # planes — one fused scatter-fold program per micro-batch instead
        # of per-key host dict updates (reference hot loop:
        # GroupAggFunction.processElement:125). The device fold already
        # pre-aggregates the whole batch in one pass, so the two-phase
        # local combine is redundant and skipped.
        from ..core.config import StateOptions

        def _int_key(n: str) -> bool:
            if is_global:
                return True  # synthesized __global__ key is int64
            f = pre_schema.field(n)
            return (f.dtype is not object
                    and np.issubdtype(np.dtype(f.dtype), np.integer))

        # changelog input + MIN/MAX => the retract-exact count-map path
        # (host only, single phase): the local combine and the device fold
        # both reduce extrema lossily (MinWithRetractAggFunction analog)
        retract_mm = (rk.ROWKIND_COLUMN in pre_schema
                      and any(s.kind in ("min", "max") for s in specs))
        if retract_mm:
            two_phase = False
        use_device = (self.env.config.get(StateOptions.BACKEND) == "tpu"
                      and all(_int_key(n) for n in key_names)
                      and all(not s.distinct for s in specs)
                      and not retract_mm)
        if two_phase and not use_device:
            from .group_agg import LocalGroupAggOperator
            ds = ds.transform(
                "LocalGroupAggregate",
                lambda: LocalGroupAggOperator(names, specs))
        if is_global:
            keyed = ds.key_by(lambda row: 0)
        elif len(key_names) == 1:
            keyed = ds.key_by(key_names[0])
        elif use_device:
            # route by the SAME combined int64 word the device backend
            # stores: DeviceGroupAggOperator's TpuKeyedStateBackend
            # snapshots key groups from hash_batch(combine_key_columns(...)),
            # so the exchange must hash that word too — hashing the Python
            # tuple instead would restore each group's state onto a subtask
            # that never receives its records (silent state loss at
            # parallelism > 1)
            from .device_group_agg import combine_key_columns

            def _combined(batch, _names=tuple(key_names)):
                return combine_key_columns(
                    [np.asarray(batch.column(n)) for n in _names])
            _combined.vectorized = True
            keyed = ds.key_by(_combined)
        else:
            # the local combine keeps key columns first in ITS output
            key_idx = (tuple(range(len(key_names)))
                       if two_phase
                       else tuple(pre_schema.index_of(n)
                                  for n in key_names))
            keyed = ds.key_by(
                lambda row, _idx=key_idx: tuple(row[i] for i in _idx))
        if use_device:
            from .device_group_agg import DeviceGroupAggOperator
            out = keyed._one_input(
                "GroupAggregate(device)",
                lambda: DeviceGroupAggOperator(names, specs),
                key_extractor=keyed.key_extractor)
        else:
            out = keyed._one_input(
                "GroupAggregate",
                lambda: GroupAggOperator(
                    names, specs, partial_input=two_phase,
                    retract_minmax=retract_mm),
                key_extractor=keyed.key_extractor)
        out_schema = Schema(
            [(n, np.float64 if n.startswith("__key") else object)
             for n in key_names]
            + [(s.out_name, np.float64) for s in agg_specs]
            + [(rk.ROWKIND_COLUMN, np.int8)])
        return out, out_schema

    def _window_agg(self, ds: DataStream, pre_schema: Schema,
                    tvf: WindowTVF, key_names: list[str],
                    agg_specs: list[SqlAggSpec]):
        if len(key_names) != 1:
            raise PlanError(
                "window aggregation currently needs exactly one non-window "
                "group key (matches the Nexmark shapes); got "
                f"{key_names or 'none'}")
        if tvf.kind == "TUMBLE":
            assigner = TumblingEventTimeWindows.of(tvf.size_ms)
        elif tvf.kind == "HOP":
            assigner = SlidingEventTimeWindows.of(tvf.size_ms, tvf.slide_ms)
        elif tvf.kind == "CUMULATE":
            from ..window import CumulateWindows
            # parser: CUMULATE(..., INTERVAL step, INTERVAL size)
            assigner = CumulateWindows.of(tvf.size_ms, tvf.slide_ms)
        elif tvf.kind == "SESSION":
            # merging windows: device session-lane operator when the TPU
            # backend is set (round 4); host WindowOperator otherwise
            from ..window import EventTimeSessionWindows
            assigner = EventTimeSessionWindows.with_gap(tvf.size_ms)
        else:
            raise PlanError(f"{tvf.kind} windows not supported yet")
        keyed = ds.key_by(key_names[0])
        windowed = keyed.window(assigner)
        from ..runtime.operators.device_window import AggSpec as DevAggSpec
        dev_specs = [
            DevAggSpec(s.kind, s.field, out_name=s.out_name)
            for s in agg_specs]
        key_field = pre_schema.field(key_names[0])
        from ..core.config import StateOptions
        use_device = (self.env.config.get(StateOptions.BACKEND) == "tpu"
                      and tvf.kind in ("TUMBLE", "HOP", "SESSION")
                      and key_field.is_numeric
                      and np.issubdtype(np.dtype(key_field.dtype),
                                        np.integer)
                      and (tvf.kind == "SESSION"
                           or assigner.pane_size is not None))
        out_schema = Schema(
            [(key_names[0], key_field.dtype),
             ("window_start", np.int64), ("window_end", np.int64)]
            + [(s.out_name, np.float64) for s in agg_specs])
        if use_device:
            out = windowed.device_aggregate(dev_specs,
                                            name="WindowAggregate")
        else:
            out = self._host_window_agg(windowed, pre_schema, key_names[0],
                                        agg_specs, out_schema)
        return out, out_schema

    def _host_window_agg(self, windowed, pre_schema: Schema, key_name: str,
                         agg_specs: list[SqlAggSpec],
                         out_schema: Schema):
        from ..core.functions import AggregateFunction

        idx = {f.name: i for i, f in enumerate(pre_schema.fields)}
        specs = list(agg_specs)
        single = len(pre_schema) == 1

        class _Composite(AggregateFunction):
            def create_accumulator(self):
                return [(0.0, 0) if s.kind == "avg"
                        else (0 if s.kind in ("count", "sum")
                              else None)
                        for s in specs]

            def add(self, value, acc):
                row = (value,) if single else value
                out = []
                for s, a in zip(specs, acc):
                    v = None if s.field is None else row[idx[s.field]]
                    if s.kind == "count":
                        out.append(a + (1 if s.field is None
                                        else (v is not None)))
                    elif s.kind == "sum":
                        out.append(a + v)
                    elif s.kind == "avg":
                        out.append((a[0] + v, a[1] + 1))
                    elif s.kind == "min":
                        out.append(v if a is None else min(a, v))
                    else:
                        out.append(v if a is None else max(a, v))
                return out

            def merge(self, a, b):
                out = []
                for s, x, y in zip(specs, a, b):
                    if s.kind in ("count", "sum"):
                        out.append(x + y)
                    elif s.kind == "avg":
                        out.append((x[0] + y[0], x[1] + y[1]))
                    elif s.kind == "min":
                        out.append(y if x is None else
                                   (x if y is None else min(x, y)))
                    else:
                        out.append(y if x is None else
                                   (x if y is None else max(x, y)))
                return out

            def get_result(self, acc):
                out = []
                for s, a in zip(specs, acc):
                    if s.kind == "avg":
                        out.append(a[0] / a[1] if a[1] else 0.0)
                    else:
                        out.append(a)
                return out

        def window_fn(key, window, result):
            yield (key, window.start, window.end) + tuple(result)

        return windowed._build("WindowAggregate", aggregate=_Composite(),
                               window_fn=window_fn, out_schema=out_schema)

    # -- post-aggregation projection --------------------------------------
    def _post_project(self, stmt: SelectStmt, ds: DataStream,
                      agg_schema: Schema, group_exprs, key_names,
                      agg_calls, agg_specs, window: bool) -> DataStream:
        agg_slots = {call: spec.out_name
                     for call, spec in zip(agg_calls, agg_specs)}
        # group-by expressions are addressable by their key column name
        rewrites: dict[Expr, str] = {}
        for g, name in zip(group_exprs, key_names):
            rewrites[g] = name

        def rewrite(e: Expr) -> Expr:
            if e in rewrites:
                return Column(rewrites[e])
            return e

        items = [type(it)(rewrite(it.expr), it.alias) if not
                 isinstance(it.expr, Star) else it for it in stmt.items]
        having_fn = None
        if stmt.having is not None:
            having_fn = compile_expr(rewrite(stmt.having), agg_slots)
        out_fields, item_fns = self._select_fns(items, agg_schema, agg_slots)
        has_rowkind = rk.ROWKIND_COLUMN in agg_schema
        if has_rowkind and not any(n == rk.ROWKIND_COLUMN
                                   for n, _ in out_fields):
            out_fields = out_fields + [(rk.ROWKIND_COLUMN, np.int8)]
            item_fns = item_fns + [(rk.ROWKIND_COLUMN,
                                    lambda cols, n: cols[rk.ROWKIND_COLUMN])]
        out_schema = Schema(out_fields)

        def post(batch: RecordBatch) -> Optional[RecordBatch]:
            cols, n = dict(batch.columns), batch.n
            ts = batch.timestamps
            if having_fn is not None:
                mask = having_fn(cols, n).astype(bool)
                idx = np.flatnonzero(mask)
                cols = {k: v[idx] for k, v in cols.items()}
                ts = ts[idx]
                n = len(idx)
            out_cols = {name: np.asarray(fn(cols, n))
                        for name, fn in item_fns}
            return RecordBatch(out_schema, out_cols, ts)

        out = ds.transform("PostProject",
                           lambda: BatchFnOperator(post, "PostProject"))
        out._sql_schema = out_schema
        return out

    # -- ORDER BY / LIMIT --------------------------------------------------
    def plan_order_limit(self, stmt: SelectStmt,
                         ds: DataStream) -> DataStream:
        if not stmt.order_by and stmt.limit is None:
            return ds
        if not stmt.order_by:
            raise PlanError("LIMIT without ORDER BY is non-deterministic "
                            "on streams; add ORDER BY")
        schema = getattr(ds, "_sql_schema", None)
        if schema is None:
            raise PlanError("ORDER BY needs a known schema")
        # resolve order expressions against the select list: an expression
        # that IS a select item (e.g. ORDER BY SUM(v) with SUM(v) selected)
        # sorts by that item's output column
        out_names: dict[Expr, str] = {}
        for i, item in enumerate(stmt.items):
            if not isinstance(item.expr, Star):
                out_names[item.expr] = (item.alias
                                        or _default_name(item.expr, i))
        sort_fns = []
        for o in stmt.order_by:
            expr = o.expr
            if expr in out_names:
                expr = Column(out_names[expr])
            elif isinstance(expr, Column) and expr.name not in schema:
                raise PlanError(f"ORDER BY column {expr.name!r} is not in "
                                "the select list")
            sort_fns.append((compile_expr(expr), o.descending))
        limit = stmt.limit
        if limit is None:
            raise PlanError("streaming ORDER BY requires LIMIT (Top-N)")
        out = ds.global_().transform(
            "TopN",
            lambda: TopNOperator(schema, sort_fns, limit),
            parallelism=1)
        out._sql_schema = schema
        return out


def _conjuncts(e: Expr) -> list:
    if isinstance(e, BinaryOp) and e.op == "AND":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _orig(rename: dict, renamed: str) -> str:
    for k, v in rename.items():
        if v == renamed:
            return k
    raise KeyError(renamed)


def _nullable_dtype(dtype, nullable: bool):
    """Outer-join null padding: integer/bool columns become float64 (NaN
    null), floats keep NaN, objects keep None."""
    if not nullable or dtype is object:
        return dtype
    if np.issubdtype(np.dtype(dtype), np.floating):
        return dtype
    return np.float64


def _resolve_stmt(stmt: SelectStmt, schema: Schema,
                  quals: dict) -> SelectStmt:
    """Rewrite qualified columns (a.x) to their current schema names and
    validate unqualified ones against the joined/renamed schema."""

    def resolve(e: Expr) -> Expr:
        if not isinstance(e, Column):
            return e
        if e.table is not None:
            m = quals.get(e.table)
            if m is None or e.name not in m:
                raise PlanError(
                    f"cannot resolve column {e.table}.{e.name}")
            return Column(m[e.name])
        if e.name in schema:
            return e
        hits = {m[e.name] for m in quals.values() if e.name in m}
        if len(hits) == 1:
            return Column(hits.pop())
        if len(hits) > 1:
            raise PlanError(f"ambiguous column {e.name!r}")
        return e  # window_start/window_end appear later; defer

    def rw(e: Expr) -> Expr:
        return rewrite_expr(e, resolve)

    out = SelectStmt(
        items=[it if isinstance(it.expr, Star)
               else SelectItem(rw(it.expr), it.alias) for it in stmt.items],
        from_=stmt.from_,
        where=rw(stmt.where) if stmt.where is not None else None,
        group_by=[rw(g) for g in stmt.group_by],
        having=rw(stmt.having) if stmt.having is not None else None,
        order_by=[type(o)(rw(o.expr), o.descending) for o in stmt.order_by],
        limit=stmt.limit)
    return out


def _default_name(e: Expr, i: int) -> str:
    if isinstance(e, Column):
        return e.name
    if isinstance(e, AggCall):
        return f"{e.kind}_{e.arg.name}" if isinstance(e.arg, Column) \
            else e.kind
    return f"EXPR{i}"


def _infer_dtype(e: Expr, schema: Schema):
    """Best-effort output dtype for a select expression."""
    if isinstance(e, Column) and e.name in schema:
        return schema.field(e.name).dtype
    if isinstance(e, AggCall):
        return np.float64
    cols: set[str] = set()
    collect_columns(e, cols)
    if cols and all(c in schema and schema.field(c).dtype is object
                    for c in cols):
        return object
    return np.float64
