"""Streaming Top-N over an (optionally changelog) input.

The analog of the reference's rank operators (flink-table-planner
StreamExecRank / flink-table-runtime operators/rank/ — e.g.
AppendOnlyTopNFunction, RetractableTopNFunction): maintains the current
result multiset under +I/+U/-U/-D input and, after every batch, emits the
*delta* of the top-N as a changelog (DELETE rows that left the top-N,
INSERT rows that entered it). Runs at parallelism 1 after a global
exchange, like the reference's singleton rank.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..core.records import MIN_TIMESTAMP, RecordBatch, Schema, \
    scalar as _scalar
from ..runtime.operators.base import OneInputOperator
from . import rowkind as rk

__all__ = ["TopNOperator"]


class TopNOperator(OneInputOperator):
    def __init__(self, schema: Schema,
                 sort_fns: Sequence[tuple[Callable, bool]], limit: int,
                 name: str = "TopN"):
        super().__init__(name)
        self._schema = schema
        self._data_names = [f.name for f in schema.fields
                            if f.name != rk.ROWKIND_COLUMN]
        self._sort_fns = list(sort_fns)
        self._limit = int(limit)
        self._rows: dict[tuple, int] = {}   # data row -> multiplicity
        self._emitted: list[tuple] = []     # last emitted top-n, in order
        self._out_schema = Schema(
            [(n, schema.field(n).dtype) for n in self._data_names]
            + [(rk.ROWKIND_COLUMN, np.int8)])

    # -- data path ---------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        kinds = (batch.column(rk.ROWKIND_COLUMN).astype(np.int8)
                 if rk.ROWKIND_COLUMN in batch.schema
                 else np.zeros(batch.n, np.int8))
        cols = [batch.column(n) for n in self._data_names]
        for i in range(batch.n):
            row = tuple(_scalar(c[i]) for c in cols)
            if kinds[i] in (rk.UPDATE_BEFORE, rk.DELETE):
                m = self._rows.get(row, 0) - 1
                if m <= 0:
                    self._rows.pop(row, None)
                else:
                    self._rows[row] = m
            else:
                self._rows[row] = self._rows.get(row, 0) + 1
        self._emit_delta(int(batch.timestamps.max()))

    def _current_topn(self) -> list[tuple]:
        rows = [r for r, m in self._rows.items() for _ in range(m)]
        if not rows:
            return []
        cols = {n: np.array([r[i] for r in rows], dtype=object)
                for i, n in enumerate(self._data_names)}
        n = len(rows)
        # lexicographic sort by the ORDER BY list (last key least significant
        # -> apply in reverse with a stable sort)
        order = np.arange(n)
        for fn, desc in reversed(self._sort_fns):
            vals = np.asarray(fn(cols, n), dtype=np.float64)
            vals = vals[order]
            idx = np.argsort(-vals if desc else vals, kind="stable")
            order = order[idx]
        return [rows[i] for i in order[:self._limit]]

    def _emit_delta(self, ts: int) -> None:
        new = self._current_topn()
        old_set, new_set = set(self._emitted), set(new)
        out_rows: list[tuple] = []
        for r in self._emitted:
            if r not in new_set:
                out_rows.append(r + (int(rk.DELETE),))
        for r in new:
            if r not in old_set:
                out_rows.append(r + (int(rk.INSERT),))
        self._emitted = new
        if out_rows:
            self.output.emit(RecordBatch.from_rows(
                self._out_schema, out_rows, [ts] * len(out_rows)))

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"operator": {"rows": dict(self._rows),
                             "emitted": list(self._emitted)}}

    def initialize_state(self, keyed_snapshots: list, operator_snapshot) -> None:
        if operator_snapshot:
            self._rows = dict(operator_snapshot["rows"])
            self._emitted = list(operator_snapshot["emitted"])

