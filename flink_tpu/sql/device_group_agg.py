"""Device-lowered unbounded GROUP BY: changelog aggregation on HBM planes.

The device twin of sql/group_agg.GroupAggOperator (reference
GroupAggFunction.processElement:125, flink-table-runtime
operators/aggregate/GroupAggFunction.java): per group key, maintain
accumulators and emit UPDATE_BEFORE/UPDATE_AFTER (INSERT first, DELETE on
full retraction). Instead of one state read-modify-write per record, each
micro-batch runs ONE fused program on dense [capacity] float64 planes:

  hash-table lookup-or-insert -> gather PREV accumulator rows (first
  occurrence per slot) -> one scatter-fold per accumulator slot kind ->
  gather NEW rows -> reset drained groups to identities -> compact the
  distinct touched groups into [B]-bounded output buffers.

Host work per batch is one scalar sync (number of distinct groups) + one
prefix transfer + columnar changelog assembly over the distinct groups —
O(groups) instead of O(records), and groups per batch is bounded by the
batch size (for TPC-H Q1 it is 6).

Semantics match the host operator:
* SUM/COUNT/AVG retract exactly (additive folds with a sign column).
* MIN/MAX fold append-only (scatter-min/max ignores retraction), the same
  documented degradation as the host op; additionally a group fully
  retracted and later re-inserted restarts MIN/MAX from identities.
* a group whose retract-count drains to <= 0 emits DELETE of its last
  aggregate row and its planes reset, so re-insertion starts fresh.

Keys: integer key columns only (the graph planner routes others to the
host op). Composite keys combine with a 64-bit mix; the combined word is
what the hash table stores, so two distinct composites colliding in 64
bits would alias (probability ~n^2/2^65 — negligible at realistic key
counts; the host operator compares real tuples and has no such term).
Original key columns are recovered from the batch at emission, never from
the table.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.records import RecordBatch, Schema
from ..metrics.device import DEVICE_STATS, instrumented_program_cache, \
    pytree_nbytes
from ..runtime.operators.base import OneInputOperator, OperatorContext, Output
from ..state.tpu_backend import TpuKeyedStateBackend
from . import rowkind as rk
from .group_agg import SqlAggSpec, _SLOTS

__all__ = ["DeviceGroupAggOperator"]

_MIX = np.int64(np.uint64(0x9E3779B97F4A7C15).astype(np.int64))


def combine_key_columns(cols: Sequence[np.ndarray]) -> np.ndarray:
    """Deterministic 64-bit combine of integer key columns (single column
    passes through untouched => exact, collision-free)."""
    out = cols[0].astype(np.int64, copy=len(cols) > 1)
    for c in cols[1:]:
        out *= _MIX
        out += c.astype(np.int64)
        out ^= (out >> np.int64(29)) & np.int64(0x5555555555555555)
    return out


@instrumented_program_cache("sql.device_group_agg")
def _gagg_program(fold_sig: tuple, dirty_block: int):
    """ONE compiled program per batch for the whole group-agg hot path.
    ``fold_sig``: tuple of (plane_name, fold_kind, col_index) where
    fold_kind in sum|min|max and col_index indexes the stacked value
    columns (-1 = fold the sign itself, for COUNT slots)."""

    donate = (0, 1)

    @partial(jax.jit, donate_argnums=donate)
    def step(planes: dict, dirty, slots, sign, vals, n_valid):
        B = slots.shape[0]
        cap = planes["__rc__"].shape[0]
        # batches are padded to power-of-two lengths so ONE executable
        # serves every batch size (a WHERE upstream makes every batch a
        # unique length; without padding XLA recompiles per batch —
        # measured 15x slower than the fold itself). Pad rows alias a
        # real key for slot resolution and are masked out here.
        valid = (slots >= 0) & (jnp.arange(B) < n_valid)
        widx = jnp.where(valid, slots, cap).astype(jnp.int32)
        # first occurrence per touched slot (the group's emission row)
        firstpos = jnp.full(cap + 1, B, jnp.int32).at[widx].min(
            jnp.arange(B, dtype=jnp.int32))
        first = valid & (jnp.arange(B, dtype=jnp.int32) == firstpos[widx])
        gidx = jnp.maximum(slots, 0)
        prev = {n: planes[n][gidx] for n in planes}
        out = dict(planes)
        out["__rc__"] = planes["__rc__"].at[widx].add(
            jnp.where(valid, sign, 0.0), mode="drop")
        for name, kind, ci in fold_sig:
            v = sign if ci < 0 else vals[ci]
            if kind == "sum":
                out[name] = out[name].at[widx].add(
                    jnp.where(valid, v * sign if ci >= 0 else v, 0.0),
                    mode="drop")
            elif kind == "min":
                out[name] = out[name].at[widx].min(
                    jnp.where(valid, v, jnp.inf), mode="drop")
            else:
                out[name] = out[name].at[widx].max(
                    jnp.where(valid, v, -jnp.inf), mode="drop")
        new_rc = out["__rc__"][gidx]
        # drained groups (net count <= 0 after this batch): reset planes to
        # identities so a later re-insert starts fresh, like the host op's
        # state.clear() analog (reference GroupAggFunction emits -D and
        # clears)
        dead = valid & (new_rc <= 0)
        didx = jnp.where(dead, slots, cap).astype(jnp.int32)
        out["__rc__"] = out["__rc__"].at[didx].set(0.0, mode="drop")
        for name, kind, _ci in fold_sig:
            ident = (0.0 if kind == "sum"
                     else jnp.inf if kind == "min" else -jnp.inf)
            out[name] = out[name].at[didx].set(ident, mode="drop")
        new = {n: out[n][gidx] for n in planes}
        # compact the first-occurrence rows into [B]-bounded buffers
        pos = jnp.cumsum(first.astype(jnp.int32)) - 1
        tgt = jnp.where(first, pos, B)
        n_groups = jnp.sum(first.astype(jnp.int64))
        row_idx = jnp.zeros(B, jnp.int32).at[tgt].set(
            jnp.arange(B, dtype=jnp.int32), mode="drop")
        comp_prev = {n: jnp.zeros(B, planes[n].dtype).at[tgt].set(
            prev[n], mode="drop") for n in planes}
        comp_new = {n: jnp.zeros(B, planes[n].dtype).at[tgt].set(
            new[n], mode="drop") for n in planes}
        dirty = dirty.at[gidx // dirty_block].set(True)
        return out, dirty, n_groups, row_idx, comp_prev, comp_new

    return step


class DeviceGroupAggOperator(OneInputOperator):
    """Changelog GROUP BY on device accumulator planes (integer keys)."""

    def __init__(self, key_columns: Sequence[str], aggs: Sequence[SqlAggSpec],
                 capacity: int = 1 << 16,
                 name: str = "DeviceGroupAgg"):
        super().__init__(name)
        self._key_columns = list(key_columns)
        self._aggs = list(aggs)
        for a in self._aggs:
            if a.distinct:
                raise NotImplementedError(
                    "DISTINCT aggregates need per-key value sets")
        self._capacity = capacity
        self._backend: Optional[TpuKeyedStateBackend] = None
        self._out_schema: Optional[Schema] = None
        self._key_dtypes: Optional[list] = None
        # plane layout mirrors the host op's slot layout: __rc__ +
        # per-agg planes (avg = .sum/.cnt pair)
        self._plane_sig: list[tuple[str, str, Optional[str]]] = []
        for a in self._aggs:
            if a.kind == "count":
                # COUNT(col) == COUNT(*) on the device path: columns are
                # numeric, never null (host op: sign * ~is_null(col) with
                # is_null identically False for numeric dtypes) — fold the
                # SIGN, not the value
                self._plane_sig.append((a.out_name, "sum", None))
            elif a.kind in ("sum", "min", "max"):
                self._plane_sig.append((a.out_name, a.kind, a.field))
            else:  # avg
                self._plane_sig.append((f"{a.out_name}.sum", "sum", a.field))
                self._plane_sig.append((f"{a.out_name}.cnt", "sum", None))

    # -- lifecycle ---------------------------------------------------------
    def setup(self, ctx: OperatorContext, output: Output) -> None:
        super().setup(ctx, output)
        self._backend = TpuKeyedStateBackend(
            ctx.key_group_range, ctx.max_parallelism,
            capacity=self._capacity)
        self._backend.register_array_state("__rc__", "sum", jnp.float64)
        for name, kind, _field in self._plane_sig:
            self._backend.register_array_state(name, kind, jnp.float64)

    # -- data path ---------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        key_cols = [np.asarray(batch.column(c)) for c in self._key_columns]
        if self._key_dtypes is None:
            self._key_dtypes = [batch.schema.field(c).dtype
                                for c in self._key_columns]
            for c, d in zip(self._key_columns, self._key_dtypes):
                if d is object or not np.issubdtype(np.dtype(d), np.integer):
                    raise TypeError(
                        f"device group aggregation needs integer key "
                        f"columns; {c!r} is {d} — the planner should route "
                        "this query to the host GroupAggOperator")
        keys = combine_key_columns(key_cols)
        kinds = (np.asarray(batch.column(rk.ROWKIND_COLUMN)).astype(np.int8)
                 if rk.ROWKIND_COLUMN in batch.schema
                 else np.zeros(batch.n, np.int8))
        sign = np.where((kinds == rk.UPDATE_BEFORE) | (kinds == rk.DELETE),
                        -1.0, 1.0)
        # value columns stacked once: fold_sig indexes into this list
        col_names: list[str] = []
        fold_sig = []
        for name, kind, field in self._plane_sig:
            if field is None:
                fold_sig.append((name, kind, -1))
            else:
                if field not in col_names:
                    col_names.append(field)
                fold_sig.append((name, kind, col_names.index(field)))
        # pad to the next power of two: constant shapes -> one executable
        from ..ops.segment_ops import pow2_ceil

        n = batch.n
        P = pow2_ceil(n)
        pad = P - n

        def _padded(a: np.ndarray, fill) -> np.ndarray:
            if pad == 0:
                return a
            return np.concatenate([a, np.full(pad, fill, a.dtype)])

        # deadline-bounded sites (runtime/watchdog.py): idempotent upload
        # and materialization stall-retry in place; the step dispatch
        # visits its fault site inside the supervised call so an injected
        # hang abandoned by the watchdog never reaches the donating
        # program
        from ..runtime.watchdog import stall_bounded
        vals = stall_bounded(
            "transfer.h2d",
            lambda: tuple(jnp.asarray(_padded(
                np.asarray(batch.column(c), np.float64), 0.0))
                for c in col_names),
            scope="device_group_agg")
        DEVICE_STATS.note_h2d(pytree_nbytes(vals) + P * 8, n)  # vals + sign
        # pads alias the first real key: no new table slots, and the
        # program's n_valid mask keeps them out of every fold
        slots = self._backend.slots_for_batch(_padded(keys, keys[0]))

        def dispatch():
            step = _gagg_program(tuple(fold_sig),
                                 self._backend.dirty_block_size)
            planes = {"__rc__": self._backend.get_array("__rc__")}
            for name, _k, _f in self._plane_sig:
                planes[name] = self._backend.get_array(name)
            return step(
                planes, self._backend.dirty_mask, slots,
                jnp.asarray(_padded(sign, 0.0)), vals, np.int64(n))

        out, dirty, n_groups, row_idx, comp_prev, comp_new = stall_bounded(
            "device.execute", dispatch, scope="device_group_agg")
        for n, arr in out.items():
            self._backend.set_array(n, arr)
        self._backend.set_dirty_mask(dirty)
        # lint: sync-ok changelog-emit gate per batch; bounds the d2h slice
        g = int(jax.device_get(n_groups))
        if g == 0:
            return
        span = min(1 << (g - 1).bit_length() if g > 1 else 1, P)
        host = stall_bounded(
            "transfer.d2h",
            # lint: sync-ok group-agg changelog drain, one bounded d2h per batch
            lambda: jax.device_get({
                "idx": row_idx[:span],
                "prev": {n: v[:span] for n, v in comp_prev.items()},
                "new": {n: v[:span] for n, v in comp_new.items()}}),
            scope="device_group_agg")
        DEVICE_STATS.note_d2h(pytree_nbytes(host), g)
        self._emit_changelog(batch, key_cols, host, g)

    # -- emission ----------------------------------------------------------
    def _results(self, acc: dict, sel: np.ndarray) -> list[np.ndarray]:
        outs = []
        for a in self._aggs:
            if a.kind == "avg":
                s = acc[f"{a.out_name}.sum"][sel]
                c = acc[f"{a.out_name}.cnt"][sel]
                outs.append(np.where(c != 0, s / np.where(c == 0, 1, c),
                                     0.0))
            else:
                outs.append(acc[a.out_name][sel])
        return outs

    def _emit_changelog(self, batch: RecordBatch, key_cols: list,
                        host: dict, g: int) -> None:
        sel = np.arange(g)
        rows = np.asarray(host["idx"])[:g]
        prev_rc = np.asarray(host["prev"]["__rc__"])[:g]
        new_rc = np.asarray(host["new"]["__rc__"])[:g]
        was = prev_rc > 0
        now = new_rc > 0
        emit_a = was                        # UB (or D when drained)
        emit_b = now                        # UA (or I when first seen)
        if not (emit_a.any() or emit_b.any()):
            return
        kind_a = np.where(now, rk.UPDATE_BEFORE, rk.DELETE).astype(np.int8)
        kind_b = np.where(was, rk.UPDATE_AFTER, rk.INSERT).astype(np.int8)
        prev_vals = self._results(host["prev"], sel)
        new_vals = self._results(host["new"], sel)
        # interleave prev-rows at even, new-rows at odd positions, then
        # filter — keeps UB immediately before its UA, like the host op
        n2 = 2 * g
        mask = np.zeros(n2, bool)
        mask[0::2] = emit_a
        mask[1::2] = emit_b
        take = np.flatnonzero(mask)
        cols: dict[str, np.ndarray] = {}
        for i, cname in enumerate(self._key_columns):
            kv = key_cols[i][rows]
            inter = np.empty(n2, kv.dtype)
            inter[0::2] = kv
            inter[1::2] = kv
            cols[cname] = inter[take]
        for a, pv, nv in zip(self._aggs, prev_vals, new_vals):
            inter = np.empty(n2, np.float64)
            inter[0::2] = pv
            inter[1::2] = nv
            cols[a.out_name] = inter[take]
        kinds = np.empty(n2, np.int8)
        kinds[0::2] = kind_a
        kinds[1::2] = kind_b
        cols[rk.ROWKIND_COLUMN] = kinds[take]
        if self._out_schema is None:
            key_fields = [(n, d) for n, d in zip(self._key_columns,
                                                 self._key_dtypes)]
            agg_fields = [(a.out_name, np.float64) for a in self._aggs]
            self._out_schema = Schema(
                key_fields + agg_fields + [(rk.ROWKIND_COLUMN, np.int8)])
        ts = np.full(len(take), int(batch.timestamps.max()), np.int64)
        self.output.emit(RecordBatch(self._out_schema, cols, ts))

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"keyed": {"backend": self._backend.snapshot(checkpoint_id)}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        if keyed_snapshots:
            self._backend.restore([s["backend"] for s in keyed_snapshots])
