"""Changelog row kinds.

Analog of the reference's RowKind (flink-table-common
org/apache/flink/table/data/RowKind.java): every changelog-producing SQL
operator emits an extra int8 ``__rowkind__`` column. Append-only streams
simply have no such column.
"""

from __future__ import annotations

import numpy as np

__all__ = ["INSERT", "UPDATE_BEFORE", "UPDATE_AFTER", "DELETE",
           "ROWKIND_COLUMN", "ROWKIND_NAMES"]

INSERT = np.int8(0)         # +I
UPDATE_BEFORE = np.int8(1)  # -U
UPDATE_AFTER = np.int8(2)   # +U
DELETE = np.int8(3)         # -D

ROWKIND_COLUMN = "__rowkind__"
ROWKIND_NAMES = {0: "+I", 1: "-U", 2: "+U", 3: "-D"}
