"""SQL/Table layer: parser, planner, changelog operators, TableEnvironment.

The TPU-native counterpart of the reference's flink-table stack (SURVEY.md
§2.6): TableEnvironmentImpl.executeSql -> Calcite plan -> Janino codegen
becomes parse() -> plan() -> vectorized column closures over RecordBatches,
with keyed aggregations lowered to the device slice-window /
scatter-fold path where eligible.
"""

from .expressions import (
    AggCall, BinaryOp, Cast, CaseWhen, Column, Expr, ExprError, FuncCall,
    Literal, Star, UnaryOp, compile_expr,
)
from .group_agg import GroupAggOperator, SqlAggSpec
from .parser import SelectStmt, SqlError, TableRef, WindowTVF, parse
from .planner import PlanError, plan
from .rowkind import (
    DELETE, INSERT, ROWKIND_COLUMN, ROWKIND_NAMES, UPDATE_AFTER,
    UPDATE_BEFORE,
)
from .table_env import Table, TableEnvironment, TableResult
from .topn import TopNOperator

__all__ = [
    "TableEnvironment", "Table", "TableResult", "parse", "plan",
    "SelectStmt", "SqlError", "PlanError", "TableRef", "WindowTVF",
    "GroupAggOperator", "SqlAggSpec", "TopNOperator",
    "Expr", "Column", "Literal", "BinaryOp", "UnaryOp", "FuncCall", "Cast",
    "CaseWhen", "Star", "AggCall", "ExprError", "compile_expr",
    "ROWKIND_COLUMN", "ROWKIND_NAMES", "INSERT", "UPDATE_BEFORE",
    "UPDATE_AFTER", "DELETE",
]
