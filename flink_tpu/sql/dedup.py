"""Streaming deduplication (reference table-runtime
operators/deduplicate/{ProcTimeDeduplicateKeepFirstRowFunction,
RowTimeDeduplicateFunction} behind StreamExecDeduplicate).

keep="first": emit only the first row per key (append-only output).
keep="last": emit a changelog — +I for a key's first row, then -U(prev)/+U
(new) as later rows replace it (the reference's keep-last with
generateUpdateBefore=true).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..core.keygroups import assign_to_key_group
from ..core.records import RecordBatch, Schema, scalar as _scalar
from ..runtime.operators.base import OneInputOperator
from . import rowkind as rk

__all__ = ["DeduplicateOperator"]



class DeduplicateOperator(OneInputOperator):
    def __init__(self, key_index: int, keep: str = "first",
                 name: str = "Deduplicate"):
        super().__init__(name)
        if keep not in ("first", "last"):
            raise ValueError("keep must be 'first' or 'last'")
        self.key_index = key_index
        self.keep = keep
        # kg -> key -> stored row (keep=last) / True (keep=first)
        self._state: dict[int, dict[Any, Any]] = {}
        self._out_schema: Optional[Schema] = None

    def _ensure_schema(self, in_schema: Schema) -> Schema:
        if self._out_schema is None:
            fields = [(f.name, f.dtype) for f in in_schema.fields
                      if f.name != rk.ROWKIND_COLUMN]
            if self.keep == "last":
                fields.append((rk.ROWKIND_COLUMN, np.int8))
            self._out_schema = Schema(fields)
        return self._out_schema

    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        schema = self._ensure_schema(batch.schema)
        names = [f.name for f in batch.schema.fields
                 if f.name != rk.ROWKIND_COLUMN]
        cols = [batch.column(n) for n in names]
        kinds = (batch.column(rk.ROWKIND_COLUMN).astype(np.int8)
                 if rk.ROWKIND_COLUMN in batch.schema
                 else np.zeros(batch.n, np.int8))
        ts_arr = batch.timestamps
        out_rows, out_ts = [], []
        for i in range(batch.n):
            row = tuple(_scalar(c[i]) for c in cols)
            key = row[self.key_index]
            kg = assign_to_key_group(key, self.ctx.max_parallelism)
            kmap = self._state.setdefault(kg, {})
            ts = int(ts_arr[i])
            retract = kinds[i] in (rk.UPDATE_BEFORE, rk.DELETE)
            if self.keep == "first":
                # keep-first assumes append-only input (like the reference's
                # KeepFirstRowFunction); retractions are ignored
                if not retract and key not in kmap:
                    kmap[key] = True
                    out_rows.append(row)
                    out_ts.append(ts)
            elif retract:
                # retraction of the current row deletes the key's entry
                if kmap.get(key) == row:
                    del kmap[key]
                    out_rows.append(row + (int(rk.DELETE),))
                    out_ts.append(ts)
            else:
                prev = kmap.get(key)
                kmap[key] = row
                if prev is None:
                    out_rows.append(row + (int(rk.INSERT),))
                    out_ts.append(ts)
                elif prev != row:
                    out_rows.append(prev + (int(rk.UPDATE_BEFORE),))
                    out_ts.append(ts)
                    out_rows.append(row + (int(rk.UPDATE_AFTER),))
                    out_ts.append(ts)
        if out_rows:
            self.output.emit(RecordBatch.from_rows(schema, out_rows, out_ts))

    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"keyed": {"backend": {"dedup": {
            kg: dict(m) for kg, m in self._state.items()}}}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        for snap in keyed_snapshots:
            for kg, entries in snap.get("backend", {}).get("dedup",
                                                           {}).items():
                if kg in self.ctx.key_group_range:
                    self._state.setdefault(kg, {}).update(entries)
