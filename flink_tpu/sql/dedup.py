"""Streaming deduplication (reference table-runtime
operators/deduplicate/{ProcTimeDeduplicateKeepFirstRowFunction,
RowTimeDeduplicateFunction} behind StreamExecDeduplicate).

keep="first": emit only the first row per key (append-only output).
keep="last": emit a changelog — +I for a key's first row, then -U(prev)/+U
(new) as later rows replace it (the reference's keep-last with
generateUpdateBefore=true).

``ttl_ms`` bounds how long a key stays deduplicated (the reference's
table.exec.state.ttl): a key re-admits after the TTL passes.

With the "tpu" state backend and an integer key column, keep-first runs
on DEVICE: the whole batch is one fused admission program on the keyed
backend's typed row plane (hash lookup-or-insert + presence/TTL check +
first-in-batch resolution — TpuKeyedStateBackend.dedup_first_batch), so
dedup state lives in HBM and scales with the hash table, not a Python
dict. Device TTL is batch-granular: duplicates within one micro-batch
always deduplicate even across a TTL boundary (a batch spans
microseconds; TTLs span seconds). keep="last" needs previous row VALUES
for retractions and stays on the host plane.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..core.keygroups import assign_to_key_group
from ..core.records import RecordBatch, Schema, scalar as _scalar
from ..runtime.operators.base import OneInputOperator
from . import rowkind as rk

__all__ = ["DeduplicateOperator"]


class DeduplicateOperator(OneInputOperator):
    def __init__(self, key_index: int, keep: str = "first",
                 ttl_ms: Optional[int] = None,
                 name: str = "Deduplicate"):
        super().__init__(name)
        if keep not in ("first", "last"):
            raise ValueError("keep must be 'first' or 'last'")
        self.key_index = key_index
        self.keep = keep
        self.ttl_ms = int(ttl_ms) if ttl_ms else 0
        # host plane: kg -> key -> (admit_ts, row-or-True)
        self._state: dict[int, dict[Any, Any]] = {}
        self._out_schema: Optional[Schema] = None
        self._backend = None          # device plane (tpu backend)
        self._device_checked = False
        self._key_checked = False

    # -- device routing ----------------------------------------------------
    def _build_backend(self):
        b = self.ctx.create_keyed_backend()
        b.register_row_state("__seen__", np.int8, self.ttl_ms or None)
        if self._restored_device:
            b.restore(self._restored_device)
            self._restored_device = []
        if self._state:
            # host-plane entries restored from a hashmap-backend
            # checkpoint migrate into the device presence plane
            keys, admit_ts = [], []
            for kmap in self._state.values():
                for k, entry in kmap.items():
                    keys.append(int(k))
                    admit_ts.append(int(entry[0]))
            b.rows_upsert("__seen__", np.asarray(keys, np.int64),
                          np.ones(len(keys), np.int8),
                          now_ms=np.asarray(admit_ts, np.int64))
            self._state = {}
        return b

    def _device_backend(self, schema: Schema):
        """The tpu keyed backend when this operator can run its admission
        on device (keep-first + tpu backend + integer key column)."""
        if self._device_checked:
            return self._backend
        self._device_checked = True
        eligible = self.keep == "first"
        if eligible:
            from ..core.config import StateOptions
            eligible = self.ctx.config.get(StateOptions.BACKEND) == "tpu"
        if eligible:
            key_field = schema.fields[self.key_index]
            eligible = (key_field.dtype is not object and np.issubdtype(
                np.dtype(key_field.dtype), np.integer))
        if not eligible:
            if self._restored_device:
                raise RuntimeError(
                    "dedup state was checkpointed on the tpu backend but "
                    "this run cannot use the device path (backend/keep/"
                    "key-dtype changed); restore with the original config")
            return None
        self._backend = self._build_backend()
        return self._backend

    _restored_device: list = ()

    def _ensure_schema(self, in_schema: Schema) -> Schema:
        if self._out_schema is None:
            fields = [(f.name, f.dtype) for f in in_schema.fields
                      if f.name != rk.ROWKIND_COLUMN]
            if self.keep == "last":
                fields.append((rk.ROWKIND_COLUMN, np.int8))
            self._out_schema = Schema(fields)
        return self._out_schema

    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        schema = self._ensure_schema(batch.schema)
        names = [f.name for f in batch.schema.fields
                 if f.name != rk.ROWKIND_COLUMN]
        kinds = (batch.column(rk.ROWKIND_COLUMN).astype(np.int8)
                 if rk.ROWKIND_COLUMN in batch.schema
                 else np.zeros(batch.n, np.int8))
        retract = np.isin(kinds, (rk.UPDATE_BEFORE, rk.DELETE))
        backend = self._device_backend(batch.schema)
        if backend is not None:
            if not self._key_checked:
                # restored-eager path skipped the schema check: a key
                # column that stopped being integer must fail loudly, not
                # truncate
                kf = batch.schema.fields[self.key_index]
                if kf.dtype is object or not np.issubdtype(
                        np.dtype(kf.dtype), np.integer):
                    raise RuntimeError(
                        "dedup device state restored but the key column "
                        f"is {kf.dtype} (not integer); restore with the "
                        "original schema or the hashmap backend")
                self._key_checked = True
            # DEVICE keep-first: one fused admission program per batch
            keys = batch.column(names[self.key_index]).astype(np.int64)
            fresh = backend.dedup_first_batch(
                "__seen__", keys, batch.timestamps, valid=~retract)
            if fresh.any():
                self.output.emit(RecordBatch(
                    schema, {n: batch.column(n)[fresh] for n in names},
                    batch.timestamps[fresh]))
            return
        self._process_host(batch, schema, names, kinds)

    def _process_host(self, batch: RecordBatch, schema: Schema,
                      names: list, kinds: np.ndarray) -> None:
        cols = [batch.column(n) for n in names]
        ts_arr = batch.timestamps
        ttl = self.ttl_ms
        out_rows, out_ts = [], []
        for i in range(batch.n):
            row = tuple(_scalar(c[i]) for c in cols)
            key = row[self.key_index]
            kg = assign_to_key_group(key, self.ctx.max_parallelism)
            kmap = self._state.setdefault(kg, {})
            ts = int(ts_arr[i])
            retract = kinds[i] in (rk.UPDATE_BEFORE, rk.DELETE)
            if self.keep == "first":
                # keep-first assumes append-only input (like the reference's
                # KeepFirstRowFunction); retractions are ignored
                if retract:
                    continue
                entry = kmap.get(key)
                expired = (entry is not None and ttl
                           and ts - entry[0] > ttl)
                if entry is None or expired:
                    kmap[key] = (ts, True)
                    out_rows.append(row)
                    out_ts.append(ts)
            elif retract:
                # retraction of the current row deletes the key's entry
                entry = kmap.get(key)
                if entry is not None and entry[1] == row:
                    del kmap[key]
                    out_rows.append(row + (int(rk.DELETE),))
                    out_ts.append(ts)
            else:
                entry = kmap.get(key)
                prev = entry[1] if entry is not None else None
                if entry is not None and ttl and ts - entry[0] > ttl:
                    prev = None
                kmap[key] = (ts, row)
                if prev is None:
                    out_rows.append(row + (int(rk.INSERT),))
                    out_ts.append(ts)
                elif prev != row:
                    out_rows.append(prev + (int(rk.UPDATE_BEFORE),))
                    out_ts.append(ts)
                    out_rows.append(row + (int(rk.UPDATE_AFTER),))
                    out_ts.append(ts)
        if out_rows:
            self.output.emit(RecordBatch.from_rows(schema, out_rows, out_ts))

    def snapshot_state(self, checkpoint_id: int) -> dict:
        if self._backend is not None:
            return {"keyed": {"backend": self._backend.snapshot(
                checkpoint_id)}}
        return {"keyed": {"backend": {"dedup2": {
            kg: dict(m) for kg, m in self._state.items()}}}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        device_snaps = []
        for snap in keyed_snapshots:
            table = snap.get("backend", {})
            if table.get("kind") == "tpu":
                device_snaps.append(table)
                continue
            for kg, entries in table.get("dedup2", {}).items():
                if kg in self.ctx.key_group_range:
                    self._state.setdefault(kg, {}).update(entries)
            for kg, entries in table.get("dedup", {}).items():
                # pre-TTL snapshot format: entries lack the admit ts
                if kg in self.ctx.key_group_range:
                    self._state.setdefault(kg, {}).update(
                        {k: (0, v) for k, v in entries.items()})
        if device_snaps:
            # build + restore EAGERLY: a checkpoint taken before the first
            # batch must re-emit this state, not an empty host plane.
            # Validate the config FIRST — a keep/backend change cannot
            # silently reinterpret device keep-first state.
            from ..core.config import StateOptions
            if (self.keep != "first"
                    or self.ctx.config.get(StateOptions.BACKEND) != "tpu"):
                raise RuntimeError(
                    "dedup state was checkpointed on the tpu backend but "
                    "this run cannot use the device path (backend or keep "
                    "changed); restore with the original config")
            self._restored_device = device_snaps
            self._backend = self._build_backend()
            self._device_checked = True