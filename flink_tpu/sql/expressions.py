"""SQL expression AST + vectorized compiler.

The analog of the reference planner's Janino expression codegen
(flink-table-planner codegen/ExprCodeGenerator et al.): instead of emitting
Java source per query, every scalar expression compiles to a closure over
whole columns — ``fn(cols: dict[str, np.ndarray], n: int) -> np.ndarray`` —
so one call evaluates the expression for an entire micro-batch, and numeric
expressions stay jax-traceable for fusion into the device step.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

import numpy as np

__all__ = [
    "Expr", "Column", "Literal", "BinaryOp", "UnaryOp", "FuncCall", "Cast",
    "CaseWhen", "Star", "AggCall", "compile_expr", "collect_columns",
    "collect_aggs", "rewrite_expr", "ExprError",
]


def rewrite_expr(e: "Expr", fn) -> "Expr":
    """Bottom-up structural rewrite: apply ``fn`` to every node after its
    children have been rewritten (the planner's column-resolution hook)."""
    if isinstance(e, BinaryOp):
        e = BinaryOp(e.op, rewrite_expr(e.left, fn), rewrite_expr(e.right, fn))
    elif isinstance(e, UnaryOp):
        e = UnaryOp(e.op, rewrite_expr(e.operand, fn))
    elif isinstance(e, FuncCall):
        e = FuncCall(e.name, tuple(rewrite_expr(a, fn) for a in e.args))
    elif isinstance(e, Cast):
        e = Cast(rewrite_expr(e.operand, fn), e.type_name)
    elif isinstance(e, CaseWhen):
        e = CaseWhen(tuple((rewrite_expr(c, fn), rewrite_expr(v, fn))
                           for c, v in e.branches),
                     rewrite_expr(e.default, fn)
                     if e.default is not None else None)
    elif isinstance(e, AggCall):
        e = AggCall(e.kind,
                    rewrite_expr(e.arg, fn) if e.arg is not None else None,
                    e.distinct)
    return fn(e)


class ExprError(ValueError):
    pass


@dataclass(frozen=True)
class Expr:
    pass


@dataclass(frozen=True)
class Column(Expr):
    name: str
    table: Optional[str] = None  # qualifier (alias) for multi-table queries


@dataclass(frozen=True)
class Literal(Expr):
    value: Any


@dataclass(frozen=True)
class BinaryOp(Expr):
    op: str
    left: Expr
    right: Expr


@dataclass(frozen=True)
class UnaryOp(Expr):
    op: str          # "-" | "NOT"
    operand: Expr


@dataclass(frozen=True)
class FuncCall(Expr):
    name: str
    args: tuple


@dataclass(frozen=True)
class Cast(Expr):
    operand: Expr
    type_name: str


@dataclass(frozen=True)
class CaseWhen(Expr):
    branches: tuple      # ((cond, value), ...)
    default: Optional[Expr]


@dataclass(frozen=True)
class Star(Expr):
    pass


@dataclass(frozen=True)
class AggCall(Expr):
    """Aggregate call site (SUM/COUNT/MIN/MAX/AVG). ``arg`` is None for
    COUNT(*). The planner hoists these out of select/having expressions;
    they never reach compile_expr."""
    kind: str
    arg: Optional[Expr]
    distinct: bool = False


_BINOPS: dict[str, Callable] = {
    "+": np.add, "-": np.subtract, "*": np.multiply,
    "/": np.divide, "%": np.mod,
    "=": np.equal, "<>": np.not_equal, "!=": np.not_equal,
    "<": np.less, "<=": np.less_equal,
    ">": np.greater, ">=": np.greater_equal,
    "AND": np.logical_and, "OR": np.logical_or,
}

_CAST_TYPES = {
    "INT": np.int64, "INTEGER": np.int64, "BIGINT": np.int64,
    "FLOAT": np.float64, "DOUBLE": np.float64,
    "BOOLEAN": np.bool_, "VARCHAR": object, "STRING": object,
}


def _vec_str(fn: Callable) -> Callable:
    u = np.frompyfunc(fn, 1, 1)

    def apply(x):
        return u(x.astype(object) if x.dtype != object else x)
    return apply


_FUNCS: dict[str, Callable] = {
    "ABS": lambda a: np.abs(a),
    "MOD": lambda a, b: np.mod(a, b),
    "FLOOR": lambda a: np.floor(a),
    "CEIL": lambda a: np.ceil(a),
    "CEILING": lambda a: np.ceil(a),
    "SQRT": lambda a: np.sqrt(a),
    "POWER": lambda a, b: np.power(a, b),
    "LN": lambda a: np.log(a),
    "EXP": lambda a: np.exp(a),
    "ROUND": lambda a, *d: np.round(a, int(d[0][0]) if d else 0),
    "GREATEST": lambda *a: np.maximum.reduce(list(a)),
    "LEAST": lambda *a: np.minimum.reduce(list(a)),
    "LOWER": _vec_str(lambda s: s.lower()),
    "UPPER": _vec_str(lambda s: s.upper()),
    "CHAR_LENGTH": _vec_str(len),
    "CONCAT": lambda *a: np.frompyfunc(
        lambda *xs: "".join(str(x) for x in xs), len(a), 1)(*a),
    "COALESCE": lambda *a: _coalesce(*a),
}


def _coalesce(*arrays):
    out = np.array(arrays[0], dtype=object, copy=True)
    for arr in arrays[1:]:
        missing = np.array([v is None for v in out], dtype=bool)
        if not missing.any():
            break
        out[missing] = np.asarray(arr, dtype=object)[missing]
    return out


def collect_columns(e: Expr, out: set[str]) -> None:
    """All column names referenced by ``e`` (including inside aggregates)."""
    if isinstance(e, Column):
        out.add(e.name)
    elif isinstance(e, BinaryOp):
        collect_columns(e.left, out)
        collect_columns(e.right, out)
    elif isinstance(e, UnaryOp):
        collect_columns(e.operand, out)
    elif isinstance(e, FuncCall):
        for a in e.args:
            collect_columns(a, out)
    elif isinstance(e, Cast):
        collect_columns(e.operand, out)
    elif isinstance(e, CaseWhen):
        for c, v in e.branches:
            collect_columns(c, out)
            collect_columns(v, out)
        if e.default is not None:
            collect_columns(e.default, out)
    elif isinstance(e, AggCall) and e.arg is not None:
        collect_columns(e.arg, out)


def collect_aggs(e: Expr, out: list[AggCall]) -> None:
    """All AggCall nodes in ``e`` in evaluation order (dedup by identity of
    the (kind, arg) pair so SUM(x)+SUM(x) shares one accumulator)."""
    if isinstance(e, AggCall):
        if e not in out:
            out.append(e)
    elif isinstance(e, BinaryOp):
        collect_aggs(e.left, out)
        collect_aggs(e.right, out)
    elif isinstance(e, UnaryOp):
        collect_aggs(e.operand, out)
    elif isinstance(e, FuncCall):
        for a in e.args:
            collect_aggs(a, out)
    elif isinstance(e, Cast):
        collect_aggs(e.operand, out)
    elif isinstance(e, CaseWhen):
        for c, v in e.branches:
            collect_aggs(c, out)
            collect_aggs(v, out)
        if e.default is not None:
            collect_aggs(e.default, out)


def compile_expr(e: Expr, agg_slots: Optional[dict] = None) -> Callable:
    """Expr -> fn(cols, n) -> np.ndarray.

    ``agg_slots`` maps AggCall -> column name; the planner uses it to
    compile post-aggregation expressions (select items over agg results)
    where each aggregate has been materialized as a column.
    """
    if isinstance(e, AggCall):
        if agg_slots is None or e not in agg_slots:
            raise ExprError(f"aggregate {e.kind} not allowed here")
        slot = agg_slots[e]
        return lambda cols, n: cols[slot]
    if isinstance(e, Column):
        name = e.name
        def col(cols, n):
            if name not in cols:
                raise ExprError(f"unknown column {name!r}")
            return cols[name]
        return col
    if isinstance(e, Literal):
        v = e.value
        def lit(cols, n):
            if isinstance(v, bool):
                return np.full(n, v, dtype=np.bool_)
            if isinstance(v, int):
                return np.full(n, v, dtype=np.int64)
            if isinstance(v, float):
                return np.full(n, v, dtype=np.float64)
            if v is None:
                return np.full(n, None, dtype=object)
            return np.full(n, v, dtype=object)
        return lit
    if isinstance(e, BinaryOp):
        fn = _BINOPS.get(e.op)
        if fn is None:
            raise ExprError(f"unsupported operator {e.op!r}")
        lf = compile_expr(e.left, agg_slots)
        rf = compile_expr(e.right, agg_slots)
        op = e.op
        def bin_(cols, n):
            a, b = lf(cols, n), rf(cols, n)
            if op in ("=", "<>", "!=") and (a.dtype == object
                                            or b.dtype == object):
                return (np.asarray(a, object) == np.asarray(b, object)
                        if op == "=" else
                        np.asarray(a, object) != np.asarray(b, object))
            return fn(a, b)
        return bin_
    if isinstance(e, UnaryOp):
        of = compile_expr(e.operand, agg_slots)
        if e.op == "-":
            return lambda cols, n: np.negative(of(cols, n))
        if e.op == "NOT":
            return lambda cols, n: np.logical_not(of(cols, n))
        raise ExprError(f"unsupported unary {e.op!r}")
    if isinstance(e, FuncCall):
        fn = _FUNCS.get(e.name)
        if fn is None:
            raise ExprError(f"unknown function {e.name!r}")
        arg_fns = [compile_expr(a, agg_slots) for a in e.args]
        return lambda cols, n: fn(*(f(cols, n) for f in arg_fns))
    if isinstance(e, Cast):
        of = compile_expr(e.operand, agg_slots)
        ty = _CAST_TYPES.get(e.type_name.upper())
        if ty is None:
            raise ExprError(f"unknown cast type {e.type_name!r}")
        if ty is object:
            return lambda cols, n: np.array(
                [str(v) for v in of(cols, n)], dtype=object)
        return lambda cols, n: of(cols, n).astype(ty)
    if isinstance(e, CaseWhen):
        branch_fns = [(compile_expr(c, agg_slots), compile_expr(v, agg_slots))
                      for c, v in e.branches]
        default_fn = (compile_expr(e.default, agg_slots)
                      if e.default is not None else None)
        def case(cols, n):
            vals = [vf(cols, n) for _, vf in branch_fns]
            default = (default_fn(cols, n) if default_fn is not None
                       else np.zeros(n, dtype=np.asarray(vals[0]).dtype))
            out = np.array(default, copy=True)
            taken = np.zeros(n, dtype=bool)
            for (cf, _), val in zip(branch_fns, vals):
                cond = cf(cols, n).astype(bool) & ~taken
                out[cond] = np.asarray(val)[cond] if np.ndim(val) else val
                taken |= cond
            return out
        return case
    raise ExprError(f"cannot compile {e!r}")
