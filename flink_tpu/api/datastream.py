"""DataStream API: the fluent stream-building surface.

Analog of flink-streaming-java's DataStream family
(api/datastream/DataStream.java — map:591, keyBy:291, transform:1178;
KeyedStream, WindowedStream, ConnectedStreams, side outputs). Builds a lazy
Transformation DAG; ``StreamExecutionEnvironment.execute`` compiles and runs
it.

Key selectors may be a column name (vectorized hashing — preferred) or a
row callable.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Sequence, Union

import numpy as np

from ..core.functions import (
    AggregateFunction, BuiltinAggregate, ProcessFunction, SinkFunction,
    as_filter, as_flat_map, as_map, as_reduce,
)
from ..core.records import RecordBatch, Schema
from ..graph.transformations import (
    OneInputTransformation, PartitionTransformation, SideOutputTransformation,
    SinkTransformation, SourceTransformation, Transformation,
    TwoInputTransformation, UnionTransformation,
)
from ..window.assigners import (
    EventTimeSessionWindows, GlobalWindows, SlidingEventTimeWindows,
    TumblingEventTimeWindows, WindowAssigner,
)
from ..window.triggers import CountTrigger, Evictor, PurgingTrigger, Trigger

__all__ = ["DataStream", "KeyedStream", "WindowedStream", "ConnectedStreams",
           "BroadcastStream", "BroadcastConnectedStream",
           "make_key_extractor"]

KeySpec = Union[str, Callable[[Any], Any]]


def make_key_extractor(key: KeySpec):
    """RecordBatch -> np.ndarray of per-row keys."""
    if isinstance(key, str):
        def extract_col(batch: RecordBatch) -> np.ndarray:
            return batch.column(key)
        extract_col.column = key  # vectorizable marker
        return extract_col

    fn = key
    if getattr(fn, "vectorized", False):
        # already a batch-level extractor (RecordBatch -> ndarray); routing
        # then hashes the array it returns, so a caller that needs exchange
        # routing to agree with a backend's own key hashing (the device
        # GROUP BY combined-word keys) can guarantee it by returning the
        # exact key array the backend stores
        return fn

    def extract_fn(batch: RecordBatch) -> np.ndarray:
        return np.array([fn(r) for r in batch.iter_rows()], dtype=object)
    return extract_fn


class DataStream:
    def __init__(self, env, transformation: Transformation):
        self.env = env
        self.transformation = transformation

    # -- basic transforms --------------------------------------------------
    def _one_input(self, name: str, factory, parallelism=None,
                   key_extractor=None, schema=None, traceable=False,
                   chaining_allowed=True) -> "DataStream":
        t = OneInputTransformation(
            name=name, operator_factory=factory,
            parallelism=parallelism,
            schema=schema, inputs=[self.transformation],
            key_extractor=key_extractor, traceable=traceable,
            chaining_allowed=chaining_allowed)
        self.env._transformations.append(t)
        return DataStream(self.env, t)

    def map(self, fn, name: str = "Map", out_schema: Optional[Schema] = None,
            parallelism: Optional[int] = None) -> "DataStream":
        """Per-row transform. When the function returns tuples with the SAME
        arity as the input, output columns inherit the input's column names
        (so key_by("col") keeps working across enrichment-style maps); a map
        that reorders/replaces fields should pass ``out_schema`` to name the
        outputs correctly."""
        mf = as_map(fn)
        from ..runtime.operators.simple import MapOperator
        return self._one_input(
            name, lambda: MapOperator(mf, out_schema, name), parallelism)

    def flat_map(self, fn, name: str = "FlatMap",
                 out_schema: Optional[Schema] = None,
                 parallelism: Optional[int] = None) -> "DataStream":
        ff = as_flat_map(fn)
        from ..runtime.operators.simple import FlatMapOperator
        return self._one_input(
            name, lambda: FlatMapOperator(ff, out_schema, name), parallelism)

    def filter(self, fn, name: str = "Filter",
               parallelism: Optional[int] = None) -> "DataStream":
        pf = as_filter(fn)
        from ..runtime.operators.simple import FilterOperator
        return self._one_input(name, lambda: FilterOperator(pf, name),
                               parallelism)

    def transform(self, name: str, operator_factory,
                  parallelism: Optional[int] = None,
                  traceable: bool = False) -> "DataStream":
        """Escape hatch: attach a custom operator (reference transform:1178)."""
        return self._one_input(name, operator_factory, parallelism,
                               traceable=traceable)

    def process(self, fn: ProcessFunction, name: str = "Process",
                parallelism: Optional[int] = None) -> "DataStream":
        """Non-keyed process function (no keyed state access)."""
        from ..runtime.operators.simple import KeyedProcessOperator

        def extract(batch: RecordBatch) -> np.ndarray:
            return np.zeros(batch.n, dtype=np.int64)  # single pseudo-key

        return self._one_input(name, lambda: KeyedProcessOperator(fn, extract,
                                                                  name=name),
                               parallelism)

    def async_io(self, fn, capacity: int = 100,
                 timeout_ms: Optional[int] = None, mode: str = "ordered",
                 retry=None, on_timeout: str = "fail",
                 out_schema: Optional[Schema] = None,
                 parallelism: Optional[int] = None,
                 name: str = "AsyncIO") -> "DataStream":
        """Asynchronous external lookups (reference AsyncDataStream
        .orderedWait/unorderedWait -> AsyncWaitOperator). ``fn`` is an
        AsyncFunction (runtime/operators/async_io.py); each subtask gets
        its own copy, so open resources (thread pools, clients) in
        ``open()``, not ``__init__`` — the reference RichFunction
        pattern."""
        from ..core.functions import copy_per_subtask as make_fn_base
        from ..runtime.operators.async_io import AsyncWaitOperator

        def make_fn():
            return make_fn_base(fn)

        return self._one_input(
            name, lambda: AsyncWaitOperator(
                make_fn(), capacity=capacity, timeout_ms=timeout_ms,
                mode=mode, retry=retry, on_timeout=on_timeout,
                out_schema=out_schema, name=name),
            parallelism=parallelism)

    # -- keying / partitioning --------------------------------------------
    def key_by(self, key: KeySpec) -> "KeyedStream":
        from ..runtime.writer import KeyGroupPartitioner
        extractor = make_key_extractor(key)
        maxp = self.env.max_parallelism
        t = PartitionTransformation(
            name="keyed-exchange",
            partitioner_factory=lambda: KeyGroupPartitioner(extractor, maxp),
            partitioner_name="hash",
            inputs=[self.transformation])
        self.env._transformations.append(t)
        return KeyedStream(self.env, t, extractor, key)

    def _repartition(self, name: str, factory) -> "DataStream":
        t = PartitionTransformation(
            name=name, partitioner_factory=factory, partitioner_name=name,
            inputs=[self.transformation])
        self.env._transformations.append(t)
        return DataStream(self.env, t)

    def rebalance(self) -> "DataStream":
        from ..runtime.writer import RebalancePartitioner
        return self._repartition("rebalance", RebalancePartitioner)

    def rescale(self) -> "DataStream":
        from ..runtime.writer import RescalePartitioner
        return self._repartition("rescale", RescalePartitioner)

    def broadcast(self, *descriptors) -> "DataStream":
        """Replicate every record to every downstream subtask. With
        MapStateDescriptors the result is a BroadcastStream for the
        broadcast state pattern: ``keyed.connect(rules.broadcast(desc))
        .process(KeyedBroadcastProcessFunction)`` (reference
        DataStream.broadcast(MapStateDescriptor...) ->
        BroadcastConnectedStream.java:55)."""
        from ..runtime.writer import BroadcastPartitioner
        replicated = self._repartition("broadcast", BroadcastPartitioner)
        if descriptors:
            return BroadcastStream(self.env, replicated.transformation,
                                   descriptors)
        return replicated

    def shuffle(self) -> "DataStream":
        from ..runtime.writer import ShufflePartitioner
        return self._repartition("shuffle", ShufflePartitioner)

    def global_(self) -> "DataStream":
        from ..runtime.writer import GlobalPartitioner
        return self._repartition("global", GlobalPartitioner)

    def forward(self) -> "DataStream":
        from ..runtime.writer import ForwardPartitioner
        return self._repartition("forward", ForwardPartitioner)

    def partition_custom(self, fn: Callable[[Any, int], int],
                         key: KeySpec) -> "DataStream":
        from ..runtime.writer import CustomPartitioner
        extractor = make_key_extractor(key)
        return self._repartition(
            "custom", lambda: CustomPartitioner(fn, extractor))

    # -- unions / connect --------------------------------------------------
    def union(self, *others: "DataStream") -> "DataStream":
        t = UnionTransformation(
            name="union",
            inputs=[self.transformation] + [o.transformation for o in others])
        self.env._transformations.append(t)
        return DataStream(self.env, t)

    def connect(self, other: "DataStream") -> "ConnectedStreams":
        if isinstance(other, BroadcastStream):
            raise NotImplementedError(
                "broadcast state requires a KEYED stream: use "
                "ds.key_by(...).connect(rules.broadcast(desc)) with a "
                "KeyedBroadcastProcessFunction (the non-keyed "
                "BroadcastProcessFunction variant is not implemented; "
                "silently dropping the state descriptors would run the "
                "job with no broadcast state at all)")
        return ConnectedStreams(self.env, self, other)

    def iterate(self, max_wait_s: float = 2.0) -> "IterativeStream":
        """Open a feedback loop (reference DataStream.iterate +
        StreamIterationHead/Tail): build the loop body on the returned
        stream, then ``close_with(feedback_stream)`` to route records back
        into the head. The head terminates once this stream's regular
        input finished and the loop stayed quiet for ``max_wait_s``.
        ``max_wait_s`` must exceed the body's worst-case per-batch latency
        — records still being processed inside the body when the window
        expires are lost (the reference iteration head has the same
        timeout semantics). Iterations are not checkpointable (deploy
        rejects the combination with periodic checkpointing, matching the
        reference's exclusion of loop state from exactly-once
        guarantees)."""
        from ..graph.transformations import FeedbackTransformation
        t = FeedbackTransformation(name="iteration",
                                   inputs=[self.transformation],
                                   max_wait_s=max_wait_s)
        self.env._transformations.append(t)
        return IterativeStream(self.env, t)

    # -- side outputs ------------------------------------------------------
    def get_side_output(self, tag: str) -> "DataStream":
        t = SideOutputTransformation(name=f"side-{tag}", tag=tag,
                                     inputs=[self.transformation])
        self.env._transformations.append(t)
        return DataStream(self.env, t)

    # -- windows (non-keyed) ----------------------------------------------
    def window_all(self, assigner: WindowAssigner) -> "WindowedStream":
        """All-windows: single pseudo-key, parallelism forced to 1."""
        keyed = self.global_().key_by(lambda _row: 0)
        return WindowedStream(keyed, assigner, all_windows=True)

    # -- sinks -------------------------------------------------------------
    def add_sink(self, sink, name: str = "Sink",
                 parallelism: Optional[int] = None) -> "DataStream":
        from ..connectors.core import Sink
        from ..runtime.operators.sink import FunctionSinkOperator, SinkOperator
        if isinstance(sink, Sink):
            factory = lambda: SinkOperator(sink, name)  # noqa: E731
        elif isinstance(sink, SinkFunction):
            factory = lambda: FunctionSinkOperator(sink, name)  # noqa: E731
        else:
            raise TypeError("add_sink expects a Sink or SinkFunction")
        t = SinkTransformation(name=name, operator_factory=factory,
                               parallelism=parallelism,
                               inputs=[self.transformation])
        self.env._transformations.append(t)
        self.env._sinks.append(t)
        return self

    def sink_to(self, sink, name: str = "Sink",
                parallelism: Optional[int] = None) -> "DataStream":
        return self.add_sink(sink, name, parallelism)

    def print(self, prefix: str = "") -> "DataStream":
        from ..connectors.core import PrintSink
        return self.add_sink(PrintSink(prefix), "Print")

    def execute_and_collect(self, job_name: str = "collect") -> list:
        from ..connectors.core import CollectSink
        sink = CollectSink()
        self.add_sink(sink, "Collect")
        self.env.execute(job_name)
        return sink.rows

    # -- misc --------------------------------------------------------------
    def set_parallelism(self, parallelism: int) -> "DataStream":
        self.transformation.parallelism = parallelism
        return self

    def uid(self, uid: str) -> "DataStream":
        self.transformation.uid = uid
        return self

    def name(self, name: str) -> "DataStream":
        self.transformation.name = name
        return self

    def disable_chaining(self) -> "DataStream":
        self.transformation.chaining_allowed = False
        return self

    def slot_sharing_group(self, group: str) -> "DataStream":
        self.transformation.slot_sharing_group = group
        return self

    def assign_timestamps_and_watermarks(self, ws) -> "DataStream":
        """Mid-stream watermark assignment (reference
        assignTimestampsAndWatermarks)."""
        from ..runtime.operators.simple import BatchFnOperator
        from ..core.elements import Watermark
        from ..runtime.operators.base import OneInputOperator

        class _WmOperator(OneInputOperator):
            def __init__(self):
                super().__init__("TimestampsWatermarks")
                self._gen = ws.create_generator()

            def process_batch(self, batch):
                batch = ws.assign_timestamps(batch)
                self._gen.on_batch(batch)
                self.output.emit(batch)
                wm = self._gen.current_watermark()
                if wm > self.current_watermark:
                    self.current_watermark = wm
                    self.output.emit_watermark(Watermark(wm))

            def process_watermark(self, watermark):
                pass  # replaced by generated watermarks

        return self._one_input("TimestampsWatermarks", _WmOperator)


class IterativeStream(DataStream):
    """Head of a feedback loop; ``close_with`` registers the back edge."""

    def close_with(self, feedback: "DataStream") -> "DataStream":
        """Route ``feedback``'s records back into the loop head; returns
        ``feedback`` so the terminating/output branch can continue from it
        (reference IterativeStream.closeWith)."""
        self.transformation.feedback_inputs.append(feedback.transformation)
        return feedback


class BroadcastStream:
    """A broadcast-partitioned stream bound to the MapStateDescriptors of
    the broadcast state it will feed (reference BroadcastStream)."""

    def __init__(self, env, transformation: Transformation, descriptors):
        self.env = env
        self.transformation = transformation
        self.descriptors = list(descriptors)


class BroadcastConnectedStream:
    """Keyed stream + broadcast stream awaiting a
    KeyedBroadcastProcessFunction (reference
    BroadcastConnectedStream.java:55)."""

    def __init__(self, env, keyed: "KeyedStream",
                 broadcast: BroadcastStream):
        self.env = env
        self.keyed = keyed
        self.broadcast = broadcast

    def process(self, fn, name: str = "CoBroadcastWithKeyed",
                out_schema: Optional[Schema] = None,
                parallelism: Optional[int] = None) -> "DataStream":
        from ..runtime.operators.co_broadcast import (
            CoBroadcastWithKeyedOperator,
        )

        ke = self.keyed.key_extractor
        descs = tuple(self.broadcast.descriptors)
        t = TwoInputTransformation(
            name=name,
            operator_factory=lambda: CoBroadcastWithKeyedOperator(
                fn, ke, descs, out_schema=out_schema, name=name),
            parallelism=parallelism,
            inputs=[self.keyed.transformation,
                    self.broadcast.transformation],
            key_extractor1=ke)
        self.env._transformations.append(t)
        return DataStream(self.env, t)


class KeyedStream(DataStream):
    def __init__(self, env, transformation: Transformation, key_extractor,
                 key_spec: KeySpec):
        super().__init__(env, transformation)
        self.key_extractor = key_extractor
        self.key_spec = key_spec

    def connect(self, other) -> "ConnectedStreams":
        if isinstance(other, BroadcastStream):
            return BroadcastConnectedStream(self.env, self, other)
        return ConnectedStreams(self.env, self, other)

    def process(self, fn: ProcessFunction, name: str = "KeyedProcess",
                parallelism: Optional[int] = None) -> "DataStream":
        from ..runtime.operators.simple import KeyedProcessOperator
        ke = self.key_extractor
        return self._one_input(
            name, lambda: KeyedProcessOperator(fn, ke, name=name),
            parallelism, key_extractor=ke)

    # -- rolling (non-windowed) aggregation -------------------------------
    def reduce(self, fn, name: str = "KeyedReduce") -> "DataStream":
        rf = as_reduce(fn)
        ke = self.key_extractor

        from ..core.functions import ProcessFunction as PF
        from ..runtime.operators.simple import KeyedProcessOperator
        from ..state.descriptors import ReducingStateDescriptor

        class _RollingReduce(PF):
            def open(self, ctx):
                self._desc = ReducingStateDescriptor("rolling-reduce", rf)
                self._ctx = ctx

            def process_element(self, value, ctx, out):
                state = self._ctx.get_state(self._desc)
                state.add(value)
                out.collect(state.get(), ctx.timestamp)

        return self._one_input(
            name, lambda: KeyedProcessOperator(_RollingReduce(), ke, name=name),
            key_extractor=ke)

    def sum(self, field: Union[str, int], name: str = "KeyedSum") -> "DataStream":
        return self._rolling_builtin("sum", field, name)

    def min(self, field: Union[str, int], name: str = "KeyedMin") -> "DataStream":
        return self._rolling_builtin("min", field, name)

    def max(self, field: Union[str, int], name: str = "KeyedMax") -> "DataStream":
        return self._rolling_builtin("max", field, name)

    def _rolling_builtin(self, kind: str, field, name: str) -> "DataStream":
        import operator as _op
        pick = (_op.itemgetter(field) if isinstance(field, int)
                else _op.itemgetter(field))

        def combine(a, b):
            va, vb = pick(a), pick(b)
            if kind == "sum":
                v = va + vb
            elif kind == "min":
                v = min(va, vb)
            else:
                v = max(va, vb)
            # keep latest record's other fields, replace aggregated field
            if isinstance(b, tuple):
                out = list(b)
                out[field if isinstance(field, int) else 0] = v
                return tuple(out)
            return v

        if isinstance(field, str):
            raise NotImplementedError(
                "string fields on rolling agg need tuple index; use window "
                "aggregation or pass an int index")
        return self.reduce(combine, name)

    # -- windows -----------------------------------------------------------
    def window(self, assigner: WindowAssigner) -> "WindowedStream":
        return WindowedStream(self, assigner)

    def count_window(self, size: int) -> "WindowedStream":
        return WindowedStream(self, GlobalWindows.create(),
                              trigger=PurgingTrigger.of(CountTrigger.of(size)))


class WindowedStream:
    """(reference WindowedStream): keyed stream + assigner + trigger/evictor
    builder, terminating in reduce/aggregate/apply."""

    def __init__(self, keyed: KeyedStream, assigner: WindowAssigner,
                 trigger: Optional[Trigger] = None,
                 evictor: Optional[Evictor] = None, all_windows: bool = False):
        self.keyed = keyed
        self.assigner = assigner
        self._trigger = trigger
        self._evictor = evictor
        self._lateness = 0
        self._late_tag: Optional[str] = None
        self._all = all_windows

    def trigger(self, trigger: Trigger) -> "WindowedStream":
        self._trigger = trigger
        return self

    def evictor(self, evictor: Evictor) -> "WindowedStream":
        self._evictor = evictor
        return self

    def allowed_lateness(self, ms: int) -> "WindowedStream":
        self._lateness = int(ms)
        return self

    def side_output_late_data(self, tag: str = "late-data") -> "WindowedStream":
        self._late_tag = tag
        return self

    def _build(self, name, aggregate=None, reduce=None, window_fn=None,
               out_schema=None) -> DataStream:
        from ..runtime.operators.window import WindowOperator
        assigner, trigger, evictor = self.assigner, self._trigger, self._evictor
        lateness, late = self._lateness, self._late_tag
        ke = self.keyed.key_extractor

        def factory():
            return WindowOperator(
                assigner, ke, aggregate=aggregate, reduce=reduce,
                window_fn=window_fn, trigger=trigger, evictor=evictor,
                allowed_lateness=lateness, emit_late_data=late is not None,
                out_schema=out_schema, name=name)

        par = 1 if self._all else None
        return self.keyed._one_input(name, factory, parallelism=par,
                                     key_extractor=ke)

    def reduce(self, fn, name: str = "WindowReduce",
               window_fn=None) -> DataStream:
        return self._build(name, reduce=as_reduce(fn), window_fn=window_fn)

    def aggregate(self, fn: AggregateFunction, name: str = "WindowAggregate",
                  window_fn=None) -> DataStream:
        return self._build(name, aggregate=fn, window_fn=window_fn)

    def apply(self, window_fn, name: str = "WindowApply") -> DataStream:
        """window_fn(key, window, elements:list) -> iterable of rows."""
        return self._build(name, window_fn=window_fn)

    def sum(self, field: Union[str, int], name: str = "WindowSum") -> DataStream:
        return self._builtin_agg("sum", field, name)

    def min(self, field: Union[str, int], name: str = "WindowMin") -> DataStream:
        return self._builtin_agg("min", field, name)

    def max(self, field: Union[str, int], name: str = "WindowMax") -> DataStream:
        return self._builtin_agg("max", field, name)

    def count(self, name: str = "WindowCount") -> DataStream:
        return self._builtin_agg("count", None, name)

    def _builtin_agg(self, kind: str, field, name: str) -> DataStream:
        device = self._try_device_agg(kind, field, name)
        if device is not None:
            return device
        import operator as _op

        class _Builtin(AggregateFunction):
            """Field-wise builtin aggregate. ``bind_schema`` resolves a
            string field to the tuple index of the actual batch schema at
            runtime (the operator calls it per batch); the device window
            operator recognizes ``kind``/``field`` and lowers this to a
            segment-reduce instead of calling add() per row."""

            builtin_kind = kind
            builtin_field = field

            def __init__(self):
                if field is None:
                    self._pick = None          # count
                elif isinstance(field, int):
                    self._pick = _op.itemgetter(field)
                else:
                    self._pick = None          # resolved via bind_schema

            def bind_schema(self, schema):
                if isinstance(field, str):
                    if len(schema) == 1:
                        self._pick = lambda v: v
                    else:
                        self._pick = _op.itemgetter(schema.index_of(field))

            def create_accumulator(self):
                return None

            def add(self, value, acc):
                pick = self._pick
                v = 1 if pick is None and field is None else pick(value)
                if acc is None:
                    return v
                if kind in ("sum", "count"):
                    return acc + v
                return min(acc, v) if kind == "min" else max(acc, v)

            def merge(self, a, b):
                if a is None:
                    return b
                if b is None:
                    return a
                if kind in ("sum", "count"):
                    return a + b
                return min(a, b) if kind == "min" else max(a, b)

            def get_result(self, acc):
                return acc

        return self._build(name, aggregate=_Builtin())


    def _try_device_agg(self, kind: str, field, name: str
                        ) -> Optional[DataStream]:
        """Planner rule: lower a builtin window aggregate to the device
        slice-window operator when the configured backend is 'tpu', the key
        is a numeric column, the assigner decomposes into panes, and no
        custom trigger/evictor/lateness is attached. Falls back to the host
        WindowOperator otherwise — outputs are identical (parity-tested)."""
        from ..core.config import StateOptions
        from ..window.assigners import CumulateWindows
        cfg = self.keyed.env.config
        if (cfg.get(StateOptions.BACKEND) != "tpu"
                or not isinstance(self.keyed.key_spec, str)
                or not isinstance(field, (str, type(None)))
                or self.assigner.pane_size is None
                # cumulate panes exist but windows span a VARIABLE number
                # of them — the device/mesh fire programs assume fixed
                # panes-per-window; host WindowOperator handles cumulate
                or isinstance(self.assigner, CumulateWindows)
                or self._trigger is not None or self._evictor is not None
                or self._lateness != 0 or self._late_tag is not None):
            return None
        from ..runtime.operators.device_window import (
            AggSpec, DeviceWindowAggOperator,
        )
        assigner = self.assigner
        key_col = self.keyed.key_spec
        capacity = cfg.get(StateOptions.TPU_CAPACITY) or (1 << 16)
        mesh_devices = cfg.get(StateOptions.MESH_DEVICES)
        spec = AggSpec(kind, field, out_name="result")

        if mesh_devices and mesh_devices >= 2:
            from ..runtime.operators.mesh_window import MeshWindowAggOperator

            def factory():
                return MeshWindowAggOperator(
                    assigner, key_col, [spec], n_devices=mesh_devices,
                    capacity=capacity, emit_window_bounds=False, name=name)

            # the mesh IS the parallelism: one SPMD vertex owns all devices
            return self.keyed._one_input(
                name, factory, parallelism=1,
                key_extractor=self.keyed.key_extractor)

        def factory():
            return DeviceWindowAggOperator(
                assigner, key_col, [spec], capacity=capacity,
                emit_window_bounds=False, name=name)

        par = 1 if self._all else None
        return self.keyed._one_input(name, factory, parallelism=par,
                                     key_extractor=self.keyed.key_extractor)

    def _reject_variable_pane_assigner(self, which: str) -> None:
        from ..window.assigners import reject_variable_pane_assigner
        reject_variable_pane_assigner(self.assigner, which)

    def device_aggregate(self, aggs, capacity: int = 1 << 16,
                         ring_size: int = 64,
                         emit_window_bounds: bool = True,
                         emit_topk: Optional[int] = None,
                         defer_overflow: bool = False,
                         async_fire: bool = False,
                         hbm_budget_slots: int = 0,
                         spill_staging_slots: int = 1 << 16,
                         name: str = "DeviceWindowAgg") -> DataStream:
        """Explicit device window aggregation with multiple AggSpecs
        (key, [window_start, window_end], *agg columns). ``emit_topk=k``
        emits only the top-k keys by the first aggregate per window (the
        Nexmark Q5 hot-items fire shape, ranked on device).
        ``defer_overflow``/``async_fire`` remove all host syncs from the
        hot path (see DeviceWindowAggOperator). ``hbm_budget_slots`` caps
        device state and pages cold key groups to host RAM — composable
        with the deferred fast path (device-side split + staging)."""
        from ..runtime.operators.device_window import DeviceWindowAggOperator
        if not isinstance(self.keyed.key_spec, str):
            raise ValueError("device aggregation needs a column key")
        assigner = self.assigner
        key_col = self.keyed.key_spec

        from ..window.assigners import EventTimeSessionWindows
        if type(assigner) is EventTimeSessionWindows:
            # merging session windows: device lanes operator (VERDICT r3
            # #5) — host gap protocol, device-resident accumulators
            if emit_topk is not None:
                raise ValueError(
                    "emit_topk is not supported for session windows")
            if defer_overflow or async_fire or hbm_budget_slots:
                raise ValueError(
                    "defer_overflow/async_fire/hbm_budget_slots are not "
                    "supported by the session operator yet; drop them or "
                    "use the host WindowOperator path")
            from ..runtime.operators.device_session import (
                DeviceSessionWindowOperator,
            )
            gap = assigner.gap

            def sess_factory():
                return DeviceSessionWindowOperator(
                    gap, key_col, aggs, capacity=capacity,
                    lanes=max(4, min(ring_size, 16)),
                    emit_window_bounds=emit_window_bounds, name=name)

            par = 1 if self._all else None
            return self.keyed._one_input(
                name, sess_factory, parallelism=par,
                key_extractor=self.keyed.key_extractor)

        self._reject_variable_pane_assigner("device")

        def factory():
            return DeviceWindowAggOperator(
                assigner, key_col, aggs, capacity=capacity,
                ring_size=ring_size, emit_window_bounds=emit_window_bounds,
                emit_topk=emit_topk, defer_overflow=defer_overflow,
                async_fire=async_fire, hbm_budget_slots=hbm_budget_slots,
                spill_staging_slots=spill_staging_slots, name=name)

        par = 1 if self._all else None
        return self.keyed._one_input(name, factory, parallelism=par,
                                     key_extractor=self.keyed.key_extractor)

    def mesh_aggregate(self, aggs, n_devices: Optional[int] = None,
                       capacity: int = 1 << 16, ring_size: int = 64,
                       device_batch: int = 1 << 12,
                       emit_window_bounds: bool = True,
                       emit_topk: Optional[int] = None,
                       async_fire: bool = False,
                       parallelism: int = 1,
                       name: str = "MeshWindowAgg") -> DataStream:
        """Window aggregation as a mesh-sharded SPMD vertex: keyBy is the
        on-device all_to_all exchange, state is sharded by key-group range
        across the mesh (parallel/sharded_window.py). With
        ``parallelism=1`` (default) the vertex is ONE subtask whose real
        parallelism is the device mesh. ``parallelism=H`` composes DCN
        with ICI for multi-host jobs: H subtasks each own a key-group
        range (the keyed exchange crosses hosts over TCP) and re-shard it
        across their host's local devices (all_to_all over ICI) —
        SURVEY §5.8's two-level plan. ``emit_topk``/``async_fire`` match
        device_aggregate: two-phase global top-k ranked on the first
        aggregate, fires emitting asynchronously with watermarks held
        behind them."""
        from ..runtime.operators.mesh_window import MeshWindowAggOperator
        if not isinstance(self.keyed.key_spec, str):
            raise ValueError("mesh aggregation needs a column key")
        if emit_topk is not None and parallelism > 1:
            raise ValueError(
                "emit_topk with parallelism > 1 would rank each subtask's "
                "key range separately, not globally; run the mesh top-k "
                "at parallelism=1 or add a downstream global TopN")
        self._reject_variable_pane_assigner("mesh")
        assigner = self.assigner
        key_col = self.keyed.key_spec

        def factory():
            return MeshWindowAggOperator(
                assigner, key_col, aggs, n_devices=n_devices,
                capacity=capacity, ring_size=ring_size,
                device_batch=device_batch,
                emit_window_bounds=emit_window_bounds,
                emit_topk=emit_topk, async_fire=async_fire, name=name)

        return self.keyed._one_input(name, factory, parallelism=parallelism,
                                     key_extractor=self.keyed.key_extractor)


class ConnectedStreams:
    """Two streams into one two-input operator (reference ConnectedStreams)."""

    def __init__(self, env, first: DataStream, second: DataStream):
        self.env = env
        self.first = first
        self.second = second

    def transform(self, name: str, operator_factory,
                  parallelism: Optional[int] = None) -> DataStream:
        t = TwoInputTransformation(
            name=name, operator_factory=operator_factory,
            parallelism=parallelism,
            inputs=[self.first.transformation, self.second.transformation])
        self.env._transformations.append(t)
        return DataStream(self.env, t)
