"""StreamExecutionEnvironment: the user's entry point.

Analog of flink-streaming-java's StreamExecutionEnvironment
(api/environment/StreamExecutionEnvironment.java:155 — execute:2309,
getStreamGraph:2499) collapsed with the local executor: builds the
Transformation DAG, compiles StreamGraph -> JobGraph (chaining), and runs it
on the local thread-cluster or hands it to a MiniCluster/remote deployment.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..connectors.core import CollectionSource, DataGenSource, Source
from ..core.config import (
    CheckpointingOptions, Configuration, PipelineOptions, StateOptions,
)
from ..core.records import Schema
from ..core.watermarks import WatermarkStrategy
from ..graph.stream_graph import JobGraph, build_job_graph, build_stream_graph
from ..graph.transformations import SourceTransformation, Transformation
from .datastream import DataStream

__all__ = ["StreamExecutionEnvironment"]


class StreamExecutionEnvironment:
    _default: Optional["StreamExecutionEnvironment"] = None

    def __init__(self, config: Optional[Configuration] = None):
        self.config = config or Configuration()
        self._transformations: list[Transformation] = []
        self._sinks: list[Transformation] = []
        self.last_job = None
        self._restore_path: Optional[str] = None
        self._remote_target: Optional[str] = None

    @staticmethod
    def get_execution_environment(
            config: Optional[Configuration] = None
    ) -> "StreamExecutionEnvironment":
        return StreamExecutionEnvironment(config)

    @classmethod
    def get_default(cls) -> "StreamExecutionEnvironment":
        """Process-default environment (the reference's context environment):
        the CLI pre-configures it, user scripts pick it up."""
        if cls._default is None:
            cls._default = StreamExecutionEnvironment()
        return cls._default

    def restore_from_savepoint(self, path: str
                               ) -> "StreamExecutionEnvironment":
        """The next execute()/execute_async() starts from this savepoint
        (reference 'flink run -s <path>'). Operators map by stable uid, so
        the pipeline may be a resubmitted build of the program."""
        self._restore_path = path
        return self

    def _restore_checkpoint_pending(self) -> bool:
        """Non-destructive peek at a staged restore point."""
        return bool(self._restore_path)

    def _take_restore_checkpoint(self):
        """Consume the pending restore path -> CompletedCheckpoint."""
        if not self._restore_path:
            return None
        from ..state_processor import SavepointReader
        path, self._restore_path = self._restore_path, None
        return SavepointReader.read(path).checkpoint

    # -- config sugar ------------------------------------------------------
    @property
    def parallelism(self) -> int:
        return self.config.get(PipelineOptions.DEFAULT_PARALLELISM)

    def set_parallelism(self, p: int) -> "StreamExecutionEnvironment":
        self.config.set(PipelineOptions.DEFAULT_PARALLELISM, p)
        return self

    @property
    def max_parallelism(self) -> int:
        return self.config.get(PipelineOptions.MAX_PARALLELISM)

    def set_max_parallelism(self, p: int) -> "StreamExecutionEnvironment":
        self.config.set(PipelineOptions.MAX_PARALLELISM, p)
        return self

    def enable_checkpointing(self, interval_seconds: float,
                             mode: str = "exactly-once"
                             ) -> "StreamExecutionEnvironment":
        self.config.set(CheckpointingOptions.INTERVAL, interval_seconds)
        self.config.set(CheckpointingOptions.MODE, mode)
        return self

    def set_state_backend(self, name: str) -> "StreamExecutionEnvironment":
        self.config.set(StateOptions.BACKEND, name)
        return self

    def disable_operator_chaining(self) -> "StreamExecutionEnvironment":
        self.config.set(PipelineOptions.CHAINING_ENABLED, False)
        return self

    # -- sources -----------------------------------------------------------
    def from_source(self, source: Source,
                    watermark_strategy: Optional[WatermarkStrategy] = None,
                    name: str = "Source",
                    parallelism: Optional[int] = None) -> DataStream:
        t = SourceTransformation(
            name=name, source=source,
            watermark_strategy=watermark_strategy or
            WatermarkStrategy.no_watermarks(),
            parallelism=parallelism, schema=source.schema)
        self._transformations.append(t)
        return DataStream(self, t)

    def from_collection(self, elements: Sequence[Any],
                        schema: Optional[Schema] = None,
                        timestamps: Optional[Sequence[int]] = None,
                        watermark_strategy: Optional[WatermarkStrategy] = None,
                        name: str = "Collection") -> DataStream:
        src = CollectionSource(elements, schema, timestamps)
        ws = watermark_strategy
        if ws is None and timestamps is not None:
            ws = WatermarkStrategy.for_monotonous_timestamps()
        return self.from_source(src, ws, name, parallelism=1)

    def from_elements(self, *elements: Any) -> DataStream:
        return self.from_collection(list(elements))

    def datagen(self, gen_fn: Callable[[np.ndarray], dict[str, np.ndarray]],
                schema: Schema, count: Optional[int] = None,
                rate_per_sec: Optional[float] = None,
                timestamp_column: Optional[str] = None,
                watermark_strategy: Optional[WatermarkStrategy] = None,
                name: str = "DataGen",
                parallelism: Optional[int] = None,
                device: bool = False) -> DataStream:
        """``device=True``: generate each batch on the accelerator and emit
        device-resident batches (see DataGenSource) — the zero-transfer
        ingest path for device pipelines."""
        src = DataGenSource(gen_fn, schema, count, rate_per_sec,
                            timestamp_column, device=device)
        return self.from_source(src, watermark_strategy, name, parallelism)

    # -- compile & run -----------------------------------------------------
    def get_stream_graph(self):
        if not self._sinks:
            raise RuntimeError("No sinks defined; nothing to execute")
        return build_stream_graph(self._sinks, self.config)

    def get_job_graph(self, name: str = "job") -> JobGraph:
        self.config.set(PipelineOptions.NAME, name)
        sg = self.get_stream_graph()
        jg = build_job_graph(sg, self.config, name)
        if self.config.get(PipelineOptions.FUSION):
            from ..graph.fusion import certify
            jg.certificate = certify(sg, jg, self.config)
        return jg

    def set_remote_target(self, address: Optional[str]) -> None:
        """Route execute() to a running session cluster's Dispatcher at
        ``host:port`` instead of running in-process (reference
        execution.target=remote + RestClusterClient; the CLI's --target
        flag sets this)."""
        self._remote_target = address

    def execute(self, job_name: str = "flink-tpu-job",
                timeout: Optional[float] = 120.0,
                metrics_registry=None, recover: bool = False):
        """Compile and run locally, blocking until completion (bounded
        sources) — reference execute():2309. With ``recover=True`` the job
        runs under a JobSupervisor that restarts from the latest completed
        checkpoint on task failure (requires enable_checkpointing). With a
        remote target set, the graph is submitted to the session cluster
        and this blocks until the remote job is terminal."""
        from ..core.config import ExecutionOptions
        if self._remote_target:
            if self.config.get(ExecutionOptions.RUNTIME_MODE) == "batch":
                raise ValueError(
                    "batch runtime mode runs in-process only (the remote "
                    "dispatcher schedules pipelined streaming jobs); "
                    "unset the remote target or the runtime mode")
            from ..cluster.dispatcher import ClusterClient
            client = ClusterClient(self._remote_target, config=self.config)
            # a pending savepoint restore ships with the submission — the
            # remote supervisor starts the job from it, matching the local
            # path's semantics
            restore = self._take_restore_checkpoint()
            job_id = client.submit(self, name=job_name, restore=restore)
            self._transformations = []
            self._sinks = []
            self.last_job = None
            return client.wait(job_id, timeout=timeout)
        jg = self.get_job_graph(job_name)
        if self.config.get(ExecutionOptions.RUNTIME_MODE) == "batch":
            # checked BEFORE consuming the pending restore point: the
            # error must not destroy a staged savepoint restore the user
            # will retry in streaming mode
            if recover or self._restore_checkpoint_pending():
                raise ValueError(
                    "batch mode schedules stages over blocking exchanges "
                    "and has no checkpoints to recover/restore from; "
                    "failed bounded jobs re-run from their sources")
            from ..cluster.batch import run_job_batch
            self.last_job = run_job_batch(jg, self.config, timeout=timeout,
                                          metrics_registry=metrics_registry)
            self._transformations = []
            self._sinks = []
            return self.last_job
        cp = self._take_restore_checkpoint()
        if recover:
            from ..cluster.scheduler import JobSupervisor
            supervisor = JobSupervisor(jg, self.config,
                                       metrics_registry=metrics_registry)
            self.last_job = supervisor.run(timeout, initial_restore=cp)
            self.last_job.supervisor = supervisor
        else:
            from ..cluster.local import run_job
            restored_state = None
            if cp is not None:
                from ..checkpoint.coordinator import build_restore_map
                restored_state = build_restore_map(cp, jg)
            self.last_job = run_job(jg, self.config, timeout=timeout,
                                    metrics_registry=metrics_registry,
                                    restored_state=restored_state)
        # a fresh env per execute is the common pattern; clear so the same
        # env can be reused for a new pipeline
        self._transformations = []
        self._sinks = []
        return self.last_job

    def execute_async(self, job_name: str = "flink-tpu-job",
                      metrics_registry=None):
        if self._remote_target:
            raise RuntimeError(
                "a remote target is set; execute_async runs in-process — "
                "use execute() (which submits to the cluster and waits) or "
                "ClusterClient.submit for fire-and-forget")
        from ..core.config import ExecutionOptions
        if self.config.get(ExecutionOptions.RUNTIME_MODE) == "batch":
            raise ValueError(
                "batch runtime mode schedules stages synchronously; use "
                "execute() — execute_async would silently run the "
                "pipelined streaming path instead")
        from ..cluster.local import deploy_local
        jg = self.get_job_graph(job_name)
        cp = self._take_restore_checkpoint()
        restored_state = None
        if cp is not None:
            from ..checkpoint.coordinator import build_restore_map
            restored_state = build_restore_map(cp, jg)
        job = deploy_local(jg, self.config, restored_state=restored_state,
                           metrics_registry=metrics_registry)
        job.start()
        self.last_job = job
        self._transformations = []
        self._sinks = []
        return job
