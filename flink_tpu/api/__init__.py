"""DataStream API layer (SURVEY.md §2.5)."""

from .datastream import (  # noqa: F401
    ConnectedStreams, DataStream, KeyedStream, WindowedStream,
    make_key_extractor,
)
from .environment import StreamExecutionEnvironment  # noqa: F401
