"""Checkpoint coordinator: master-side snapshot orchestration.

Analog of the reference's CheckpointCoordinator
(flink-runtime checkpoint/CheckpointCoordinator.java — triggerCheckpoint:571,
receiveAcknowledgeMessage:1202, restoreLatestCheckpointedStateToAll:1704,
restoreSavepoint:1868) plus CompletedCheckpointStore subsumption:

* periodically injects barriers at the sources (through each source task's
  mailbox — the triggerCheckpointAsync analog); barriers flow through the
  dataflow, tasks align, snapshot, and ack back here;
* a pending checkpoint completes when every task acked; completed
  checkpoints are stored, retained up to N, older ones subsumed;
* timeouts abort pending checkpoints; declines abort immediately;
* restore produces a task_id -> snapshot map for a (possibly rescaled) new
  topology: keyed snapshots from ALL old subtasks are handed to every new
  subtask (backends filter by key-group range — the StateAssignmentOperation
  analog), reader/operator state maps 1:1 when parallelism is unchanged.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ..core.config import CheckpointingOptions, Configuration
from ..core.elements import CheckpointBarrier
from .storage import (
    CheckpointNotFoundError, CheckpointStorage, CompletedCheckpoint,
    CorruptArtifactError, FsCheckpointStorage, MemoryCheckpointStorage,
)

__all__ = ["CheckpointCoordinator", "build_restore_map"]


def savepoint_self_contained(snapshots: dict, config: Configuration) -> dict:
    """Savepoints must outlive the changelog backend's generation
    truncation (reference: savepoints are canonical FULL snapshots).
    Rewrite every changelog-dstl handle snapshot into the inline full
    format — base + replay log embedded in the savepoint metadata — so
    the savepoint's lifetime is owned by its storage, not by DSTL
    cleanup. Shared by the local and distributed coordinators."""
    import os
    import pickle as _pickle

    from ..state.dstl import read_any_base, read_any_segment

    directory = config.get(CheckpointingOptions.DIRECTORY)
    root = os.path.join(directory, "changelog") if directory else None

    def rewrite(node):
        if isinstance(node, dict):
            if node.get("kind") == "changelog-dstl":
                base = None
                if node.get("base") is not None:
                    base = _pickle.loads(read_any_base(
                        node["driver"], node["base"], root))
                base_seq = node.get("base_seq", 0)
                records: list = []
                for h in node.get("segments", []):
                    records.extend(read_any_segment(h, root))
                log = [rec for seq, rec in sorted(records)
                       if seq > base_seq]
                return {"kind": "changelog", "mat": base, "log": log}
            return {k: rewrite(v) for k, v in node.items()}
        if isinstance(node, list):
            return [rewrite(v) for v in node]
        return node

    return rewrite(snapshots)


@dataclass
class _Pending:
    checkpoint_id: int
    started: float
    is_savepoint: bool
    acks: dict[str, dict] = field(default_factory=dict)
    # task set captured AT TRIGGER TIME: completion must not shrink with
    # job.tasks (a region restart temporarily removes tasks; a checkpoint
    # completing without them would restore them empty later)
    expected: frozenset = frozenset()
    declined: bool = False
    # root SpanBuilder of this checkpoint's trace tree; its context rides
    # the barrier so task-side Align/Snapshot spans become its children
    span: Any = None
    done = None  # threading.Event set on complete/abort

    def __post_init__(self):
        self.done = threading.Event()
    # result slot filled on completion
    completed: Optional[CompletedCheckpoint] = None


class CheckpointCoordinator:
    def __init__(self, job, config: Configuration,
                 storage: Optional[CheckpointStorage] = None, tracer=None):
        """``job`` is a LocalJob-like object exposing .tasks, .source_tasks,
        and a checkpoint_listener hook. ``tracer`` (metrics/tracing.Tracer)
        receives a span per completed checkpoint, like the reference's
        CheckpointStatsTracker span emission."""
        self.job = job
        self.config = config
        self.tracer = tracer
        directory = config.get(CheckpointingOptions.DIRECTORY)
        self.storage = storage or (
            FsCheckpointStorage(directory, config=config) if directory
            else MemoryCheckpointStorage())
        # restore-candidate verification events (kind 'corrupt-artifact'),
        # merged into the job failure history -> REST /jobs/<n>/exceptions
        self.verify_failures: list[dict] = []
        self.retained = config.get(CheckpointingOptions.RETAINED)
        self.timeout = config.get(CheckpointingOptions.TIMEOUT)
        self.min_pause = config.get(CheckpointingOptions.MIN_PAUSE)
        self.interval = config.get(CheckpointingOptions.INTERVAL)
        self._next_id = 1
        self._pending: dict[int, _Pending] = {}
        self._completed: list[CompletedCheckpoint] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_complete_time = 0.0
        self._paused = False
        self.stats: list[dict] = []  # checkpoint stats history (REST/UI)
        job.checkpoint_listener = self._on_event

    # -- trigger -----------------------------------------------------------
    def trigger_checkpoint(self, is_savepoint: bool = False) -> _Pending:
        """reference triggerCheckpoint:571 — inject barriers at sources."""
        jg = getattr(self.job, "job_graph", None)
        if jg is not None and any(getattr(e, "feedback", False)
                                  for e in jg.edges):
            # a barrier cannot circulate a feedback loop (the back edge
            # drops barriers by design): refuse instead of wedging the
            # iteration head's alignment forever
            raise ValueError(
                "iteration jobs (feedback edges) cannot be checkpointed "
                "or savepointed")
        with self._lock:
            if self._paused:
                raise RuntimeError("checkpointing paused (region restart)")
            cid = self._next_id
            self._next_id += 1
            span = None
            if self.tracer is not None:
                span = (self.tracer.span("checkpoint", "Checkpoint")
                        .set_attribute("checkpointId", cid)
                        .set_attribute("savepoint", is_savepoint))
            pending = _Pending(cid, time.time(), is_savepoint,
                               expected=frozenset(self.job.tasks),
                               span=span)
            self._pending[cid] = pending
        barrier = CheckpointBarrier(
            cid, is_savepoint=is_savepoint,
            trace=span.context.to_wire() if span is not None else None)
        for st in self.job.source_tasks.values():
            st.trigger_checkpoint(barrier)
        return pending

    def trigger_savepoint(self, timeout: float = 60.0) -> CompletedCheckpoint:
        p = self.trigger_checkpoint(is_savepoint=True)
        if not p.done.wait(timeout):
            raise TimeoutError(f"savepoint {p.checkpoint_id} timed out")
        if p.completed is None:
            raise RuntimeError(f"savepoint {p.checkpoint_id} failed/declined")
        return p.completed

    # -- acks --------------------------------------------------------------
    def _on_event(self, kind: str, task_id: str, checkpoint_id: int,
                  payload) -> None:
        if kind == "ack":
            self._on_ack(task_id, checkpoint_id, payload)
        else:
            self._on_decline(task_id, checkpoint_id, payload)

    def _on_ack(self, task_id: str, checkpoint_id: int, snapshot: dict) -> None:
        """reference receiveAcknowledgeMessage:1202."""
        complete = None
        notify_stale = False
        with self._lock:
            p = self._pending.get(checkpoint_id)
            if p is None or p.declined:
                notify_stale = not any(c.checkpoint_id == checkpoint_id
                                       for c in self._completed)
            else:
                p.acks[task_id] = snapshot
                expected = p.expected or frozenset(self.job.tasks)
                if set(p.acks) >= set(expected):
                    del self._pending[checkpoint_id]
                    complete = p
        if notify_stale:
            # a snapshot for an ABANDONED checkpoint just landed: the
            # task's barrier was still in the data channel when the abort
            # broadcast ran (a no-op for it — nothing was pinned yet), so
            # its freshly-registered generation pin would leak forever.
            # Re-broadcast the abort now that the late snapshot exists
            # (reference: late acks for disposed checkpoints get discard
            # callbacks the same way).
            self._notify_aborted(checkpoint_id)
            return
        if complete is not None:
            self._complete(complete)

    def _on_decline(self, task_id: str, checkpoint_id: int, reason) -> None:
        with self._lock:
            p = self._pending.pop(checkpoint_id, None)
        if p is not None:
            p.declined = True
            p.done.set()
            if p.span is not None:
                p.span.set_attribute("aborted", True).set_attribute(
                    "declined_by", task_id).finish()
            # tasks that already snapshotted this id hold generation pins
            # (changelog DSTL); a declined checkpoint is abandoned exactly
            # like a timed-out one and must release them
            self._notify_aborted(checkpoint_id)

    def _complete(self, p: _Pending) -> None:
        if p.is_savepoint:
            p.acks = savepoint_self_contained(p.acks, self.config)
        vertex_par = {vid: v.parallelism
                      for vid, v in self.job.job_graph.vertices.items()}
        vertex_uids = {vid: v.uid
                       for vid, v in self.job.job_graph.vertices.items()
                       if getattr(v, "uid", "")}
        cp = CompletedCheckpoint(
            checkpoint_id=p.checkpoint_id, timestamp=p.started,
            task_snapshots=dict(p.acks), is_savepoint=p.is_savepoint,
            vertex_parallelism=vertex_par, vertex_uids=vertex_uids)
        store_sb = None
        if p.span is not None:
            store_sb = (self.tracer.span("checkpoint", "Store",
                                         parent=p.span.context)
                        .set_attribute("checkpointId", p.checkpoint_id))
        try:
            cp = self.storage.store(cp)
        except Exception as e:  # noqa: BLE001 - storage outage/injection
            # a failed checkpoint WRITE must not fail the job (reference:
            # tolerable checkpoint failures): abort this checkpoint, keep
            # running on the previous completed one, record the event
            if store_sb is not None:
                store_sb.set_attribute("error", True).finish()
                p.span.set_attribute("error", True).set_attribute(
                    "aborted", True).finish()
            with self._lock:
                self.stats.append({
                    "id": p.checkpoint_id, "savepoint": p.is_savepoint,
                    "duration_s": time.time() - p.started,
                    "tasks": len(p.acks), "failed": True,
                    "error": f"{type(e).__name__}: {e}"})
            p.declined = True
            p.done.set()
            self._notify_aborted(p.checkpoint_id)
            return
        if store_sb is not None:
            store_sb.finish()
        duration = time.time() - p.started
        with self._lock:
            # keep the store ordered by checkpoint id, not completion time:
            # with max-concurrent > 1 a slow older checkpoint may complete
            # after a newer one, and subsumption must discard the OLDER id
            self._completed.append(cp)
            self._completed.sort(key=lambda c: c.checkpoint_id)
            self._last_complete_time = time.time()
            self.stats.append({
                "id": p.checkpoint_id, "savepoint": p.is_savepoint,
                "duration_s": duration, "tasks": len(p.acks)})
            # subsume old (savepoints never auto-discarded)
            regulars = [c for c in self._completed if not c.is_savepoint]
            while len(regulars) > self.retained:
                old = regulars.pop(0)
                self._completed.remove(old)
                self.storage.discard(old)
        # notify tasks (two-phase-commit sinks commit on this)
        notify_sb = None
        if p.span is not None:
            notify_sb = (self.tracer.span("checkpoint", "Notify",
                                          parent=p.span.context)
                         .set_attribute("checkpointId", p.checkpoint_id)
                         .set_attribute("tasks", len(self.job.tasks)))
        for t in self.job.tasks.values():
            t.execute_in_mailbox(
                lambda t=t: t.chain.notify_checkpoint_complete(
                    p.checkpoint_id, is_savepoint=p.is_savepoint)
                if getattr(t, "chain", None) else None)
        if notify_sb is not None:
            notify_sb.finish()
        if p.span is not None:
            (p.span.set_attribute("tasks", len(p.acks))
             .set_start_ts(int(p.started * 1000))
             .set_attribute("duration_s", round(duration, 6))
             .finish())
        p.completed = cp
        p.done.set()

    def pause(self) -> None:
        """Hold new triggers and abort in-flight checkpoints — a region
        restart removes tasks mid-flight; their checkpoints can never
        complete and must not complete PARTIALLY either."""
        with self._lock:
            self._paused = True
            aborted = list(self._pending)
            for cid, p in list(self._pending.items()):
                p.declined = True
                p.done.set()
                if p.span is not None:
                    p.span.set_attribute("aborted", True).finish()
                del self._pending[cid]
        for cid in aborted:
            self._notify_aborted(cid)

    def _notify_aborted(self, checkpoint_id: int) -> None:
        """Tell every task an in-flight checkpoint can no longer complete,
        so backends drop its pins (the changelog DSTL pins a generation
        per triggered snapshot; without an explicit abort a still-running
        savepoint's pin could only be inferred — and mis-inferred — from
        checkpoint-id distance)."""
        for t in self.job.tasks.values():
            t.execute_in_mailbox(
                lambda t=t, c=checkpoint_id:
                t.chain.notify_checkpoint_aborted(c)
                if getattr(t, "chain", None) else None)

    def resume(self) -> None:
        with self._lock:
            self._paused = False

    def latest_checkpoint(self) -> Optional[CompletedCheckpoint]:
        with self._lock:
            return self._completed[-1] if self._completed else None

    def latest_verified_checkpoint(self) -> Optional[CompletedCheckpoint]:
        """The newest retained checkpoint whose ON-DISK artifact passes
        integrity verification — what every restore decision must use.

        Walks backward through the retained list: a candidate that fails
        verification is counted (``checkpoint_verify_failures_total``),
        recorded on the job failure history (kind ``corrupt-artifact`` →
        REST ``/jobs/<name>/exceptions``), quarantined on disk
        (``<dir>.corrupt``, refs dropped), and removed from the retained
        list; the walk continues to the next-oldest. Raises
        CorruptArtifactError when retained checkpoints exist but NONE
        verifies — restarting from scratch would replay the whole stream
        past committed output, so that must be a terminal job failure,
        never a silent restore of garbage (or nothing)."""
        from ..metrics.device import DEVICE_STATS

        verify = self.config.get(CheckpointingOptions.VERIFY_ON_RESTORE)
        quarantine = self.config.get(CheckpointingOptions.QUARANTINE_CORRUPT)
        restore_sb = (self.tracer.span("restore", "Restore")
                      if self.tracer is not None else None)
        try:
            return self._verified_candidate(
                verify, quarantine, restore_sb, DEVICE_STATS)
        except BaseException:
            if restore_sb is not None:
                restore_sb.set_attribute("error", True).finish()
                restore_sb = None
            raise
        finally:
            if restore_sb is not None:
                restore_sb.finish()

    def _verified_candidate(self, verify, quarantine, restore_sb,
                            DEVICE_STATS) -> Optional[CompletedCheckpoint]:
        skipped = 0
        while True:
            with self._lock:
                cand = self._completed[-1] if self._completed else None
            if cand is None:
                if skipped:
                    raise CorruptArtifactError(
                        f"all {skipped} retained checkpoints failed "
                        "verification; refusing to restore garbage state")
                return None
            if (not verify
                    or not isinstance(self.storage, FsCheckpointStorage)
                    or not cand.external_path):
                break  # nothing on disk to verify (in-memory storage)
            try:
                self.storage.verify_checkpoint(cand.external_path)
            except (CorruptArtifactError, CheckpointNotFoundError) as e:
                skipped += 1
                DEVICE_STATS.note_verify_failure("checkpoint.restore")
                event = {"timestamp": time.time(),
                         "kind": "corrupt-artifact",
                         "checkpoint": cand.checkpoint_id,
                         "path": cand.external_path,
                         "error": f"{type(e).__name__}: {e}"}
                self.verify_failures.append(event)
                hist = getattr(self.job, "failure_history", None)
                if hist is not None:
                    hist.append(event)
                with self._lock:
                    if cand in self._completed:
                        self._completed.remove(cand)
                if quarantine:
                    self.storage.quarantine(cand)
                continue
            break
        if skipped:
            DEVICE_STATS.note_restore_fallback("checkpoint.restore")
            if restore_sb is not None:
                (self.tracer.span("restore", "Fallback",
                                  parent=restore_sb.context)
                 .set_attribute("checkpointId", cand.checkpoint_id)
                 .set_attribute("skipped", skipped)
                 .finish())
            hist = getattr(self.job, "failure_history", None)
            if hist is not None:
                hist.append({"timestamp": time.time(),
                             "kind": "restore-fallback",
                             "checkpoint": cand.checkpoint_id,
                             "skipped": skipped})
        if restore_sb is not None:
            restore_sb.set_attribute(
                "checkpointId", cand.checkpoint_id).set_attribute(
                "skipped", skipped)
        return cand

    # -- periodic loop -----------------------------------------------------
    def start_periodic(self) -> None:
        if self.interval <= 0:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="checkpoint-coordinator",
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            if self._paused:
                continue
            now = time.time()
            timed_out = []
            with self._lock:
                # abort timed-out pendings
                for cid, p in list(self._pending.items()):
                    if now - p.started > self.timeout:
                        del self._pending[cid]
                        p.done.set()
                        if p.span is not None:
                            p.span.set_attribute(
                                "aborted", True).set_attribute(
                                "timeout", True).finish()
                        timed_out.append(cid)
                in_flight = len(self._pending)
                too_soon = now - self._last_complete_time < self.min_pause
            for cid in timed_out:
                self._notify_aborted(cid)
            if in_flight >= self.config.get(
                    CheckpointingOptions.MAX_CONCURRENT) or too_soon:
                continue
            alive = any(t.is_alive for t in self.job.tasks.values())
            if not alive:
                return
            try:
                self.trigger_checkpoint()
            except Exception:  # noqa: BLE001 - job may be tearing down
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def build_restore_map(checkpoint: CompletedCheckpoint,
                      job_graph) -> dict[str, dict]:
    """Map a completed checkpoint onto a (possibly rescaled) topology:
    the StateAssignmentOperation analog.

    Keyed state: every new subtask receives the keyed snapshots of ALL old
    subtasks of its vertex; backends keep only their key-group range.
    Reader/operator state: 1:1 when the vertex parallelism is unchanged;
    otherwise readers restart (splits are re-enumerated) and operator list
    state is redistributed (split: round-robin; union: broadcast) via
    OperatorStateBackend.redistribute.
    """
    from ..state.backend import OperatorStateBackend

    # group old snapshots by vertex
    by_vertex: dict[str, dict[int, dict]] = {}
    for task_id, snap in checkpoint.task_snapshots.items():
        vid, sub = task_id.rsplit("#", 1)
        by_vertex.setdefault(vid, {})[int(sub)] = snap

    # uid -> old vertex id: restore into a resubmitted program whose
    # generated vertex ids differ (reference operator-uid mapping)
    uid_to_old = {uid: vid
                  for vid, uid in (checkpoint.vertex_uids or {}).items()
                  if vid in by_vertex}

    restore: dict[str, dict] = {}
    for vid, vertex in job_graph.vertices.items():
        # uid match takes precedence: generated vertex ids can COLLIDE
        # across resubmissions of a modified program (process-global
        # counter), so a raw id hit may be the wrong operator
        uid = getattr(vertex, "uid", "")
        if uid and uid in uid_to_old:
            old_vid = uid_to_old[uid]
            old = by_vertex[old_vid]
        elif uid and checkpoint.vertex_uids:
            # uids were recorded but this vertex's isn't among them: a raw
            # id match would be a collision with a DIFFERENT operator
            continue
        else:
            old_vid = vid
            old = by_vertex.get(vid)
        if not old:
            continue
        old_par = checkpoint.vertex_parallelism.get(old_vid, len(old))
        same_par = old_par == vertex.parallelism
        # union of chain op keys across old subtasks
        op_keys: set[str] = set()
        for snap in old.values():
            op_keys.update((snap.get("chain") or {}).keys())

        # rescale path: redistribute each operator's non-keyed list state
        # across the NEW parallelism (split round-robin / union broadcast)
        redistributed: dict[str, list[dict]] = {}
        if not same_par:
            for op_key in op_keys:
                op_snaps = [
                    snap for osub in sorted(old)
                    if (snap := ((old[osub].get("chain") or {})
                                 .get(op_key) or {}).get("operator"))
                    is not None]
                if op_snaps:
                    redistributed[op_key] = OperatorStateBackend.redistribute(
                        op_snaps, vertex.parallelism)

        if not same_par and any(
                s.get("inflight") or s.get("inflight1") or s.get("inflight2")
                for s in old.values()):
            # early reference versions had the same restriction: unaligned
            # channel state cannot be re-distributed across parallelisms
            raise ValueError(
                f"cannot rescale vertex {vid} from an unaligned checkpoint "
                "with in-flight data; take an aligned checkpoint/savepoint "
                "first")

        for sub in range(vertex.parallelism):
            task_snap: dict[str, Any] = {}
            if same_par and sub in old:
                task_snap["reader"] = old[sub].get("reader")
                for fk in ("inflight", "inflight1", "inflight2"):
                    if old[sub].get(fk):
                        task_snap[fk] = old[sub][fk]
            chain_map: dict[str, dict] = {}
            for op_key in op_keys:
                keyed_list = []
                operator_state = None
                for osub in sorted(old):
                    op_snap = (old[osub].get("chain") or {}).get(op_key) or {}
                    if op_snap.get("keyed") is not None:
                        keyed_list.append(op_snap["keyed"])
                    if same_par and osub == sub:
                        operator_state = op_snap.get("operator")
                if not same_par and op_key in redistributed:
                    operator_state = redistributed[op_key][sub]
                chain_map[op_key] = {"keyed_list": keyed_list,
                                     "operator": operator_state}
            if chain_map:
                task_snap["chain"] = chain_map
            restore[f"{vid}#{sub}"] = task_snap
    return restore
