"""Checkpointing: coordinator, storage, and verified-recovery errors."""

from .storage import (
    CheckpointNotFoundError, CheckpointStorage, CompletedCheckpoint,
    CorruptArtifactError, FsCheckpointStorage, MemoryCheckpointStorage,
)

__all__ = ["CheckpointNotFoundError", "CheckpointStorage",
           "CompletedCheckpoint", "CorruptArtifactError",
           "FsCheckpointStorage", "MemoryCheckpointStorage"]
