"""Checkpoint storage: where completed snapshots live.

Analog of the reference's CheckpointStorage
(flink-runtime state/filesystem/FsCheckpointStorageAccess.java:44 and
JobManagerCheckpointStorage): in-memory for tests, filesystem directory
layout ``<dir>/chk-<id>/metadata`` for durability. Snapshots are
host-serialized (device state was already DMA'd to numpy by the backends'
snapshot()).
"""

from __future__ import annotations

import os
import pickle
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Optional

__all__ = ["CompletedCheckpoint", "CheckpointStorage", "MemoryCheckpointStorage",
           "FsCheckpointStorage"]


@dataclass
class CompletedCheckpoint:
    checkpoint_id: int
    timestamp: float
    # task_id -> task snapshot ({"reader":..., "chain": {...}})
    task_snapshots: dict[str, dict]
    is_savepoint: bool = False
    external_path: Optional[str] = None
    # topology at snapshot time, for rescaling restore
    vertex_parallelism: dict[str, int] = field(default_factory=dict)
    # vertex id -> stable uid, for restore into a RESUBMITTED program whose
    # generated vertex ids differ (reference operator-uid mapping)
    vertex_uids: dict[str, str] = field(default_factory=dict)


class CheckpointStorage:
    def store(self, checkpoint: CompletedCheckpoint) -> CompletedCheckpoint:
        raise NotImplementedError

    def discard(self, checkpoint: CompletedCheckpoint) -> None:
        pass

    def load(self, path_or_id: Any) -> CompletedCheckpoint:
        raise NotImplementedError


class MemoryCheckpointStorage(CheckpointStorage):
    def __init__(self):
        self._store: dict[int, CompletedCheckpoint] = {}

    def store(self, checkpoint: CompletedCheckpoint) -> CompletedCheckpoint:
        self._store[checkpoint.checkpoint_id] = checkpoint
        return checkpoint

    def discard(self, checkpoint: CompletedCheckpoint) -> None:
        self._store.pop(checkpoint.checkpoint_id, None)

    def load(self, checkpoint_id: int) -> CompletedCheckpoint:
        return self._store[checkpoint_id]


class FsCheckpointStorage(CheckpointStorage):
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, checkpoint: CompletedCheckpoint) -> str:
        prefix = "sp" if checkpoint.is_savepoint else "chk"
        return os.path.join(self.directory, f"{prefix}-{checkpoint.checkpoint_id}")

    def store(self, checkpoint: CompletedCheckpoint) -> CompletedCheckpoint:
        d = self._path(checkpoint)
        os.makedirs(d, exist_ok=True)
        # set the path BEFORE pickling so a checkpoint load()ed from disk
        # knows where it lives
        checkpoint.external_path = d
        # block-compressed like the reference's snapshot compression
        # (io/compression/BlockCompressionFactory); native LZ4-style codec
        # when built, zlib otherwise — self-describing tag either way
        from ..native import compress
        payload = compress(pickle.dumps(
            checkpoint, protocol=pickle.HIGHEST_PROTOCOL))
        tmp = os.path.join(d, "_metadata.part")
        with open(tmp, "wb") as f:
            f.write(_COMPRESSED_MAGIC)
            f.write(payload)
        final = os.path.join(d, "_metadata")
        os.replace(tmp, final)  # atomic publish
        return checkpoint

    def discard(self, checkpoint: CompletedCheckpoint) -> None:
        if checkpoint.is_savepoint:
            return  # savepoints are user-owned (reference semantics)
        d = self._path(checkpoint)
        shutil.rmtree(d, ignore_errors=True)

    def load(self, path: str) -> CompletedCheckpoint:
        meta = path if path.endswith("_metadata") else os.path.join(path,
                                                                    "_metadata")
        with open(meta, "rb") as f:
            data = f.read()
        if data.startswith(_COMPRESSED_MAGIC):
            from ..native import decompress
            return pickle.loads(decompress(data[len(_COMPRESSED_MAGIC):]))
        return pickle.loads(data)  # pre-compression snapshots


_COMPRESSED_MAGIC = b"FTCK"
