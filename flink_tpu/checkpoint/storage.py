"""Checkpoint storage: where completed snapshots live.

Analog of the reference's CheckpointStorage
(flink-runtime state/filesystem/FsCheckpointStorageAccess.java:44 and
JobManagerCheckpointStorage): in-memory for tests, filesystem directory
layout ``<dir>/chk-<id>/metadata`` for durability. Snapshots are
host-serialized (device state was already DMA'd to numpy by the backends'
snapshot()).

Incremental checkpoints (VERDICT #5; the RocksDB SST-diff analog,
RocksIncrementalSnapshotStrategy.java:70 + SharedStateRegistry): device
keyed snapshots ({"kind": "tpu"}) are re-ordered by key group, split into
KEY-GROUP PAGES, and stored as content-addressed chunks under
``<dir>/chunks/``. A page whose key membership and values did not change
since the previous checkpoint hashes identically and is NOT rewritten —
checkpoint bytes are O(changed pages), while every checkpoint stays
logically self-contained (its manifest references the chunks it needs; a
refcount GC deletes chunks when their last referencing checkpoint is
subsumed). Savepoints are always written full and inline (user-owned,
relocatable — reference canonical-format semantics).

Verified recovery: every stored checkpoint carries a ``_manifest.json``
(per-chunk payload sizes + digests and a whole-metadata checksum,
committed write-tmp/fsync/rename), restore recomputes chunk content
digests against the manifest/filename and raises a typed
``CorruptArtifactError`` instead of materializing garbage, and the
restore paths walk backward through the retained checkpoints when a
candidate fails verification (quarantining the corrupt artifact as
``<dir>.corrupt``). See docs/ROBUSTNESS.md "Verified recovery".
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..core.config import CheckpointingOptions

__all__ = ["CompletedCheckpoint", "CheckpointStorage", "MemoryCheckpointStorage",
           "FsCheckpointStorage", "CorruptArtifactError",
           "CheckpointNotFoundError", "retained_checkpoint_dirs"]


class CorruptArtifactError(RuntimeError):
    """A checkpoint artifact (chunk, metadata, changelog segment) failed
    its integrity check — digest mismatch, truncation, or an undecodable
    payload. Restore paths treat the artifact as unusable and fall back
    to the next-oldest retained checkpoint; the job fails with this
    error only when NO retained checkpoint verifies (restoring from
    scratch past committed output would violate exactly-once)."""


class CheckpointNotFoundError(FileNotFoundError, KeyError):
    """No checkpoint exists at the requested id/path. Subclasses both
    FileNotFoundError and KeyError so pre-typed callers keep working."""

    def __str__(self):  # KeyError quotes its arg; keep the message plain
        return self.args[0] if self.args else ""


#: Per-checkpoint integrity manifest (sibling of ``_metadata``).
MANIFEST_NAME = "_manifest.json"


def _payload_digest(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=16).hexdigest()


def _fsync_write(path: str, data: bytes) -> None:
    """Atomic durable publish: write-tmp, fsync, rename."""
    tmp = path + ".part"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def retained_checkpoint_dirs(directory: str) -> list:
    """``(checkpoint_id, path)`` for every retained ``chk-*``/``sp-*``
    directory under ``directory``, ordered oldest first. Quarantined
    ``*.corrupt`` directories and non-checkpoint entries are skipped."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if ".corrupt" in name:
            continue
        prefix, _, tail = name.partition("-")
        if prefix not in ("chk", "sp") or not tail:
            continue
        path = os.path.join(directory, name)
        if not os.path.isdir(path):
            continue
        try:
            out.append((int(tail), path))
        except ValueError:
            continue
    out.sort()
    return out


@dataclass
class CompletedCheckpoint:
    checkpoint_id: int
    timestamp: float
    # task_id -> task snapshot ({"reader":..., "chain": {...}})
    task_snapshots: dict[str, dict]
    is_savepoint: bool = False
    external_path: Optional[str] = None
    # topology at snapshot time, for rescaling restore
    vertex_parallelism: dict[str, int] = field(default_factory=dict)
    # vertex id -> stable uid, for restore into a RESUBMITTED program whose
    # generated vertex ids differ (reference operator-uid mapping)
    vertex_uids: dict[str, str] = field(default_factory=dict)


class CheckpointStorage:
    def store(self, checkpoint: CompletedCheckpoint) -> CompletedCheckpoint:
        raise NotImplementedError

    def discard(self, checkpoint: CompletedCheckpoint) -> None:
        pass

    def load(self, path_or_id: Any) -> CompletedCheckpoint:
        raise NotImplementedError


def _bounded_io(site: str, fn):
    """Run one storage operation under the stall watchdog
    (``watchdog.checkpoint-timeout``). The write/read is idempotent
    (atomic publish + content-addressed chunks), so one in-place stall
    retry is safe; a repeated stall raises StallError — which the
    coordinators tolerate for writes exactly like any other failed
    store, and which fails the restore (-> restart strategy) for loads.
    Raising fault trips keep their PR-2 single-visit semantics (a failed
    write aborts the checkpoint; it is NOT absorbed by retry)."""
    from ..metrics.device import DEVICE_STATS
    from ..runtime.faults import FAULTS
    from ..runtime.watchdog import WATCHDOG, StallError

    def _body():
        FAULTS.fire(site)
        return fn()

    attempt = 0
    while True:
        try:
            return WATCHDOG.run(site, _body, scope="checkpoint.storage")
        except StallError:
            if attempt >= WATCHDOG.stall_retries:
                raise
            attempt += 1
            DEVICE_STATS.note_retry(site)


class MemoryCheckpointStorage(CheckpointStorage):
    def __init__(self):
        self._store: dict[int, CompletedCheckpoint] = {}

    def store(self, checkpoint: CompletedCheckpoint) -> CompletedCheckpoint:
        def _write():
            self._store[checkpoint.checkpoint_id] = checkpoint
            return checkpoint

        return _bounded_io("checkpoint.write", _write)

    def discard(self, checkpoint: CompletedCheckpoint) -> None:
        self._store.pop(checkpoint.checkpoint_id, None)

    def load(self, checkpoint_id: int) -> CompletedCheckpoint:
        try:
            return self._store[checkpoint_id]
        except KeyError:
            raise CheckpointNotFoundError(
                f"no checkpoint with id {checkpoint_id} in memory "
                "storage") from None


class _ChunkRef:
    """Manifest placeholder for a content-addressed page on disk
    (legacy format — still readable; new manifests use _PagedState's
    compact digest list)."""

    __slots__ = ("hash", "dtype", "shape")

    def __init__(self, h: str, dtype: str, shape: tuple):
        self.hash = h
        self.dtype = dtype
        self.shape = shape


class _PagedState:
    """One state's values split into key-group pages, reassembled by
    concatenation along the last (key) axis.

    Manifest cost is what makes an *unchanged* checkpoint cheap, so the
    per-page record is a bare 16-byte content digest; dtype and leading
    shape are stored once here and each page's last-axis length is
    derived from its decompressed byte count."""

    __slots__ = ("pages", "dtype", "lead_shape")

    def __init__(self, pages: list, dtype: str = None, lead_shape: tuple = None):
        self.pages = pages          # list[bytes] digests (or legacy _ChunkRef)
        self.dtype = dtype
        self.lead_shape = lead_shape

    def __reduce__(self):
        return (_PagedState, (self.pages, getattr(self, "dtype", None),
                              getattr(self, "lead_shape", None)))


N_PAGES = 16  # key-group space divided into this many dedup pages


class FsCheckpointStorage(CheckpointStorage):
    def __init__(self, directory: str, incremental: bool = True,
                 config=None):
        self.directory = directory
        self.incremental = incremental
        self.chunk_dir = os.path.join(directory, "chunks")
        os.makedirs(self.chunk_dir, exist_ok=True)
        self._refs_path = os.path.join(self.chunk_dir, "_refs.pkl")
        # payload identity (size, digest of the stored bytes) per chunk,
        # captured at write time so manifests never re-read every chunk;
        # pre-existing chunks are read once on first reference
        self._chunk_info: dict[str, tuple] = {}
        self._current_chunks: set = set()  # chunks referenced by one store
        self.verify_on_restore = True
        self.quarantine_corrupt = True
        if config is not None:
            self.verify_on_restore = bool(
                config.get(CheckpointingOptions.VERIFY_ON_RESTORE))
            self.quarantine_corrupt = bool(
                config.get(CheckpointingOptions.QUARANTINE_CORRUPT))
        # refs load LAST: a lost/corrupt refs file rebuilds by scanning
        # checkpoint manifests/metadata, which needs the flags above
        self._refs: dict[str, set] = self._load_refs()
        self.last_bytes_written = 0  # chunk + metadata bytes of last store

    def _load_refs(self) -> dict[str, set]:
        """Refcounts from ``_refs.pkl`` — rebuilt by scanning the
        surviving checkpoint manifests when the file is lost OR corrupt.
        Starting from ``{}`` after a lost refs file would let GC delete
        chunks still referenced by retained checkpoints; a corrupt pickle
        (not just a short read) used to crash storage construction."""
        try:
            with open(self._refs_path, "rb") as f:
                refs = pickle.load(f)
            if isinstance(refs, dict):
                return refs
        except FileNotFoundError:
            # a fresh directory has no refs file AND no checkpoints: the
            # rebuild below naturally returns {} then — and recovers the
            # real counts when checkpoints exist but the file was lost
            pass
        except Exception:  # noqa: BLE001 - any unpicklable/corrupt refs
            pass
        return self._rebuild_refs()

    def _rebuild_refs(self) -> dict:
        """Scan every retained checkpoint for the chunks it references:
        the manifest's chunk list when present, else the decoded metadata
        (legacy checkpoints). Unreadable checkpoints contribute nothing —
        their chunks are only GC-able once every READABLE referent is
        subsumed, which errs on the side of keeping bytes."""
        refs: dict = {}

        def note(h, cid):
            refs.setdefault(h, set()).add(cid)

        def walk(obj, cid):
            if isinstance(obj, _PagedState):
                for p in obj.pages:
                    note(p.hash if isinstance(p, _ChunkRef) else p, cid)
            elif isinstance(obj, _ChunkRef):
                note(obj.hash, cid)
            elif isinstance(obj, dict):
                for v in obj.values():
                    walk(v, cid)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    walk(v, cid)

        for cid, path in retained_checkpoint_dirs(self.directory):
            try:
                manifest = self._read_manifest(path)
                if manifest is not None:
                    for name in (manifest.get("chunks") or {}):
                        note(bytes.fromhex(name), cid)
                    continue
                cp = self._load_inner(path, resolve=False)
                walk(cp.task_snapshots, cid)
            except Exception:  # noqa: BLE001 - skip unreadable checkpoints
                continue
        return refs

    def _save_refs(self) -> None:
        with open(self._refs_path + ".part", "wb") as f:
            pickle.dump(self._refs, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(self._refs_path + ".part", self._refs_path)

    def _path(self, checkpoint: CompletedCheckpoint) -> str:
        prefix = "sp" if checkpoint.is_savepoint else "chk"
        return os.path.join(self.directory, f"{prefix}-{checkpoint.checkpoint_id}")

    # -- chunking ------------------------------------------------------
    def _write_chunk(self, arr: np.ndarray, ckpt_id: int) -> bytes:
        """Write one page; returns its 16-byte content digest. The dtype
        and leading dims participate in the hash (two byte-identical pages
        of different dtype must not collide) but are NOT stored per page —
        the enclosing _PagedState carries them once."""
        raw = np.ascontiguousarray(arr).tobytes()
        h = hashlib.blake2b(
            raw + str((arr.dtype, arr.shape[:-1])).encode(),
            digest_size=16).digest()
        name = h.hex()
        path = os.path.join(self.chunk_dir, name)
        if not os.path.exists(path):
            from ..native import compress
            payload = compress(raw)
            with open(path + ".part", "wb") as f:
                f.write(payload)
            os.replace(path + ".part", path)
            self.last_bytes_written += len(payload)
            self._chunk_info[name] = (len(payload), _payload_digest(payload))
        elif name not in self._chunk_info:
            # dedup hit on a chunk written by a previous process: capture
            # its payload identity once so manifests never re-read every
            # chunk per checkpoint
            with open(path, "rb") as f:
                data = f.read()
            self._chunk_info[name] = (len(data), _payload_digest(data))
        self._refs.setdefault(h, set()).add(ckpt_id)
        self._current_chunks.add(name)
        # artifact-corruption fault sites fire AFTER the manifest identity
        # was captured, so verification sees exactly what a bad disk would
        # produce (and a shared-chunk hit poisons every referent, the
        # scenario the fallback chain exists for)
        self._fault_mutate_chunk(path)
        return h

    @staticmethod
    def _fault_mutate_chunk(path: str) -> None:
        """Deterministic artifact-corruption sites: every chunk write
        visits ``checkpoint.corrupt`` (bit-flip one byte mid-file) and
        ``checkpoint.truncate`` (drop the second half of the file)."""
        from ..runtime.faults import FAULTS
        if not FAULTS.enabled:
            return
        if FAULTS.check("checkpoint.corrupt"):
            try:
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.seek(size // 2)
                    b = f.read(1)
                    f.seek(size // 2)
                    f.write(bytes([(b[0] if b else 0) ^ 0x40]))
            except OSError:
                pass
        if FAULTS.check("checkpoint.truncate"):
            try:
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.truncate(max(size // 2, 1))
            except OSError:
                pass

    def _read_chunk(self, ref, chunk_dir: Optional[str] = None,
                    dtype: Optional[str] = None,
                    lead_shape: Optional[tuple] = None) -> np.ndarray:
        if isinstance(ref, _ChunkRef):  # legacy manifest
            name, dt, shape = ref.hash, np.dtype(ref.dtype), ref.shape
        else:
            name, dt = ref.hex(), np.dtype(dtype)
            shape = None
        path = os.path.join(chunk_dir or self.chunk_dir, name)
        try:
            with open(path, "rb") as f:
                payload = f.read()
        except FileNotFoundError as e:
            raise CorruptArtifactError(
                f"checkpoint chunk {name} is missing from "
                f"{os.path.dirname(path)}") from e
        try:
            from ..native import decompress
            raw = decompress(payload)
        except CorruptArtifactError:
            raise
        except Exception as e:  # noqa: BLE001 - truncated/garbled payload
            raise CorruptArtifactError(
                f"checkpoint chunk {name} is undecodable "
                f"({type(e).__name__}: {e})") from e
        if shape is None:
            if self.verify_on_restore:
                # the filename IS the content digest: recompute it from
                # the decompressed bytes + the dtype/lead-shape that
                # participated in the write-side hash
                got = hashlib.blake2b(
                    raw + str((dt, tuple(lead_shape or ()))).encode(),
                    digest_size=16).digest()
                if got != ref:
                    raise CorruptArtifactError(
                        f"checkpoint chunk {name} failed content-digest "
                        "verification (stored bytes do not hash to the "
                        "chunk's content address)")
            lead = 1
            for d in lead_shape:
                lead *= d
            n = len(raw) // dt.itemsize
            shape = tuple(lead_shape) + (n // lead if lead else 0,)
        try:
            return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
        except ValueError as e:
            raise CorruptArtifactError(
                f"checkpoint chunk {name} has the wrong byte count for "
                f"shape {shape} ({e})") from e

    def _page_tpu_snapshot(self, snap: dict, ckpt_id: int) -> dict:
        """Reorder a device keyed snapshot by key group and replace its
        value arrays — AND the keys/groups themselves — with
        key-group-page chunk refs. Page boundaries are fixed spans of the
        job's max-parallelism key-group space (stable across checkpoints),
        so a page's bytes only change when one of ITS key groups changed."""
        keys = np.asarray(snap["keys"])
        groups = np.asarray(snap["key_groups"])
        if len(keys) == 0:
            return snap
        order = np.lexsort((keys, groups))
        keys, groups = keys[order], groups[order]
        mp = int(snap.get("max_parallelism") or (int(groups.max()) + 1))
        # page boundaries: equal spans of the key-group space
        bounds = np.searchsorted(
            groups, np.arange(1, N_PAGES) * ((mp + N_PAGES - 1) // N_PAGES))
        out = dict(snap)
        out["keys"] = _PagedState(
            [self._write_chunk(p, ckpt_id)
             for p in np.split(keys, bounds)],
            str(keys.dtype), ())
        out["key_groups"] = _PagedState(
            [self._write_chunk(p, ckpt_id)
             for p in np.split(groups, bounds)],
            str(groups.dtype), ())
        states = {}
        for name, sdata in snap["states"].items():
            vals = np.asarray(sdata["values"])
            vals = vals[..., order]
            pages = [self._write_chunk(np.ascontiguousarray(p), ckpt_id)
                     for p in np.split(vals, bounds, axis=-1)]
            sd = dict(sdata)
            sd["values"] = _PagedState(pages, str(vals.dtype),
                                       vals.shape[:-1])
            states[name] = sd
        out["states"] = states
        return out

    def _resolve(self, obj, chunk_dir: Optional[str] = None):
        """Recursively materialize chunk refs back into numpy arrays."""
        if isinstance(obj, _ChunkRef):
            return self._read_chunk(obj, chunk_dir)
        if isinstance(obj, _PagedState):
            # pre-upgrade pickles carry only the 'pages' slot (of _ChunkRef
            # entries, which ignore the dtype/lead_shape arguments)
            dtype = getattr(obj, "dtype", None)
            lead = getattr(obj, "lead_shape", None)
            parts = [self._read_chunk(r, chunk_dir, dtype, lead)
                     for r in obj.pages]
            parts = [p for p in parts if p.shape[-1]]
            if not parts:
                return np.empty(0)
            return np.concatenate(parts, axis=-1)
        if isinstance(obj, dict):
            return {k: self._resolve(v, chunk_dir) for k, v in obj.items()}
        if isinstance(obj, list):
            return [self._resolve(v, chunk_dir) for v in obj]
        if isinstance(obj, tuple):
            return tuple(self._resolve(v, chunk_dir) for v in obj)
        return obj

    def _chunk_snapshots(self, checkpoint: CompletedCheckpoint) -> dict:
        """Walk task snapshots; page every device keyed snapshot."""
        def walk(obj):
            if isinstance(obj, dict):
                if obj.get("kind") == "tpu" and "keys" in obj:
                    return self._page_tpu_snapshot(
                        obj, checkpoint.checkpoint_id)
                return {k: walk(v) for k, v in obj.items()}
            if isinstance(obj, list):
                return [walk(v) for v in obj]
            if isinstance(obj, tuple):
                return tuple(walk(v) for v in obj)
            return obj

        return {tid: walk(s)
                for tid, s in checkpoint.task_snapshots.items()}

    # -- versioned metadata encoding -----------------------------------
    # The TypeSerializerSnapshot analog (flink-core api/common/typeutils/
    # TypeSerializerSnapshot.java): checkpoint metadata is written as a
    # VERSIONED, self-describing structure — framework classes are encoded
    # as tagged plain dicts before pickling, so the on-disk format
    # survives refactors of those classes (only plain containers, scalars,
    # numpy arrays, and user payload types hit the pickle stream). The
    # restore side rebuilds through a tag registry and still reads every
    # older format (legacy class-pickle, uncompressed).

    def _encode(self, obj):
        if isinstance(obj, CompletedCheckpoint):
            return {"__ftck__": "checkpoint",
                    "checkpoint_id": obj.checkpoint_id,
                    "timestamp": obj.timestamp,
                    "task_snapshots": self._encode(obj.task_snapshots),
                    "is_savepoint": obj.is_savepoint,
                    "external_path": obj.external_path,
                    "vertex_parallelism": dict(obj.vertex_parallelism),
                    "vertex_uids": dict(obj.vertex_uids)}
        if isinstance(obj, _PagedState):
            return {"__ftck__": "paged",
                    "pages": list(obj.pages),
                    "dtype": getattr(obj, "dtype", None),
                    "lead_shape": getattr(obj, "lead_shape", None)}
        if isinstance(obj, _ChunkRef):
            return {"__ftck__": "chunk", "hash": obj.hash,
                    "dtype": obj.dtype, "shape": obj.shape}
        if isinstance(obj, dict):
            enc = {k: self._encode(v) for k, v in obj.items()}
            if "__ftck__" in obj:
                # keep the encoding injective: a user dict carrying the
                # reserved tag key must not decode as a framework type
                return {"__ftck__": "escaped", "value": enc}
            return enc
        if isinstance(obj, list):
            return [self._encode(v) for v in obj]
        if isinstance(obj, tuple):
            return {"__ftck__": "tuple",
                    "items": [self._encode(v) for v in obj]}
        return obj

    def _decode(self, obj):
        if isinstance(obj, dict):
            tag = obj.get("__ftck__")
            if tag == "escaped":
                # the wrapped dict's OWN top level is plain data — decode
                # only its values, never its (user-owned) tag key
                return {k: self._decode(v)
                        for k, v in obj["value"].items()}
            if tag == "checkpoint":
                # keyword construction: field insertions/reorders in the
                # dataclass must not misassign decoded values
                return CompletedCheckpoint(
                    checkpoint_id=obj["checkpoint_id"],
                    timestamp=obj["timestamp"],
                    task_snapshots=self._decode(obj["task_snapshots"]),
                    is_savepoint=obj["is_savepoint"],
                    external_path=obj["external_path"],
                    vertex_parallelism=obj["vertex_parallelism"],
                    vertex_uids=obj["vertex_uids"])
            if tag == "paged":
                return _PagedState(obj["pages"], obj["dtype"],
                                   obj["lead_shape"])
            if tag == "chunk":
                return _ChunkRef(obj["hash"], obj["dtype"], obj["shape"])
            if tag == "tuple":
                return tuple(self._decode(v) for v in obj["items"])
            return {k: self._decode(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [self._decode(v) for v in obj]
        return obj

    # -- storage API ---------------------------------------------------
    def store(self, checkpoint: CompletedCheckpoint) -> CompletedCheckpoint:
        return _bounded_io("checkpoint.write",
                           lambda: self._store_inner(checkpoint))

    def _store_inner(self, checkpoint: CompletedCheckpoint
                     ) -> CompletedCheckpoint:
        d = self._path(checkpoint)
        os.makedirs(d, exist_ok=True)
        # set the path BEFORE pickling so a checkpoint load()ed from disk
        # knows where it lives
        checkpoint.external_path = d
        self.last_bytes_written = 0
        self._current_chunks = set()
        to_write = checkpoint
        incremental = self.incremental and not checkpoint.is_savepoint
        if incremental:
            to_write = CompletedCheckpoint(
                checkpoint.checkpoint_id, checkpoint.timestamp,
                self._chunk_snapshots(checkpoint),
                checkpoint.is_savepoint, checkpoint.external_path,
                checkpoint.vertex_parallelism, checkpoint.vertex_uids)
        # block-compressed like the reference's snapshot compression
        # (io/compression/BlockCompressionFactory); native LZ4-style codec
        # when built, zlib otherwise — self-describing tag either way
        from ..native import compress
        meta_bytes = _VERSIONED_MAGIC + compress(pickle.dumps(
            self._encode(to_write), protocol=pickle.HIGHEST_PROTOCOL))
        # integrity manifest first, metadata rename last: the metadata
        # stays the commit point, and a published checkpoint always has
        # its manifest. A crash between chunk writes and these renames
        # leaves orphan chunks + an incomplete dir — never a checkpoint
        # that loads without being verifiable.
        manifest = {
            "format": 1,
            "checkpoint_id": checkpoint.checkpoint_id,
            "savepoint": bool(checkpoint.is_savepoint),
            "metadata_size": len(meta_bytes),
            "metadata_digest": _payload_digest(meta_bytes),
            "chunks": {name: {"size": self._chunk_info[name][0],
                              "digest": self._chunk_info[name][1]}
                       for name in sorted(self._current_chunks)},
        }
        _fsync_write(os.path.join(d, MANIFEST_NAME),
                     json.dumps(manifest, sort_keys=True).encode())
        _fsync_write(os.path.join(d, "_metadata"), meta_bytes)
        if incremental:
            # refs persist only AFTER the metadata exists: a crash mid-store
            # leaves orphan chunk files (re-usable, GC-able) rather than
            # phantom refs that would pin shared chunks forever
            self._save_refs()
        self.last_bytes_written += len(meta_bytes)
        return checkpoint

    def discard(self, checkpoint: CompletedCheckpoint) -> None:
        if checkpoint.is_savepoint:
            return  # savepoints are user-owned (reference semantics)
        d = self._path(checkpoint)
        shutil.rmtree(d, ignore_errors=True)
        self._release_refs(checkpoint.checkpoint_id)

    def _release_refs(self, cid: int) -> None:
        """Drop one checkpoint's chunk references; GC chunks whose last
        referent it was (shared chunks survive for older checkpoints)."""
        dead = []
        for h, refs in self._refs.items():
            refs.discard(cid)
            if not refs:
                dead.append(h)
        for h in dead:
            self._refs.pop(h, None)
            name = h.hex() if isinstance(h, bytes) else h
            try:
                os.remove(os.path.join(self.chunk_dir, name))
            except OSError:
                pass
        if dead:
            self._save_refs()

    # -- verification ---------------------------------------------------
    @staticmethod
    def _read_manifest(path: str) -> Optional[dict]:
        """The checkpoint directory's integrity manifest, or None for a
        legacy (pre-manifest) checkpoint. An unreadable manifest IS
        corruption — it was fsync-renamed atomically."""
        try:
            with open(os.path.join(path, MANIFEST_NAME), "rb") as f:
                return json.loads(f.read())
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            raise CorruptArtifactError(
                f"unreadable checkpoint manifest in {path}: {e}") from e

    def verify_checkpoint(self, path: str) -> dict:
        """Offline integrity check of one stored checkpoint: the
        manifest's whole-metadata checksum plus every referenced chunk's
        size and payload digest (no decompression, no materialization).
        Legacy checkpoints without a manifest are verified the expensive
        way — a full decode+resolve, which checks the content digests of
        every new-style chunk ref. Returns ``{"chunks": n, "bytes": m,
        "manifest": bool}``; raises CheckpointNotFoundError /
        CorruptArtifactError."""
        d = path.rstrip("/")
        meta = d if d.endswith("_metadata") else os.path.join(d, "_metadata")
        d = os.path.dirname(meta)
        try:
            with open(meta, "rb") as f:
                meta_bytes = f.read()
        except FileNotFoundError as e:
            raise CheckpointNotFoundError(
                f"no checkpoint metadata at {meta}") from e
        manifest = self._read_manifest(d)
        if manifest is None:
            try:
                self._load_inner(meta, resolve=True)
            except (CorruptArtifactError, CheckpointNotFoundError):
                raise
            except Exception as e:  # noqa: BLE001 - undecodable legacy
                raise CorruptArtifactError(
                    f"legacy checkpoint at {d} is undecodable "
                    f"({type(e).__name__}: {e})") from e
            return {"chunks": 0, "bytes": len(meta_bytes), "manifest": False}
        if (manifest.get("metadata_size") != len(meta_bytes)
                or manifest.get("metadata_digest")
                != _payload_digest(meta_bytes)):
            raise CorruptArtifactError(
                f"checkpoint metadata at {meta} does not match its "
                "manifest checksum")
        chunk_dir = os.path.join(os.path.dirname(os.path.abspath(d)),
                                 "chunks")
        total = len(meta_bytes)
        for name, info in (manifest.get("chunks") or {}).items():
            cpath = os.path.join(chunk_dir, name)
            try:
                with open(cpath, "rb") as f:
                    data = f.read()
            except FileNotFoundError as e:
                raise CorruptArtifactError(
                    f"chunk {name} referenced by {d} is missing") from e
            if (len(data) != info.get("size")
                    or _payload_digest(data) != info.get("digest")):
                raise CorruptArtifactError(
                    f"chunk {name} referenced by {d} failed its "
                    "size/digest check")
            total += len(data)
        return {"chunks": len(manifest.get("chunks") or {}),
                "bytes": total, "manifest": True}

    def quarantine(self, checkpoint_or_path) -> Optional[str]:
        """Quarantine a corrupt checkpoint: rename its directory to
        ``<dir>.corrupt`` (so it never sits first in the restore order
        again) and release its chunk refs — chunks whose only referent it
        was are GC'd; shared chunks survive for the older retained
        checkpoints that still reference them. Returns the quarantine
        path, or None when the rename was impossible."""
        if isinstance(checkpoint_or_path, CompletedCheckpoint):
            d = (checkpoint_or_path.external_path
                 or self._path(checkpoint_or_path))
            cid = checkpoint_or_path.checkpoint_id
        else:
            d = str(checkpoint_or_path).rstrip("/")
            try:
                cid = int(os.path.basename(d).split("-", 1)[1])
            except (IndexError, ValueError):
                cid = None
        dest, i = d + ".corrupt", 0
        while os.path.exists(dest):
            i += 1
            dest = f"{d}.corrupt.{i}"
        try:
            os.rename(d, dest)
        except OSError:
            dest = None
        if cid is not None:
            self._release_refs(cid)
        return dest

    def load(self, path: str,
             resolve: bool = True) -> CompletedCheckpoint:
        """``resolve=False`` returns the checkpoint with chunk REFS still
        in place (metadata is fully usable: ids, uids, parallelism) —
        callers that substitute some tasks' snapshots from elsewhere
        (local recovery) resolve only the remainder via resolve_tasks,
        skipping those tasks' chunk reads entirely.

        Deadline-bounded (site checkpoint.load): a restore reading from a
        wedged checkpoint volume stalls into StallError instead of
        freezing recovery — the restart strategy then handles it like any
        other failed restore attempt."""
        return _bounded_io("checkpoint.load",
                           lambda: self._load_inner(path, resolve))

    def _load_inner(self, path: str, resolve: bool) -> CompletedCheckpoint:
        meta = path if path.endswith("_metadata") else os.path.join(path,
                                                                    "_metadata")
        try:
            with open(meta, "rb") as f:
                data = f.read()
        except FileNotFoundError as e:
            raise CheckpointNotFoundError(
                f"no checkpoint at {path}") from e
        if self.verify_on_restore:
            # whole-metadata checksum from the manifest (when one exists:
            # legacy checkpoints predate manifests) BEFORE decoding
            manifest = self._read_manifest(
                os.path.dirname(os.path.abspath(meta)))
            if manifest is not None and (
                    manifest.get("metadata_size") != len(data)
                    or manifest.get("metadata_digest")
                    != _payload_digest(data)):
                raise CorruptArtifactError(
                    f"checkpoint metadata at {meta} does not match its "
                    "manifest checksum")
        try:
            if data.startswith(_VERSIONED_MAGIC):
                from ..native import decompress
                cp = self._decode(pickle.loads(
                    decompress(data[len(_VERSIONED_MAGIC):])))
            elif data.startswith(_COMPRESSED_MAGIC):
                # format v1: compressed class-pickle
                from ..native import decompress
                cp = pickle.loads(decompress(data[len(_COMPRESSED_MAGIC):]))
            else:
                cp = pickle.loads(data)  # pre-compression snapshots
        except CorruptArtifactError:
            raise
        except Exception as e:  # noqa: BLE001 - truncated/garbled metadata
            raise CorruptArtifactError(
                f"checkpoint metadata at {meta} is undecodable "
                f"({type(e).__name__}: {e})") from e
        # chunk refs resolve against the sibling chunks/ dir of wherever
        # this metadata actually lives (the storage instance may have been
        # constructed for a different root)
        chunk_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(meta))),
            "chunks")
        cp._chunk_dir = chunk_dir
        if resolve:
            cp.task_snapshots = self._resolve(cp.task_snapshots, chunk_dir)
        return cp

    def resolve_tasks(self, cp: CompletedCheckpoint,
                      skip: "set[str]" = frozenset()) -> None:
        """Materialize chunk refs for every task NOT in ``skip`` (whose
        snapshots the caller replaces; their chunks are never read)."""
        chunk_dir = getattr(cp, "_chunk_dir", None)
        cp.task_snapshots = {
            tid: (snap if tid in skip
                  else self._resolve(snap, chunk_dir))
            for tid, snap in cp.task_snapshots.items()}


_COMPRESSED_MAGIC = b"FTCK"   # format v1: compressed class-pickle (legacy)
_VERSIONED_MAGIC = b"FTC2"    # format v2: compressed tagged-plain encoding
