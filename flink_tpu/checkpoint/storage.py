"""Checkpoint storage: where completed snapshots live.

Analog of the reference's CheckpointStorage
(flink-runtime state/filesystem/FsCheckpointStorageAccess.java:44 and
JobManagerCheckpointStorage): in-memory for tests, filesystem directory
layout ``<dir>/chk-<id>/metadata`` for durability. Snapshots are
host-serialized (device state was already DMA'd to numpy by the backends'
snapshot()).

Incremental checkpoints (VERDICT #5; the RocksDB SST-diff analog,
RocksIncrementalSnapshotStrategy.java:70 + SharedStateRegistry): device
keyed snapshots ({"kind": "tpu"}) are re-ordered by key group, split into
KEY-GROUP PAGES, and stored as content-addressed chunks under
``<dir>/chunks/``. A page whose key membership and values did not change
since the previous checkpoint hashes identically and is NOT rewritten —
checkpoint bytes are O(changed pages), while every checkpoint stays
logically self-contained (its manifest references the chunks it needs; a
refcount GC deletes chunks when their last referencing checkpoint is
subsumed). Savepoints are always written full and inline (user-owned,
relocatable — reference canonical-format semantics).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

__all__ = ["CompletedCheckpoint", "CheckpointStorage", "MemoryCheckpointStorage",
           "FsCheckpointStorage"]


@dataclass
class CompletedCheckpoint:
    checkpoint_id: int
    timestamp: float
    # task_id -> task snapshot ({"reader":..., "chain": {...}})
    task_snapshots: dict[str, dict]
    is_savepoint: bool = False
    external_path: Optional[str] = None
    # topology at snapshot time, for rescaling restore
    vertex_parallelism: dict[str, int] = field(default_factory=dict)
    # vertex id -> stable uid, for restore into a RESUBMITTED program whose
    # generated vertex ids differ (reference operator-uid mapping)
    vertex_uids: dict[str, str] = field(default_factory=dict)


class CheckpointStorage:
    def store(self, checkpoint: CompletedCheckpoint) -> CompletedCheckpoint:
        raise NotImplementedError

    def discard(self, checkpoint: CompletedCheckpoint) -> None:
        pass

    def load(self, path_or_id: Any) -> CompletedCheckpoint:
        raise NotImplementedError


def _bounded_io(site: str, fn):
    """Run one storage operation under the stall watchdog
    (``watchdog.checkpoint-timeout``). The write/read is idempotent
    (atomic publish + content-addressed chunks), so one in-place stall
    retry is safe; a repeated stall raises StallError — which the
    coordinators tolerate for writes exactly like any other failed
    store, and which fails the restore (-> restart strategy) for loads.
    Raising fault trips keep their PR-2 single-visit semantics (a failed
    write aborts the checkpoint; it is NOT absorbed by retry)."""
    from ..metrics.device import DEVICE_STATS
    from ..runtime.faults import FAULTS
    from ..runtime.watchdog import WATCHDOG, StallError

    def _body():
        FAULTS.fire(site)
        return fn()

    attempt = 0
    while True:
        try:
            return WATCHDOG.run(site, _body, scope="checkpoint.storage")
        except StallError:
            if attempt >= WATCHDOG.stall_retries:
                raise
            attempt += 1
            DEVICE_STATS.note_retry(site)


class MemoryCheckpointStorage(CheckpointStorage):
    def __init__(self):
        self._store: dict[int, CompletedCheckpoint] = {}

    def store(self, checkpoint: CompletedCheckpoint) -> CompletedCheckpoint:
        def _write():
            self._store[checkpoint.checkpoint_id] = checkpoint
            return checkpoint

        return _bounded_io("checkpoint.write", _write)

    def discard(self, checkpoint: CompletedCheckpoint) -> None:
        self._store.pop(checkpoint.checkpoint_id, None)

    def load(self, checkpoint_id: int) -> CompletedCheckpoint:
        return self._store[checkpoint_id]


class _ChunkRef:
    """Manifest placeholder for a content-addressed page on disk
    (legacy format — still readable; new manifests use _PagedState's
    compact digest list)."""

    __slots__ = ("hash", "dtype", "shape")

    def __init__(self, h: str, dtype: str, shape: tuple):
        self.hash = h
        self.dtype = dtype
        self.shape = shape


class _PagedState:
    """One state's values split into key-group pages, reassembled by
    concatenation along the last (key) axis.

    Manifest cost is what makes an *unchanged* checkpoint cheap, so the
    per-page record is a bare 16-byte content digest; dtype and leading
    shape are stored once here and each page's last-axis length is
    derived from its decompressed byte count."""

    __slots__ = ("pages", "dtype", "lead_shape")

    def __init__(self, pages: list, dtype: str = None, lead_shape: tuple = None):
        self.pages = pages          # list[bytes] digests (or legacy _ChunkRef)
        self.dtype = dtype
        self.lead_shape = lead_shape

    def __reduce__(self):
        return (_PagedState, (self.pages, getattr(self, "dtype", None),
                              getattr(self, "lead_shape", None)))


N_PAGES = 16  # key-group space divided into this many dedup pages


class FsCheckpointStorage(CheckpointStorage):
    def __init__(self, directory: str, incremental: bool = True):
        self.directory = directory
        self.incremental = incremental
        self.chunk_dir = os.path.join(directory, "chunks")
        os.makedirs(self.chunk_dir, exist_ok=True)
        self._refs_path = os.path.join(self.chunk_dir, "_refs.pkl")
        self._refs: dict[str, set] = self._load_refs()
        self.last_bytes_written = 0  # chunk + metadata bytes of last store

    def _load_refs(self) -> dict[str, set]:
        try:
            with open(self._refs_path, "rb") as f:
                return pickle.load(f)
        except (OSError, EOFError):
            return {}

    def _save_refs(self) -> None:
        with open(self._refs_path + ".part", "wb") as f:
            pickle.dump(self._refs, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(self._refs_path + ".part", self._refs_path)

    def _path(self, checkpoint: CompletedCheckpoint) -> str:
        prefix = "sp" if checkpoint.is_savepoint else "chk"
        return os.path.join(self.directory, f"{prefix}-{checkpoint.checkpoint_id}")

    # -- chunking ------------------------------------------------------
    def _write_chunk(self, arr: np.ndarray, ckpt_id: int) -> bytes:
        """Write one page; returns its 16-byte content digest. The dtype
        and leading dims participate in the hash (two byte-identical pages
        of different dtype must not collide) but are NOT stored per page —
        the enclosing _PagedState carries them once."""
        raw = np.ascontiguousarray(arr).tobytes()
        h = hashlib.blake2b(
            raw + str((arr.dtype, arr.shape[:-1])).encode(),
            digest_size=16).digest()
        path = os.path.join(self.chunk_dir, h.hex())
        if not os.path.exists(path):
            from ..native import compress
            payload = compress(raw)
            with open(path + ".part", "wb") as f:
                f.write(payload)
            os.replace(path + ".part", path)
            self.last_bytes_written += len(payload)
        self._refs.setdefault(h, set()).add(ckpt_id)
        return h

    def _read_chunk(self, ref, chunk_dir: Optional[str] = None,
                    dtype: Optional[str] = None,
                    lead_shape: Optional[tuple] = None) -> np.ndarray:
        if isinstance(ref, _ChunkRef):  # legacy manifest
            name, dt, shape = ref.hash, np.dtype(ref.dtype), ref.shape
        else:
            name, dt = ref.hex(), np.dtype(dtype)
            shape = None
        with open(os.path.join(chunk_dir or self.chunk_dir, name),
                  "rb") as f:
            from ..native import decompress
            raw = decompress(f.read())
        if shape is None:
            lead = 1
            for d in lead_shape:
                lead *= d
            n = len(raw) // dt.itemsize
            shape = tuple(lead_shape) + (n // lead if lead else 0,)
        return np.frombuffer(raw, dtype=dt).reshape(shape).copy()

    def _page_tpu_snapshot(self, snap: dict, ckpt_id: int) -> dict:
        """Reorder a device keyed snapshot by key group and replace its
        value arrays — AND the keys/groups themselves — with
        key-group-page chunk refs. Page boundaries are fixed spans of the
        job's max-parallelism key-group space (stable across checkpoints),
        so a page's bytes only change when one of ITS key groups changed."""
        keys = np.asarray(snap["keys"])
        groups = np.asarray(snap["key_groups"])
        if len(keys) == 0:
            return snap
        order = np.lexsort((keys, groups))
        keys, groups = keys[order], groups[order]
        mp = int(snap.get("max_parallelism") or (int(groups.max()) + 1))
        # page boundaries: equal spans of the key-group space
        bounds = np.searchsorted(
            groups, np.arange(1, N_PAGES) * ((mp + N_PAGES - 1) // N_PAGES))
        out = dict(snap)
        out["keys"] = _PagedState(
            [self._write_chunk(p, ckpt_id)
             for p in np.split(keys, bounds)],
            str(keys.dtype), ())
        out["key_groups"] = _PagedState(
            [self._write_chunk(p, ckpt_id)
             for p in np.split(groups, bounds)],
            str(groups.dtype), ())
        states = {}
        for name, sdata in snap["states"].items():
            vals = np.asarray(sdata["values"])
            vals = vals[..., order]
            pages = [self._write_chunk(np.ascontiguousarray(p), ckpt_id)
                     for p in np.split(vals, bounds, axis=-1)]
            sd = dict(sdata)
            sd["values"] = _PagedState(pages, str(vals.dtype),
                                       vals.shape[:-1])
            states[name] = sd
        out["states"] = states
        return out

    def _resolve(self, obj, chunk_dir: Optional[str] = None):
        """Recursively materialize chunk refs back into numpy arrays."""
        if isinstance(obj, _ChunkRef):
            return self._read_chunk(obj, chunk_dir)
        if isinstance(obj, _PagedState):
            # pre-upgrade pickles carry only the 'pages' slot (of _ChunkRef
            # entries, which ignore the dtype/lead_shape arguments)
            dtype = getattr(obj, "dtype", None)
            lead = getattr(obj, "lead_shape", None)
            parts = [self._read_chunk(r, chunk_dir, dtype, lead)
                     for r in obj.pages]
            parts = [p for p in parts if p.shape[-1]]
            if not parts:
                return np.empty(0)
            return np.concatenate(parts, axis=-1)
        if isinstance(obj, dict):
            return {k: self._resolve(v, chunk_dir) for k, v in obj.items()}
        if isinstance(obj, list):
            return [self._resolve(v, chunk_dir) for v in obj]
        if isinstance(obj, tuple):
            return tuple(self._resolve(v, chunk_dir) for v in obj)
        return obj

    def _chunk_snapshots(self, checkpoint: CompletedCheckpoint) -> dict:
        """Walk task snapshots; page every device keyed snapshot."""
        def walk(obj):
            if isinstance(obj, dict):
                if obj.get("kind") == "tpu" and "keys" in obj:
                    return self._page_tpu_snapshot(
                        obj, checkpoint.checkpoint_id)
                return {k: walk(v) for k, v in obj.items()}
            if isinstance(obj, list):
                return [walk(v) for v in obj]
            if isinstance(obj, tuple):
                return tuple(walk(v) for v in obj)
            return obj

        return {tid: walk(s)
                for tid, s in checkpoint.task_snapshots.items()}

    # -- versioned metadata encoding -----------------------------------
    # The TypeSerializerSnapshot analog (flink-core api/common/typeutils/
    # TypeSerializerSnapshot.java): checkpoint metadata is written as a
    # VERSIONED, self-describing structure — framework classes are encoded
    # as tagged plain dicts before pickling, so the on-disk format
    # survives refactors of those classes (only plain containers, scalars,
    # numpy arrays, and user payload types hit the pickle stream). The
    # restore side rebuilds through a tag registry and still reads every
    # older format (legacy class-pickle, uncompressed).

    def _encode(self, obj):
        if isinstance(obj, CompletedCheckpoint):
            return {"__ftck__": "checkpoint",
                    "checkpoint_id": obj.checkpoint_id,
                    "timestamp": obj.timestamp,
                    "task_snapshots": self._encode(obj.task_snapshots),
                    "is_savepoint": obj.is_savepoint,
                    "external_path": obj.external_path,
                    "vertex_parallelism": dict(obj.vertex_parallelism),
                    "vertex_uids": dict(obj.vertex_uids)}
        if isinstance(obj, _PagedState):
            return {"__ftck__": "paged",
                    "pages": list(obj.pages),
                    "dtype": getattr(obj, "dtype", None),
                    "lead_shape": getattr(obj, "lead_shape", None)}
        if isinstance(obj, _ChunkRef):
            return {"__ftck__": "chunk", "hash": obj.hash,
                    "dtype": obj.dtype, "shape": obj.shape}
        if isinstance(obj, dict):
            enc = {k: self._encode(v) for k, v in obj.items()}
            if "__ftck__" in obj:
                # keep the encoding injective: a user dict carrying the
                # reserved tag key must not decode as a framework type
                return {"__ftck__": "escaped", "value": enc}
            return enc
        if isinstance(obj, list):
            return [self._encode(v) for v in obj]
        if isinstance(obj, tuple):
            return {"__ftck__": "tuple",
                    "items": [self._encode(v) for v in obj]}
        return obj

    def _decode(self, obj):
        if isinstance(obj, dict):
            tag = obj.get("__ftck__")
            if tag == "escaped":
                # the wrapped dict's OWN top level is plain data — decode
                # only its values, never its (user-owned) tag key
                return {k: self._decode(v)
                        for k, v in obj["value"].items()}
            if tag == "checkpoint":
                # keyword construction: field insertions/reorders in the
                # dataclass must not misassign decoded values
                return CompletedCheckpoint(
                    checkpoint_id=obj["checkpoint_id"],
                    timestamp=obj["timestamp"],
                    task_snapshots=self._decode(obj["task_snapshots"]),
                    is_savepoint=obj["is_savepoint"],
                    external_path=obj["external_path"],
                    vertex_parallelism=obj["vertex_parallelism"],
                    vertex_uids=obj["vertex_uids"])
            if tag == "paged":
                return _PagedState(obj["pages"], obj["dtype"],
                                   obj["lead_shape"])
            if tag == "chunk":
                return _ChunkRef(obj["hash"], obj["dtype"], obj["shape"])
            if tag == "tuple":
                return tuple(self._decode(v) for v in obj["items"])
            return {k: self._decode(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [self._decode(v) for v in obj]
        return obj

    # -- storage API ---------------------------------------------------
    def store(self, checkpoint: CompletedCheckpoint) -> CompletedCheckpoint:
        return _bounded_io("checkpoint.write",
                           lambda: self._store_inner(checkpoint))

    def _store_inner(self, checkpoint: CompletedCheckpoint
                     ) -> CompletedCheckpoint:
        d = self._path(checkpoint)
        os.makedirs(d, exist_ok=True)
        # set the path BEFORE pickling so a checkpoint load()ed from disk
        # knows where it lives
        checkpoint.external_path = d
        self.last_bytes_written = 0
        to_write = checkpoint
        incremental = self.incremental and not checkpoint.is_savepoint
        if incremental:
            to_write = CompletedCheckpoint(
                checkpoint.checkpoint_id, checkpoint.timestamp,
                self._chunk_snapshots(checkpoint),
                checkpoint.is_savepoint, checkpoint.external_path,
                checkpoint.vertex_parallelism, checkpoint.vertex_uids)
        # block-compressed like the reference's snapshot compression
        # (io/compression/BlockCompressionFactory); native LZ4-style codec
        # when built, zlib otherwise — self-describing tag either way
        from ..native import compress
        payload = compress(pickle.dumps(
            self._encode(to_write), protocol=pickle.HIGHEST_PROTOCOL))
        tmp = os.path.join(d, "_metadata.part")
        with open(tmp, "wb") as f:
            f.write(_VERSIONED_MAGIC)
            f.write(payload)
        final = os.path.join(d, "_metadata")
        os.replace(tmp, final)  # atomic publish
        if incremental:
            # refs persist only AFTER the metadata exists: a crash mid-store
            # leaves orphan chunk files (re-usable, GC-able) rather than
            # phantom refs that would pin shared chunks forever
            self._save_refs()
        self.last_bytes_written += len(payload)
        return checkpoint

    def discard(self, checkpoint: CompletedCheckpoint) -> None:
        if checkpoint.is_savepoint:
            return  # savepoints are user-owned (reference semantics)
        d = self._path(checkpoint)
        shutil.rmtree(d, ignore_errors=True)
        # release this checkpoint's chunk references; GC orphans
        cid = checkpoint.checkpoint_id
        dead = []
        for h, refs in self._refs.items():
            refs.discard(cid)
            if not refs:
                dead.append(h)
        for h in dead:
            self._refs.pop(h, None)
            name = h.hex() if isinstance(h, bytes) else h
            try:
                os.remove(os.path.join(self.chunk_dir, name))
            except OSError:
                pass
        if dead:
            self._save_refs()

    def load(self, path: str,
             resolve: bool = True) -> CompletedCheckpoint:
        """``resolve=False`` returns the checkpoint with chunk REFS still
        in place (metadata is fully usable: ids, uids, parallelism) —
        callers that substitute some tasks' snapshots from elsewhere
        (local recovery) resolve only the remainder via resolve_tasks,
        skipping those tasks' chunk reads entirely.

        Deadline-bounded (site checkpoint.load): a restore reading from a
        wedged checkpoint volume stalls into StallError instead of
        freezing recovery — the restart strategy then handles it like any
        other failed restore attempt."""
        return _bounded_io("checkpoint.load",
                           lambda: self._load_inner(path, resolve))

    def _load_inner(self, path: str, resolve: bool) -> CompletedCheckpoint:
        meta = path if path.endswith("_metadata") else os.path.join(path,
                                                                    "_metadata")
        with open(meta, "rb") as f:
            data = f.read()
        if data.startswith(_VERSIONED_MAGIC):
            from ..native import decompress
            cp = self._decode(pickle.loads(
                decompress(data[len(_VERSIONED_MAGIC):])))
        elif data.startswith(_COMPRESSED_MAGIC):
            # format v1: compressed class-pickle
            from ..native import decompress
            cp = pickle.loads(decompress(data[len(_COMPRESSED_MAGIC):]))
        else:
            cp = pickle.loads(data)  # pre-compression snapshots
        # chunk refs resolve against the sibling chunks/ dir of wherever
        # this metadata actually lives (the storage instance may have been
        # constructed for a different root)
        chunk_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(meta))),
            "chunks")
        cp._chunk_dir = chunk_dir
        if resolve:
            cp.task_snapshots = self._resolve(cp.task_snapshots, chunk_dir)
        return cp

    def resolve_tasks(self, cp: CompletedCheckpoint,
                      skip: "set[str]" = frozenset()) -> None:
        """Materialize chunk refs for every task NOT in ``skip`` (whose
        snapshots the caller replaces; their chunks are never read)."""
        chunk_dir = getattr(cp, "_chunk_dir", None)
        cp.task_snapshots = {
            tid: (snap if tid in skip
                  else self._resolve(snap, chunk_dir))
            for tid, snap in cp.task_snapshots.items()}


_COMPRESSED_MAGIC = b"FTCK"   # format v1: compressed class-pickle (legacy)
_VERSIONED_MAGIC = b"FTC2"    # format v2: compressed tagged-plain encoding
