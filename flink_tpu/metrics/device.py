"""Device-path accounting: compiles, program-cache hits, transfers.

The compiled fire/step programs are process-global ``lru_cache``-backed
builders (one executable shared by every operator instance with the same
shape signature — see runtime/operators/device_window.py), so their
accounting is process-global too: one ``DeviceStats`` singleton that the
instrumented builders and the explicit transfer sites feed, readable from
any ``MetricRegistry`` through ``bind_device_metrics`` (gauges under the
``device`` scope) and as a flat dict through ``snapshot()`` (what
bench.py embeds in its stage reports).

Analog of the reference's compile/IO visibility split: Flink counts
bytes/records per task (TaskIOMetricGroup) and DrJAX-style JAX pipelines
treat compiled-program reuse as a measured resource — a recompile in the
hot path costs tens of seconds when the chip sits behind a tunnel, so
``compiles`` staying flat across identical-shape fires is the invariant
this module exists to watch.
"""

from __future__ import annotations

import collections
import functools
import threading
import time
from typing import Any, Callable, Optional

from .profiler import DEVICE_LEDGER

__all__ = ["DeviceStats", "DEVICE_STATS", "instrumented_program_cache",
           "bind_device_metrics", "set_compile_tracer", "pytree_nbytes",
           "PROGRAM_AUDIT", "ProgramAuditEntry", "clear_program_audit"]


class DeviceStats:
    """Process-global compile + transfer counters (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._compiles: dict[str, int] = {}
        self._cache_hits: dict[str, int] = {}
        self._compile_ms: dict[str, float] = {}
        self.h2d_bytes = 0
        self.h2d_records = 0
        self.h2d_batches = 0
        self.d2h_bytes = 0
        self.d2h_records = 0
        self.d2h_fires = 0
        # robustness accounting (PR 2): retries/degradations per scope,
        # dead-letter quarantines, and injected-fault trips per site
        self._retries: dict[str, int] = {}
        self._degraded: dict[str, int] = {}
        self._injected: dict[str, int] = {}
        self.dead_letter_records = 0
        self.dead_letter_batches = 0
        # stall accounting (PR 3): watchdog deadline expiries per site,
        # task-progress / backpressure stall detections per scope
        self._watchdog_trips: dict[str, int] = {}
        self._stalls: dict[str, int] = {}
        # verified-recovery accounting (PR 4): restore-candidate artifact
        # verification failures and restore fallbacks per scope
        self._verify_failures: dict[str, int] = {}
        self._restore_fallbacks: dict[str, int] = {}
        # partition-tolerance accounting (PR 5): channel reconnects per
        # scope (data/control), replayed frames deduped at the receiver,
        # stale-epoch peers fenced, and swallowed-no-longer socket
        # errors per direction (accept/receive/credit/send)
        self._net_reconnects: dict[str, int] = {}
        self._frames_deduped: dict[str, int] = {}
        self._zombies_fenced: dict[str, int] = {}
        self._net_errors: dict[str, int] = {}
        # tracing accounting (PR 7): spans evicted from the bounded
        # in-memory trace reporter (traces.max-retained)
        self._spans_dropped = 0
        # incremental-fire / coalesced-ingest accounting (PR 8): panes
        # folded into the running window accumulators (seals count 1,
        # rebuilds count every live pane), upstream micro-batches merged
        # into coalesced dispatches, and pane rows read per window fire
        # (the O(W) vs O(1) distinction made measurable)
        self._panes_sealed = 0
        self._batches_coalesced = 0
        self._fire_merge_rows = 0
        # whole-chain fusion accounting (PR 11): micro-batches ingested
        # through a certified fused chain program — ONE dispatch covering
        # source-decode + window step (graph/fusion.py certificate)
        self._chain_dispatches = 0
        # live-rescale accounting (PR 12): worker-set changes applied
        # without a restart, key groups whose owner changed, page bytes
        # shipped through the checkpoint transfer format, and total time
        # spent inside the barrier-aligned switch
        self._rescales = 0
        self._keygroups_migrated = 0
        self._rescale_bytes_moved = 0
        self._rescale_ms = 0.0
        # tiered-state accounting (PR 15): key groups demoted to the
        # host-warm tier / promoted back, hot-tier touch ratio (accesses
        # landing on device-resident groups over all accesses), and the
        # latest HBM bytes held by the keyed-state planes
        self._tier_evictions = 0
        self._tier_evicted_keys = 0
        self._tier_prefetches = 0
        self._tier_promoted_keys = 0
        self._tier_hot_touches = 0
        self._tier_touches = 0
        self._tier_hbm_bytes = 0
        # coordinator-failover accounting (PR 18): leader elections won
        # per scope, takeovers completed per mode (hot/restore), and a
        # bounded list of takeover durations for the failover histogram
        self._leader_elections: dict[str, int] = {}
        self._failovers: dict[str, int] = {}
        self._takeover_ms: list[float] = []
        # AOT executable cache accounting (PR 19): persistent-cache hits
        # and misses per scope, executables persisted, dispatch-time
        # fallbacks from a loaded executable to the live jit path,
        # in-memory program-cache LRU evictions, live XLA compiles paid
        # while the persistent cache was active (the compile storm a
        # warmed process must not see), and the process cold-start clock:
        # configure-time mark -> first fired window (d2h fire)
        self._aot_hits: dict[str, int] = {}
        self._aot_misses: dict[str, int] = {}
        self._aot_stores: dict[str, int] = {}
        self._aot_fallbacks: dict[str, int] = {}
        self._aot_evictions = 0
        self._compile_storms: dict[str, int] = {}
        self._cold_start_ms: list[float] = []
        self._cold_start_t0: Optional[float] = None
        self._tracer = None  # optional Tracer receiving device spans

    # -- compile accounting ------------------------------------------------
    def note_build(self, scope: str) -> None:
        with self._lock:
            self._compiles[scope] = self._compiles.get(scope, 0) + 1

    def note_cache_hit(self, scope: str) -> None:
        with self._lock:
            self._cache_hits[scope] = self._cache_hits.get(scope, 0) + 1

    def note_compile_done(self, scope: str, ms: float,
                          start_ms: Optional[int] = None) -> None:
        with self._lock:
            self._compile_ms[scope] = self._compile_ms.get(scope, 0.0) + ms
            tracer = self._tracer
        if tracer is not None:
            sb = tracer.span("device", "Compile").set_attribute(
                "scope", scope).set_attribute("ms", round(ms, 3))
            if start_ms is not None:
                sb.set_start_ts(start_ms)
            sb.finish()

    # -- AOT executable-cache accounting -------------------------------------
    def note_aot_hit(self, scope: str) -> None:
        with self._lock:
            self._aot_hits[scope] = self._aot_hits.get(scope, 0) + 1

    def note_aot_miss(self, scope: str) -> None:
        with self._lock:
            self._aot_misses[scope] = self._aot_misses.get(scope, 0) + 1

    def note_aot_store(self, scope: str) -> None:
        with self._lock:
            self._aot_stores[scope] = self._aot_stores.get(scope, 0) + 1

    def note_aot_fallback(self, scope: str) -> None:
        with self._lock:
            self._aot_fallbacks[scope] = self._aot_fallbacks.get(scope, 0) + 1

    def note_aot_eviction(self, n: int = 1) -> None:
        with self._lock:
            self._aot_evictions += int(n)

    def note_compile_storm(self, scope: str) -> None:
        """A live XLA compile paid while the persistent AOT cache was
        active — zero on a properly warmed process is the recovery
        contract."""
        with self._lock:
            self._compile_storms[scope] = \
                self._compile_storms.get(scope, 0) + 1

    def mark_cold_start(self) -> None:
        """Start the cold-start clock (idempotent until the first fired
        window records it): called when an AOT-enabled deploy configures
        this process."""
        with self._lock:
            if self._cold_start_t0 is None and not self._cold_start_ms:
                self._cold_start_t0 = time.perf_counter()

    # -- transfer accounting -----------------------------------------------
    def note_h2d(self, nbytes: int, records: int = 0,
                 ms: Optional[float] = None) -> None:
        with self._lock:
            self.h2d_bytes += int(nbytes)
            self.h2d_records += int(records)
            self.h2d_batches += 1
            tracer = self._tracer
        if tracer is not None:
            self._finish_transfer(tracer.span("device", "H2D"),
                                  nbytes, records, ms)
        DEVICE_LEDGER.record("transfer.h2d", ms or 0.0, nbytes=nbytes)

    def note_d2h(self, nbytes: int, records: int = 0,
                 ms: Optional[float] = None) -> None:
        with self._lock:
            self.d2h_bytes += int(nbytes)
            self.d2h_records += int(records)
            self.d2h_fires += 1
            if self._cold_start_t0 is not None:
                # first materialized result since the AOT-enabled deploy
                # marked this process cold: the time-to-first-fired-window
                # sample the coldstart bench compares warm vs cold
                self._cold_start_ms.append(
                    (time.perf_counter() - self._cold_start_t0) * 1e3)
                del self._cold_start_ms[:-256]
                self._cold_start_t0 = None
            tracer = self._tracer
        if tracer is not None:
            self._finish_transfer(tracer.span("device", "D2H"),
                                  nbytes, records, ms)
        DEVICE_LEDGER.record("transfer.d2h", ms or 0.0, nbytes=nbytes)

    @staticmethod
    def _finish_transfer(sb, nbytes: int, records: int,
                         ms: Optional[float]) -> None:
        from .tracing import now_ms
        end = now_ms()
        sb.set_attribute("bytes", int(nbytes))
        sb.set_attribute("records", int(records))
        sb.set_start_ts(end - int(ms) if ms else end)
        sb.finish(end)

    # -- robustness accounting ---------------------------------------------
    def note_retry(self, scope: str, n: int = 1) -> None:
        with self._lock:
            self._retries[scope] = self._retries.get(scope, 0) + n

    def note_degraded(self, scope: str) -> None:
        with self._lock:
            self._degraded[scope] = self._degraded.get(scope, 0) + 1

    def note_injected(self, site: str) -> None:
        with self._lock:
            self._injected[site] = self._injected.get(site, 0) + 1

    def note_dead_letter(self, records: int, batches: int = 1) -> None:
        with self._lock:
            self.dead_letter_records += int(records)
            self.dead_letter_batches += int(batches)

    def note_watchdog_trip(self, site: str) -> None:
        with self._lock:
            self._watchdog_trips[site] = \
                self._watchdog_trips.get(site, 0) + 1

    def note_stall(self, scope: str) -> None:
        with self._lock:
            self._stalls[scope] = self._stalls.get(scope, 0) + 1

    def note_verify_failure(self, scope: str) -> None:
        with self._lock:
            self._verify_failures[scope] = \
                self._verify_failures.get(scope, 0) + 1
        from .tracing import dump_flight_recorder
        dump_flight_recorder("corrupt-artifact", scope=scope)

    def note_restore_fallback(self, scope: str) -> None:
        with self._lock:
            self._restore_fallbacks[scope] = \
                self._restore_fallbacks.get(scope, 0) + 1

    # -- partition-tolerance accounting --------------------------------------
    def note_net_reconnect(self, scope: str) -> None:
        with self._lock:
            self._net_reconnects[scope] = \
                self._net_reconnects.get(scope, 0) + 1

    def note_frame_deduped(self, scope: str, n: int = 1) -> None:
        with self._lock:
            self._frames_deduped[scope] = \
                self._frames_deduped.get(scope, 0) + n

    def note_zombie_fenced(self, scope: str) -> None:
        with self._lock:
            self._zombies_fenced[scope] = \
                self._zombies_fenced.get(scope, 0) + 1
        from .tracing import dump_flight_recorder
        dump_flight_recorder("zombie-fenced", scope=scope)

    def note_net_error(self, direction: str) -> None:
        with self._lock:
            self._net_errors[direction] = \
                self._net_errors.get(direction, 0) + 1

    # -- coordinator-failover accounting -------------------------------------
    def note_leader_election(self, scope: str) -> None:
        with self._lock:
            self._leader_elections[scope] = \
                self._leader_elections.get(scope, 0) + 1

    def note_coordinator_failover(self, took_ms: float, mode: str) -> None:
        """A standby finished taking over a running job: ``mode`` is
        'hot' (all workers re-registered, no restart) or 'restore'
        (fenced global restore from the latest verified checkpoint)."""
        with self._lock:
            self._failovers[mode] = self._failovers.get(mode, 0) + 1
            self._takeover_ms.append(float(took_ms))
            del self._takeover_ms[:-256]

    # -- incremental-fire / coalescing accounting ----------------------------
    def note_panes_sealed(self, n: int = 1) -> None:
        with self._lock:
            self._panes_sealed += int(n)

    def note_batches_coalesced(self, n: int) -> None:
        with self._lock:
            self._batches_coalesced += int(n)

    def note_fire_merge_rows(self, n: int) -> None:
        with self._lock:
            self._fire_merge_rows += int(n)

    def note_chain_dispatch(self, n: int = 1) -> None:
        with self._lock:
            self._chain_dispatches += int(n)

    @property
    def chain_dispatches(self) -> int:
        with self._lock:
            return self._chain_dispatches

    @property
    def panes_sealed(self) -> int:
        with self._lock:
            return self._panes_sealed

    @property
    def batches_coalesced(self) -> int:
        with self._lock:
            return self._batches_coalesced

    @property
    def fire_merge_rows(self) -> int:
        with self._lock:
            return self._fire_merge_rows

    # -- live-rescale accounting ---------------------------------------------
    def note_rescale(self, keygroups_migrated: int, bytes_moved: int,
                     duration_ms: float) -> None:
        with self._lock:
            self._rescales += 1
            self._keygroups_migrated += int(keygroups_migrated)
            self._rescale_bytes_moved += int(bytes_moved)
            self._rescale_ms += float(duration_ms)

    @property
    def rescales(self) -> int:
        with self._lock:
            return self._rescales

    @property
    def keygroups_migrated(self) -> int:
        with self._lock:
            return self._keygroups_migrated

    @property
    def rescale_bytes_moved(self) -> int:
        with self._lock:
            return self._rescale_bytes_moved

    @property
    def rescale_ms(self) -> float:
        with self._lock:
            return self._rescale_ms

    # -- tiered-state accounting ---------------------------------------------
    def note_tier_eviction(self, groups: int, keys: int) -> None:
        with self._lock:
            self._tier_evictions += int(groups)
            self._tier_evicted_keys += int(keys)

    def note_tier_prefetch(self, groups: int, keys: int) -> None:
        with self._lock:
            self._tier_prefetches += int(groups)
            self._tier_promoted_keys += int(keys)

    def note_tier_touches(self, hot: int, total: int) -> None:
        with self._lock:
            self._tier_hot_touches += int(hot)
            self._tier_touches += int(total)

    def set_tier_hbm_bytes(self, nbytes: int) -> None:
        with self._lock:
            self._tier_hbm_bytes = int(nbytes)

    @property
    def tier_evictions(self) -> int:
        with self._lock:
            return self._tier_evictions

    @property
    def tier_prefetches(self) -> int:
        with self._lock:
            return self._tier_prefetches

    @property
    def tier_hot_hit_ratio(self) -> float:
        with self._lock:
            return self._tier_hot_touches / max(self._tier_touches, 1)

    @property
    def tier_hbm_bytes_in_use(self) -> int:
        with self._lock:
            return self._tier_hbm_bytes

    # -- tracing accounting --------------------------------------------------
    def note_spans_dropped(self, n: int = 1) -> None:
        with self._lock:
            self._spans_dropped += int(n)

    @property
    def spans_dropped(self) -> int:
        with self._lock:
            return self._spans_dropped

    @property
    def net_reconnects(self) -> int:
        with self._lock:
            return sum(self._net_reconnects.values())

    @property
    def frames_deduped(self) -> int:
        with self._lock:
            return sum(self._frames_deduped.values())

    @property
    def zombies_fenced(self) -> int:
        with self._lock:
            return sum(self._zombies_fenced.values())

    @property
    def net_errors(self) -> int:
        with self._lock:
            return sum(self._net_errors.values())

    @property
    def leader_elections(self) -> int:
        with self._lock:
            return sum(self._leader_elections.values())

    @property
    def coordinator_failovers(self) -> int:
        with self._lock:
            return sum(self._failovers.values())

    @property
    def verify_failures(self) -> int:
        with self._lock:
            return sum(self._verify_failures.values())

    @property
    def restore_fallbacks(self) -> int:
        with self._lock:
            return sum(self._restore_fallbacks.values())

    @property
    def watchdog_trips(self) -> int:
        with self._lock:
            return sum(self._watchdog_trips.values())

    @property
    def stall_detections(self) -> int:
        with self._lock:
            return sum(self._stalls.values())

    @property
    def retries(self) -> int:
        with self._lock:
            return sum(self._retries.values())

    @property
    def degraded(self) -> int:
        with self._lock:
            return sum(self._degraded.values())

    @property
    def injected_faults(self) -> int:
        with self._lock:
            return sum(self._injected.values())

    @property
    def aot_hits(self) -> int:
        with self._lock:
            return sum(self._aot_hits.values())

    @property
    def aot_misses(self) -> int:
        with self._lock:
            return sum(self._aot_misses.values())

    @property
    def aot_stores(self) -> int:
        with self._lock:
            return sum(self._aot_stores.values())

    @property
    def aot_fallbacks(self) -> int:
        with self._lock:
            return sum(self._aot_fallbacks.values())

    @property
    def aot_in_memory_evictions(self) -> int:
        with self._lock:
            return self._aot_evictions

    @property
    def compile_storms(self) -> int:
        with self._lock:
            return sum(self._compile_storms.values())

    # -- views -------------------------------------------------------------
    @property
    def compiles(self) -> int:
        with self._lock:
            return sum(self._compiles.values())

    @property
    def compile_cache_hits(self) -> int:
        with self._lock:
            return sum(self._cache_hits.values())

    @property
    def compile_ms(self) -> float:
        with self._lock:
            return sum(self._compile_ms.values())

    def snapshot(self) -> dict[str, Any]:
        """Flat cumulative view — the shape bench.py embeds per stage
        report and tests compare against the prometheus exposition."""
        with self._lock:
            out: dict[str, Any] = {
                "compiles": sum(self._compiles.values()),
                "compile_cache_hits": sum(self._cache_hits.values()),
                "compile_ms": round(sum(self._compile_ms.values()), 3),
                "h2d_bytes": self.h2d_bytes,
                "h2d_records": self.h2d_records,
                "h2d_batches": self.h2d_batches,
                "d2h_bytes": self.d2h_bytes,
                "d2h_records": self.d2h_records,
                "d2h_fires": self.d2h_fires,
                "device_retries_total": sum(self._retries.values()),
                "device_degraded_total": sum(self._degraded.values()),
                "dead_letter_records_total": self.dead_letter_records,
                "dead_letter_batches_total": self.dead_letter_batches,
                "injected_faults_total": sum(self._injected.values()),
                "watchdog_trips_total": sum(self._watchdog_trips.values()),
                "stall_detections_total": sum(self._stalls.values()),
                "checkpoint_verify_failures_total":
                    sum(self._verify_failures.values()),
                "restore_fallbacks_total":
                    sum(self._restore_fallbacks.values()),
                "network_reconnects_total":
                    sum(self._net_reconnects.values()),
                "frames_deduped_total":
                    sum(self._frames_deduped.values()),
                "zombies_fenced_total":
                    sum(self._zombies_fenced.values()),
                "network_errors_total": sum(self._net_errors.values()),
                "leader_elections_total":
                    sum(self._leader_elections.values()),
                "coordinator_failovers_total":
                    sum(self._failovers.values()),
                "spans_dropped_total": self._spans_dropped,
                "panes_sealed_total": self._panes_sealed,
                "batches_coalesced_total": self._batches_coalesced,
                "fire_merge_rows_read": self._fire_merge_rows,
                "chain_fused_dispatches_total": self._chain_dispatches,
                "rescales_total": self._rescales,
                "keygroups_migrated_total": self._keygroups_migrated,
                "rescale_bytes_moved_total": self._rescale_bytes_moved,
                "rescale_ms": round(self._rescale_ms, 3),
                "tier_evictions_total": self._tier_evictions,
                "tier_evicted_keys_total": self._tier_evicted_keys,
                "tier_prefetches_total": self._tier_prefetches,
                "tier_promoted_keys_total": self._tier_promoted_keys,
                "tier_hot_hit_ratio": round(
                    self._tier_hot_touches / max(self._tier_touches, 1), 6),
                "tier_hbm_bytes_in_use": self._tier_hbm_bytes,
            }
            tk = sorted(self._takeover_ms)
            out["takeover_duration_ms_count"] = len(tk)
            out["takeover_duration_ms_p50"] = (
                round(tk[len(tk) // 2], 3) if tk else 0.0)
            out["takeover_duration_ms_max"] = (
                round(tk[-1], 3) if tk else 0.0)
            out["aot_hits_total"] = sum(self._aot_hits.values())
            out["aot_misses_total"] = sum(self._aot_misses.values())
            out["aot_stores_total"] = sum(self._aot_stores.values())
            out["aot_fallbacks_total"] = sum(self._aot_fallbacks.values())
            out["aot_in_memory_evictions_total"] = self._aot_evictions
            out["compile_storms_total"] = \
                sum(self._compile_storms.values())
            cs = sorted(self._cold_start_ms)
            out["cold_start_ms_count"] = len(cs)
            out["cold_start_ms_p50"] = (
                round(cs[len(cs) // 2], 3) if cs else 0.0)
            out["cold_start_ms_max"] = (
                round(cs[-1], 3) if cs else 0.0)
            for scope, n in sorted(self._compiles.items()):
                out[f"compiles.{scope}"] = n
            for scope, n in sorted(self._retries.items()):
                out[f"retries.{scope}"] = n
            for scope, n in sorted(self._degraded.items()):
                out[f"degraded.{scope}"] = n
            for site, n in sorted(self._injected.items()):
                out[f"injected.{site}"] = n
            for site, n in sorted(self._watchdog_trips.items()):
                out[f"watchdog.{site}"] = n
            for scope, n in sorted(self._stalls.items()):
                out[f"stalls.{scope}"] = n
            for scope, n in sorted(self._verify_failures.items()):
                out[f"verify_failures.{scope}"] = n
            for scope, n in sorted(self._restore_fallbacks.items()):
                out[f"restore_fallbacks.{scope}"] = n
            for scope, n in sorted(self._net_reconnects.items()):
                out[f"net_reconnects.{scope}"] = n
            for scope, n in sorted(self._frames_deduped.items()):
                out[f"frames_deduped.{scope}"] = n
            for scope, n in sorted(self._zombies_fenced.items()):
                out[f"zombies_fenced.{scope}"] = n
            for direction, n in sorted(self._net_errors.items()):
                out[f"net_errors.{direction}"] = n
            for scope, n in sorted(self._leader_elections.items()):
                out[f"leader_elections.{scope}"] = n
            for mode, n in sorted(self._failovers.items()):
                out[f"coordinator_failovers.{mode}"] = n
            for scope, n in sorted(self._aot_hits.items()):
                out[f"aot_hits.{scope}"] = n
            for scope, n in sorted(self._aot_fallbacks.items()):
                out[f"aot_fallbacks.{scope}"] = n
            for scope, n in sorted(self._compile_storms.items()):
                out[f"compile_storms.{scope}"] = n
            return out

    def reset(self) -> None:
        """Test/bench isolation only — counters are otherwise cumulative
        for the process lifetime (prometheus counter semantics)."""
        with self._lock:
            self._compiles.clear()
            self._cache_hits.clear()
            self._compile_ms.clear()
            self._retries.clear()
            self._degraded.clear()
            self._injected.clear()
            self._watchdog_trips.clear()
            self._stalls.clear()
            self._verify_failures.clear()
            self._restore_fallbacks.clear()
            self._net_reconnects.clear()
            self._frames_deduped.clear()
            self._zombies_fenced.clear()
            self._net_errors.clear()
            self._leader_elections.clear()
            self._failovers.clear()
            self._takeover_ms.clear()
            self._aot_hits.clear()
            self._aot_misses.clear()
            self._aot_stores.clear()
            self._aot_fallbacks.clear()
            self._aot_evictions = 0
            self._compile_storms.clear()
            self._cold_start_ms.clear()
            self._cold_start_t0 = None
            self._spans_dropped = 0
            self._panes_sealed = 0
            self._batches_coalesced = 0
            self._fire_merge_rows = 0
            self._chain_dispatches = 0
            self._rescales = 0
            self._keygroups_migrated = 0
            self._rescale_bytes_moved = 0
            self._rescale_ms = 0.0
            self._tier_evictions = self._tier_evicted_keys = 0
            self._tier_prefetches = self._tier_promoted_keys = 0
            self._tier_hot_touches = self._tier_touches = 0
            self._tier_hbm_bytes = 0
            self.dead_letter_records = self.dead_letter_batches = 0
            self.h2d_bytes = self.h2d_records = self.h2d_batches = 0
            self.d2h_bytes = self.d2h_records = self.d2h_fires = 0


DEVICE_STATS = DeviceStats()


def set_compile_tracer(tracer) -> None:
    """Route compile-duration spans into a Tracer (scope 'device',
    name 'Compile', attributes scope/ms)."""
    DEVICE_STATS._tracer = tracer


def pytree_nbytes(tree) -> int:
    """Total buffer bytes across a pytree of arrays (host or device)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


class ProgramAuditEntry:
    """One compiled program captured for the tpu-lint Tier-B jaxpr audit
    (flink_tpu/analysis/jaxpr_rules.py): the jitted callable plus the
    abstract (shape/dtype) signature of its first dispatch, so the audit
    can re-trace it without real buffers, and the builder-arg key so
    value-derived cache keys are detectable."""

    __slots__ = ("scope", "fn", "abstract_args", "abstract_kwargs",
                 "build_key", "source")

    def __init__(self, scope, fn, abstract_args, abstract_kwargs,
                 build_key, source):
        self.scope = scope
        self.fn = fn
        self.abstract_args = abstract_args
        self.abstract_kwargs = abstract_kwargs
        self.build_key = build_key
        self.source = source  # (filename, lineno) of the underlying fn


# Every instrumented program's first dispatch appends its audit entry
# here; `python -m flink_tpu.cli lint` / `bench.py --audit` read it after
# exercising a pipeline.  Bounded so a pathological builder loop cannot
# grow it without limit.
PROGRAM_AUDIT: list = []  # lint: guarded-by GIL-atomic append/clear; read offline by the Tier-B audit
_PROGRAM_AUDIT_LIMIT = 512


def clear_program_audit() -> None:
    PROGRAM_AUDIT.clear()


def _program_source(fn):
    inner = getattr(fn, "__wrapped__", fn)
    code = getattr(inner, "__code__", None)
    if code is None:
        return None
    return (code.co_filename, code.co_firstlineno)


def _record_program_audit(scope, fn, args, kwargs, build_key) -> None:
    """Capture the abstract signature of a program's first dispatch.
    Non-fatal by design: the audit is an observer, never a reason for a
    dispatch to fail."""
    if len(PROGRAM_AUDIT) >= _PROGRAM_AUDIT_LIMIT:
        return
    try:
        import jax

        def _abs(x):
            shape = getattr(x, "shape", None)
            dtype = getattr(x, "dtype", None)
            if shape is not None and dtype is not None:
                return jax.ShapeDtypeStruct(tuple(shape), dtype)
            return x

        PROGRAM_AUDIT.append(ProgramAuditEntry(
            scope, fn,
            jax.tree_util.tree_map(_abs, args),
            jax.tree_util.tree_map(_abs, kwargs),
            build_key, _program_source(fn)))
    except Exception:
        pass


class _TimedProgram:
    """Times the FIRST dispatch of a freshly-built program — jax.jit
    traces/lowers/compiles synchronously inside that call, so its wall
    clock IS the compile cost; later calls pay one extra branch.

    When the persistent AOT cache is active, dispatches route through an
    explicitly-compiled executable per call signature: a warm-loaded one
    (no compile at all) or a live ``lower().compile()`` whose result is
    persisted for the next cold process. Any failure on that path falls
    back to the plain jit call — the cache never fails a dispatch."""

    __slots__ = ("_fn", "_scope", "_compiled", "_build_key",
                 "_build_counted", "_aot_execs", "_aot_bad")

    def __init__(self, fn, scope: str, build_key: str = "",
                 build_counted: bool = True):
        self._fn = fn
        self._scope = scope
        self._compiled = False
        self._build_key = build_key
        self._build_counted = build_counted
        self._aot_execs = None  # call_sig -> compiled executable
        self._aot_bad = None    # call_sigs pinned to the plain jit path

    def __call__(self, *args, **kwargs):
        from ..runtime.aot import AOT
        if AOT.dispatch_active():
            return self._call_aot(AOT, args, kwargs)
        return self._call_plain(args, kwargs)

    def _call_plain(self, args, kwargs):
        if self._compiled:
            if not DEVICE_LEDGER.enabled:
                return self._fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = self._fn(*args, **kwargs)
            DEVICE_LEDGER.record(self._scope,
                                 (time.perf_counter() - t0) * 1e3,
                                 shape_sig=self._build_key)
            return out
        from .tracing import now_ms
        start_ms = now_ms()
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        self._note_live_compile((time.perf_counter() - t0) * 1e3,
                                start_ms, args, kwargs)
        return out

    def _note_live_compile(self, ms, start_ms, args, kwargs) -> None:
        self._compiled = True
        if not self._build_counted:
            # the builder skipped compile accounting expecting a warm
            # executable; this dispatch compiled after all, so it counts
            self._build_counted = True
            DEVICE_STATS.note_build(self._scope)
        DEVICE_STATS.note_compile_done(self._scope, ms, start_ms)
        # first dispatch = trace/lower/compile: charged to the ledger as
        # compile time, never as a steady-state dispatch sample
        DEVICE_LEDGER.record(self._scope, ms, shape_sig=self._build_key,
                             kind="compile")
        _record_program_audit(self._scope, self._fn, args, kwargs,
                              self._build_key)

    def _call_aot(self, aot, args, kwargs):
        sig = aot.call_signature(args, kwargs)
        lower = getattr(self._fn, "lower", None)
        if sig is None or lower is None or \
                (self._aot_bad and sig in self._aot_bad):
            # not an AOT-able dispatch (non-array leaves, a plain python
            # builder, or a signature already pinned to the jit path)
            return self._call_plain(args, kwargs)
        execs = self._aot_execs
        if execs is None:
            execs = self._aot_execs = {}
        compiled = execs.get(sig)
        fresh = False
        if compiled is None:
            compiled = aot.lookup(self._scope, self._build_key, sig)
            if compiled is not None:
                # warm hit: the executable was pre-loaded by warmup — no
                # compile happens, no compile is counted
                execs[sig] = compiled
                self._compiled = True
            else:
                # persistent-cache miss while the cache is active: pay
                # the live compile (the compile storm a warmed process
                # must not see) and persist the result for the next one
                from .tracing import now_ms
                start_ms = now_ms()
                t0 = time.perf_counter()
                try:
                    compiled = lower(*args, **kwargs).compile()
                except Exception:  # noqa: BLE001 - degrade to jit
                    self._pin_bad(sig)
                    return self._call_plain(args, kwargs)
                execs[sig] = compiled
                fresh = True
                ms = (time.perf_counter() - t0) * 1e3
                DEVICE_STATS.note_compile_storm(self._scope)
                if not self._compiled:
                    self._note_live_compile(ms, start_ms, args, kwargs)
                else:
                    # an additional specialization of an already-compiled
                    # program: still compile time, never a dispatch sample
                    DEVICE_STATS.note_compile_done(self._scope, ms,
                                                   start_ms)
                    DEVICE_LEDGER.record(self._scope, ms,
                                         shape_sig=self._build_key,
                                         kind="compile")
        try:
            if not DEVICE_LEDGER.enabled:
                out = compiled(*args, **kwargs)
            else:
                t0 = time.perf_counter()
                out = compiled(*args, **kwargs)
                DEVICE_LEDGER.record(self._scope,
                                     (time.perf_counter() - t0) * 1e3,
                                     shape_sig=self._build_key)
        except Exception as e:  # noqa: BLE001 - degrade to jit
            execs.pop(sig, None)
            self._pin_bad(sig)
            aot.note_dispatch_fallback(self._scope, e)
            return self._call_plain(args, kwargs)
        if fresh:
            aot.store(self._scope, self._build_key, sig, compiled)
        return out

    def _pin_bad(self, sig) -> None:
        if self._aot_bad is None:
            self._aot_bad = set()
        self._aot_bad.add(sig)


#: ``functools.lru_cache``-compatible statistics tuple, preserved so the
#: ``wrapper.cache_info()`` API survives the switch to the config-capped
#: LRU below.
_CacheInfo = collections.namedtuple(
    "CacheInfo", ["hits", "misses", "maxsize", "currsize"])


def instrumented_program_cache(scope: str, maxsize: int = 128):
    """Drop-in replacement for ``functools.lru_cache`` on a compiled-
    program BUILDER: a cache miss counts one compile (the returned
    program's first dispatch is timed as its compile span); a hit counts
    one cache hit. The cached object is shared exactly as before, so
    donation/in-place semantics of the jitted programs are untouched.

    The cache is a config-capped LRU (``aot.in-memory-max-programs``;
    0 = unbounded): evictions count into
    ``aot_in_memory_evictions_total``, and an evicted program rebuilt
    while its executable is warm in the persistent AOT cache skips the
    compile counters entirely — eviction + AOT reload is never a
    recompile. A miss while a warm executable exists likewise bypasses
    the compile accounting, the recompile-attribution ledger, and the
    ``device.compile`` fault/watchdog sites: building the lazy jit
    wrapper is not a compile."""

    def deco(builder: Callable):
        lock = threading.Lock()
        cache = collections.OrderedDict()
        stats = {"hits": 0, "misses": 0}

        def _build_program(args, kwargs):
            key = repr((args, tuple(sorted(kwargs.items()))))
            from ..runtime.aot import AOT
            if AOT.has_program(scope, key):
                # warm start: executables for this program were
                # pre-loaded from the persistent cache, so no compile is
                # decided here — the dispatch path serves them directly
                return _TimedProgram(builder(*args, **kwargs), scope,
                                     build_key=key, build_counted=False)
            # the device.compile fault site + watchdog deadline cover
            # EVERY instrumented builder (device_window/device_session/
            # device_group_agg/pallas_topk/tpu_backend) at the one place
            # a compile is decided; transient trips retry, hang trips
            # stall into the watchdog's deadline, persistent failures
            # surface to the caller's DeviceGuard / failover
            from ..runtime.watchdog import WATCHDOG

            def _build():
                from ..runtime.faults import fire_with_retries
                fire_with_retries("device.compile", scope=scope)
                DEVICE_STATS.note_build(scope)
                # recompile attribution only — the ledger never touches
                # DEVICE_STATS.compiles (the bench recompile budget)
                DEVICE_LEDGER.note_build(scope, key, builder, args,
                                         kwargs)
                return _TimedProgram(builder(*args, **kwargs), scope,
                                     build_key=key)

            return WATCHDOG.run("device.compile", _build, scope=scope)

        def _cap() -> int:
            from ..runtime.aot import AOT
            return AOT.in_memory_max_programs

        @functools.wraps(builder)
        def wrapper(*args, **kwargs):
            ck = (args, tuple(sorted(kwargs.items())))
            with lock:
                prog = cache.get(ck)
                if prog is not None:
                    cache.move_to_end(ck)
                    stats["hits"] += 1
            if prog is not None:
                DEVICE_STATS.note_cache_hit(scope)
                return prog
            # build outside the lock: compiles are slow and must not
            # serialize unrelated builders' cache hits
            prog = _build_program(args, kwargs)
            evicted = 0
            with lock:
                prog = cache.setdefault(ck, prog)
                cache.move_to_end(ck)
                stats["misses"] += 1
                cap = _cap()
                while cap and len(cache) > cap:
                    cache.popitem(last=False)
                    evicted += 1
            if evicted:
                DEVICE_STATS.note_aot_eviction(evicted)
            return prog

        def cache_info() -> _CacheInfo:
            with lock:
                return _CacheInfo(stats["hits"], stats["misses"],
                                  _cap() or None, len(cache))

        def cache_clear() -> None:
            with lock:
                cache.clear()
                stats["hits"] = stats["misses"] = 0

        wrapper.cache_clear = cache_clear
        wrapper.cache_info = cache_info
        return wrapper

    return deco


def bind_device_metrics(registry) -> None:
    """Register the global device stats as gauges under the ``device``
    scope of a MetricRegistry, so prometheus_text / the REST endpoint /
    reporters expose the same series bench.py reads via snapshot().
    Idempotent: re-binding overwrites the same scope entries."""
    g = registry.root().group("device")
    s = DEVICE_STATS
    g.gauge("compiles", lambda: s.compiles)
    g.gauge("compile_cache_hits", lambda: s.compile_cache_hits)
    g.gauge("compile_ms", lambda: s.compile_ms)
    g.gauge("h2d_bytes", lambda: s.h2d_bytes)
    g.gauge("h2d_records", lambda: s.h2d_records)
    g.gauge("h2d_batches", lambda: s.h2d_batches)
    g.gauge("d2h_bytes", lambda: s.d2h_bytes)
    g.gauge("d2h_records", lambda: s.d2h_records)
    g.gauge("d2h_fires", lambda: s.d2h_fires)
    # degradation-ladder counters (prometheus: flink_tpu_device_*)
    g.gauge("retries_total", lambda: s.retries)
    g.gauge("degraded_total", lambda: s.degraded)
    g.gauge("dead_letter_records_total", lambda: s.dead_letter_records)
    g.gauge("dead_letter_batches_total", lambda: s.dead_letter_batches)
    g.gauge("injected_faults_total", lambda: s.injected_faults)
    # stall supervision (prometheus: flink_tpu_device_watchdog_trips_total
    # / flink_tpu_device_stall_detections_total)
    g.gauge("watchdog_trips_total", lambda: s.watchdog_trips)
    g.gauge("stall_detections_total", lambda: s.stall_detections)
    # verified recovery (prometheus:
    # flink_tpu_device_checkpoint_verify_failures_total /
    # flink_tpu_device_restore_fallbacks_total)
    g.gauge("checkpoint_verify_failures_total", lambda: s.verify_failures)
    g.gauge("restore_fallbacks_total", lambda: s.restore_fallbacks)
    # partition tolerance (prometheus:
    # flink_tpu_device_network_reconnects_total /
    # flink_tpu_device_frames_deduped_total /
    # flink_tpu_device_zombies_fenced_total /
    # flink_tpu_device_network_errors_total)
    g.gauge("network_reconnects_total", lambda: s.net_reconnects)
    g.gauge("frames_deduped_total", lambda: s.frames_deduped)
    g.gauge("zombies_fenced_total", lambda: s.zombies_fenced)
    g.gauge("network_errors_total", lambda: s.net_errors)
    # coordinator failover (prometheus:
    # flink_tpu_device_leader_elections_total /
    # flink_tpu_device_coordinator_failovers_total)
    g.gauge("leader_elections_total", lambda: s.leader_elections)
    g.gauge("coordinator_failovers_total", lambda: s.coordinator_failovers)
    # AOT executable cache (prometheus: flink_tpu_device_aot_hits_total /
    # flink_tpu_device_aot_misses_total /
    # flink_tpu_device_aot_stores_total /
    # flink_tpu_device_aot_fallbacks_total /
    # flink_tpu_device_aot_in_memory_evictions_total /
    # flink_tpu_device_compile_storms_total)
    g.gauge("aot_hits_total", lambda: s.aot_hits)
    g.gauge("aot_misses_total", lambda: s.aot_misses)
    g.gauge("aot_stores_total", lambda: s.aot_stores)
    g.gauge("aot_fallbacks_total", lambda: s.aot_fallbacks)
    g.gauge("aot_in_memory_evictions_total",
            lambda: s.aot_in_memory_evictions)
    g.gauge("compile_storms_total", lambda: s.compile_storms)
    # tracing (prometheus: flink_tpu_device_spans_dropped_total)
    g.gauge("spans_dropped_total", lambda: s.spans_dropped)
    # incremental fire engine / coalesced ingest (prometheus:
    # flink_tpu_device_panes_sealed_total /
    # flink_tpu_device_batches_coalesced_total /
    # flink_tpu_device_fire_merge_rows_read)
    g.gauge("panes_sealed_total", lambda: s.panes_sealed)
    g.gauge("batches_coalesced_total", lambda: s.batches_coalesced)
    g.gauge("fire_merge_rows_read", lambda: s.fire_merge_rows)
    # whole-chain fusion (prometheus:
    # flink_tpu_device_chain_fused_dispatches_total)
    g.gauge("chain_fused_dispatches_total", lambda: s.chain_dispatches)
    # live rescale (prometheus: flink_tpu_device_rescales_total /
    # flink_tpu_device_keygroups_migrated_total /
    # flink_tpu_device_rescale_bytes_moved_total /
    # flink_tpu_device_rescale_ms)
    g.gauge("rescales_total", lambda: s.rescales)
    g.gauge("keygroups_migrated_total", lambda: s.keygroups_migrated)
    g.gauge("rescale_bytes_moved_total", lambda: s.rescale_bytes_moved)
    g.gauge("rescale_ms", lambda: s.rescale_ms)
    # tiered state (prometheus: flink_tpu_device_tier_evictions_total /
    # flink_tpu_device_tier_prefetches_total /
    # flink_tpu_device_tier_hot_hit_ratio /
    # flink_tpu_device_tier_hbm_bytes_in_use)
    g.gauge("tier_evictions_total", lambda: s.tier_evictions)
    g.gauge("tier_prefetches_total", lambda: s.tier_prefetches)
    g.gauge("tier_hot_hit_ratio", lambda: s.tier_hot_hit_ratio)
    g.gauge("tier_hbm_bytes_in_use", lambda: s.tier_hbm_bytes_in_use)
