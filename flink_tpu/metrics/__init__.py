"""Metrics package: counters/gauges/meters/histograms in scoped groups,
span tracing, and push/pull reporters.

Public API re-exported here so ``from flink_tpu.metrics import Counter,
Tracer, prometheus_text`` works (the reference exposes flink-metrics-core
the same way).
"""

from .core import (
    Counter, Gauge, Histogram, Meter, MetricGroup, MetricRegistry,
    TaskMetrics,
)
from .device import (
    DEVICE_STATS, DeviceStats, bind_device_metrics,
    instrumented_program_cache, pytree_nbytes, set_compile_tracer,
)
from .reporters import (
    LoggingReporter, MetricReporter, PrometheusReporter, prometheus_text,
    register_reporter, reporters_from_config,
)
from .tracing import (
    InMemoryTraceReporter, Span, SpanBuilder, TraceReporter, Tracer,
)

__all__ = [
    # core
    "Counter", "Gauge", "Meter", "Histogram", "MetricGroup",
    "MetricRegistry", "TaskMetrics",
    # tracing
    "Span", "SpanBuilder", "TraceReporter", "InMemoryTraceReporter",
    "Tracer",
    # reporters
    "MetricReporter", "PrometheusReporter", "LoggingReporter",
    "prometheus_text", "register_reporter", "reporters_from_config",
    # device-path accounting
    "DeviceStats", "DEVICE_STATS", "bind_device_metrics",
    "instrumented_program_cache", "set_compile_tracer", "pytree_nbytes",
]
