"""Metrics package: counters/gauges/meters/histograms in scoped groups,
span tracing, and push/pull reporters.

Public API re-exported here so ``from flink_tpu.metrics import Counter,
Tracer, prometheus_text`` works (the reference exposes flink-metrics-core
the same way).
"""

from .core import (
    Counter, Gauge, Histogram, Meter, MetricGroup, MetricRegistry,
    TaskMetrics,
)
from .device import (
    DEVICE_STATS, DeviceStats, bind_device_metrics,
    instrumented_program_cache, pytree_nbytes, set_compile_tracer,
)
from .profiler import (
    DEVICE_LEDGER, LEDGER_SITE_INVENTORY, DeviceLedger, ProgramKey,
    bind_ledger_metrics, clear_dispatch_context, dispatch_context,
    set_dispatch_context,
)
from .reporters import (
    LoggingReporter, MetricReporter, PrometheusReporter, prometheus_text,
    register_reporter, reporters_from_config,
)
from .tracing import (
    FLIGHT_RECORDER, TRACER, FlightRecorder, InMemoryTraceReporter, Span,
    SpanBuilder, TraceContext, TraceReporter, Tracer, chrome_trace_events,
    current_context, dump_flight_recorder, record_flight_event, use_context,
)

__all__ = [
    # core
    "Counter", "Gauge", "Meter", "Histogram", "MetricGroup",
    "MetricRegistry", "TaskMetrics",
    # tracing
    "Span", "SpanBuilder", "TraceReporter", "InMemoryTraceReporter",
    "Tracer", "TraceContext", "TRACER", "FlightRecorder",
    "FLIGHT_RECORDER", "chrome_trace_events", "current_context",
    "use_context", "record_flight_event", "dump_flight_recorder",
    # reporters
    "MetricReporter", "PrometheusReporter", "LoggingReporter",
    "prometheus_text", "register_reporter", "reporters_from_config",
    # device-path accounting
    "DeviceStats", "DEVICE_STATS", "bind_device_metrics",
    "instrumented_program_cache", "set_compile_tracer", "pytree_nbytes",
    # device-time ledger
    "DeviceLedger", "DEVICE_LEDGER", "ProgramKey",
    "LEDGER_SITE_INVENTORY", "bind_ledger_metrics",
    "set_dispatch_context", "clear_dispatch_context", "dispatch_context",
]
